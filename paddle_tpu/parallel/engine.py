"""ParallelTrainer — ONE jitted SPMD train step over the mesh.

Replaces (TPU-native) the reference's executor pipeline:
ParallelExecutor + fleet meta_optimizer Program rewrites
(/root/reference/paddle/fluid/framework/parallel_executor.cc,
python/paddle/distributed/fleet/meta_optimizers/*).  Where the
reference *rewrites a graph* to insert allreduce/recompute/AMP-cast ops,
here the strategy simply parameterizes how ONE pure function is built
and sharded, and XLA's SPMD partitioner materializes the collectives:

  batch P('dp')          → grads arrive per-shard; psum by partitioner
  params per-layer specs → tp matmul sharding (psum on row outputs)
  opt state on 'dp'      → ZeRO-1: reduce-scatter + sharded update
  strategy.recompute     → jax.checkpoint around the forward
  strategy.gradient_merge→ lax.scan over microbatches inside the step
  strategy.amp           → bf16 auto_cast applied during trace

donate_argnums on (params, opt_state) lets XLA update HBM in place —
peak memory ≈ params + state + activations, like the reference's
in-place optimizer kernels.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..core import rng as rng_mod
from ..distributed import env as _env
from ..resilience import NanSentinel, finite_step, guard_update
from .api import collect_param_shardings, make_spec

__all__ = ['ParallelTrainer']


def _zero_spec(spec, shape, mesh, dp_axis='dp'):
    """ZeRO-1: additionally shard a (replicated-on-dp) state/param leaf
    along dim 0 over dp when divisible."""
    parts = list(make_spec(spec, len(shape), mesh))
    if not shape or dp_axis not in mesh.shape or mesh.shape[dp_axis] <= 1:
        return P(*parts)
    if parts and parts[0] is not None:
        return P(*parts)
    if shape[0] % mesh.shape[dp_axis] == 0:
        parts = [dp_axis] + parts[1:]
    return P(*parts)


class ParallelTrainer:
    """Compile model+optimizer+loss into a sharded train step.

    loss_fn(outputs, *labels) -> scalar Tensor; model outputs are
    Tensors.  Used by hapi.Model.prepare(...) and directly by power
    users (GPT/ERNIE training scripts).
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None, strategy=None,
                 donate=True, n_inputs=1, nan_guard=False, nan_patience=3,
                 nan_max_rollbacks=2, lint=None, auto_shard=False,
                 hbm_budget_gb=None, calibration=None, profile=None,
                 watchdog=None, fused_steps=None, quant_collectives=None,
                 cluster_stats=None, supervisor=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.n_inputs = n_inputs  # batch[:n_inputs] feed forward, rest loss
        self.mesh = mesh or _env.get_mesh()
        self.strategy = strategy or getattr(optimizer, '_fleet_strategy',
                                            None)
        self.donate = donate
        # auto_shard: consult analysis.planner for the best
        # (mesh, PartitionSpec) plan over the available devices and
        # apply it before the first compile.  True -> defaults; a dict
        # is passed through to planner.plan_model (max_candidates,
        # include_pp, thresholds, ...).  hbm_budget_gb gates the plan's
        # peak-memory estimate; calibration is a measured
        # costmodel.Calibration (or a path to one).
        self.auto_shard = auto_shard
        self.hbm_budget_gb = hbm_budget_gb
        self.plan_calibration = calibration
        self._auto_planned = False
        self.plan = None        # the winning analysis.planner plan
        # lint: audit the compiled step with paddle_tpu.analysis on
        # first build — the mesh is passed through, so the
        # replicated-giant rule is live here.  None/False off,
        # 'warn'/True warns, 'error' raises on high severity.
        self.lint = lint
        # profile: sampled on-device trace capture over this trainer's
        # step loop (telemetry.profile).  None → the PADDLE_TPU_PROFILE
        # env decides; False off; True/str/dict/ProfileSchedule
        # configure windows.  Profiled collectives are census-matched
        # through compiled_text() and emitted as collective_observed
        # events — the calibration-fit input.
        self.profile = profile
        self._profiler = None
        self._profiler_init = False
        # watchdog: straggler/hang supervision (resilience.watchdog).
        # None → the PADDLE_TPU_WATCHDOG env decides (default OFF);
        # False hard-off; True/dict/Budget arm per-step deadline
        # budgets — derived from the auto-shard plan's cost-model
        # estimate × slack when one exists — plus the heartbeat
        # quorum when a cluster KV transport is configured.  A blown
        # deadline escalates timeout → flight dump → coordinated
        # abort → WATCHDOG_EXIT_CODE so the elastic supervisor
        # restarts the rank instead of the cluster deadlocking.
        self.watchdog = watchdog
        self._watchdog = None
        self._watchdog_init = False
        self._step_ledger_init = False
        self._step_ledger = None
        # cluster_stats: the live training-cluster observability plane
        # (telemetry.cluster).  None → PADDLE_TPU_CLUSTER_STATS
        # decides (default OFF); False hard-off; True/float arm a
        # ClusterPublisher on this rank (stats frames over the
        # existing KV transport at the boundary-rate stream's cadence
        # — zero new device syncs) and, on rank 0, a ClusterAggregator
        # served as /cluster/status.json through the metrics server.
        self.cluster_stats = cluster_stats
        self._cluster_plane = None
        self._cluster_init = False
        # supervisor: the self-healing actuator (resilience.
        # supervisor).  None → PADDLE_TPU_SUPERVISOR decides (default
        # OFF); False hard-off; True/dict/SupervisorConfig arm a
        # PlanSupervisor subscribed to this process's recorder: SLO/
        # drift/straggler triggers re-run the planner with the live
        # calibration, background-AOT-compile the winner, and queue a
        # plan swap this trainer applies at its next step/chunk
        # boundary (_apply_pending_plan).  Every failure in the
        # ladder degrades to the incumbent plan.
        self.supervisor = supervisor
        self._supervisor = None
        self._supervisor_init = False
        self._pending_plan = None     # (plan, devices, incident meta)
        import threading as _threading
        # serializes trace-time _env.set_mesh flips between the live
        # build path and the supervisor's shadow precompile
        self._trace_lock = _threading.RLock()
        # rolling measured step times feeding Budget.note_measured —
        # host-side perf_counter deltas only, no device reads
        from collections import deque as _deque
        self._measured_dts = _deque(maxlen=256)
        self._measured_n = 0
        # fused_steps: whole-loop compilation (core.scan_loop) — K
        # steps per compiled dispatch via step_fused().  None → the
        # PADDLE_TPU_FUSED_STEPS env decides (default OFF); K clamps
        # adaptively against the watchdog step budget when a plan's
        # cost-model estimate exists (fused_chunk_len()).
        from ..core import scan_loop as _scan
        self.fused_steps = _scan.resolve_fused_steps(fused_steps)
        self._fused_cache = {}
        # quant_collectives: EQuARX-style block-scaled int8 wire for
        # the DP grad sync (parallel.quant_collectives).  None → the
        # PADDLE_TPU_QUANT_COLLECTIVES env decides (default OFF);
        # False hard-off; 'int8'/True/dict/QuantCollectiveConfig arm
        # the quantized reduce-scatter→all-gather decomposition.  The
        # stochastic-rounding keys derive in-module from the step
        # counter — the quantized step stays sync-free and consumes
        # nothing from the model's rng stream.
        from . import quant_collectives as _qc
        self.quant_collectives = _qc.resolve_quant_collectives(
            quant_collectives)
        self._quant_active = None   # the config the built step uses
        self._step_no = 0
        self._compiled = None
        self._eval_compiled = None
        # divergence sentinel (resilience.NanSentinel): opt-in — the
        # finiteness flag costs one host sync per step, and the lazy
        # no-readback contract of step() is the default perf posture
        self.nan_guard = bool(nan_guard)
        self.sentinel = NanSentinel(
            patience=nan_patience, max_rollbacks=nan_max_rollbacks) \
            if nan_guard else None

        pp = (dict(self.mesh.shape).get('pp', 1)
              if self.mesh is not None else 1)
        self._pipeline = bool(self.strategy and self.strategy.pipeline
                              and pp > 1)
        if self.strategy is not None:
            from ..distributed.fleet.fleet_base import validate_strategy
            validate_strategy(self.strategy)
            if self.strategy.pipeline and not self._pipeline:
                import warnings
                warnings.warn(
                    'strategy.pipeline=True but the mesh has no pp axis '
                    '(>1); running without pipeline parallelism. Set '
                    'hybrid_configs.pp_degree before fleet.init.',
                    UserWarning, stacklevel=2)
        if self._pipeline:
            if self.quant_collectives is not None:
                import warnings
                warnings.warn(
                    'quant_collectives is not supported under pipeline '
                    'parallelism (the 1F1B schedule owns its own '
                    'collectives); the wire stays full width.',
                    RuntimeWarning, stacklevel=3)
                self.quant_collectives = None
            if self.lint:
                import warnings
                warnings.warn(
                    'ParallelTrainer(lint=...) is not supported under '
                    'pipeline parallelism yet (the 1F1B step compiles '
                    'per stage); the step will run UNLINTED. Lint the '
                    'dp/tp configuration of the same model instead.',
                    UserWarning, stacklevel=3)
                self.lint = None
            if self.auto_shard:
                import warnings
                warnings.warn(
                    'ParallelTrainer(auto_shard=True) is not supported '
                    'under pipeline parallelism (the planner cannot '
                    'reshape a configured 1F1B schedule); keeping the '
                    'hand-specified mesh.', UserWarning, stacklevel=3)
                self.auto_shard = False
            self._init_pipeline(pp)
            return

        params, buffers = model.functional_state()
        self.param_specs = collect_param_shardings(model)
        self.params = params
        self.buffers = buffers
        self.opt_state = optimizer.init(params)
        if self.auto_shard:
            pass    # placement deferred: the planner picks the mesh
                    # and PartitionSpecs at the first step, when the
                    # batch shapes are known (_auto_plan)
        elif self.mesh is not None:
            self._place_state()
        elif self.donate:
            # device_put would alias the live Parameters' arrays; the
            # donated step would delete them out from under the Layer
            self.params = {n: jnp.array(v, copy=True)
                           for n, v in self.params.items()}
            self.buffers = {n: jnp.array(v, copy=True)
                            for n, v in self.buffers.items()}

    # -- pipeline path (strategy.pipeline + pp>1) ----------------------------
    def _init_pipeline(self, pp):
        """1F1B engine: the model is repacked into shared/stage pytrees
        (GPT exposes as_pipeline_module; a fleet PipelineLayer gets the
        generic heterogeneous adapter).  Reference analogue:
        fleet/meta_parallel/pipeline_parallel.py:43."""
        from .pipeline import PipelineLayerModule
        from ..distributed.fleet.meta_parallel import PipelineLayer
        model = self.model
        if hasattr(model, 'as_pipeline_module'):
            self._pipe = model.as_pipeline_module(pp, self.mesh)
        elif isinstance(model, PipelineLayer):
            assert model.num_stages == pp, (
                f'PipelineLayer has {model.num_stages} stages but '
                f'pp_degree is {pp}')
            self._pipe = PipelineLayerModule(model, self.mesh,
                                             loss_fn=self.loss_fn)
        else:
            raise NotImplementedError(
                'strategy.pipeline needs a model with '
                'as_pipeline_module() or a fleet PipelineLayer')
        self.params = self._pipe.params
        self.opt_state = self.optimizer.init(self.params)
        self.buffers = {}
        self._pipe_shardings = self._pipe_sharding_tree()
        self._pipe_state_shardings = self._state_sharding_tree(
            self.opt_state)
        self.params = jax.tree_util.tree_map(
            jax.device_put, self.params, self._pipe_shardings)
        self.opt_state = jax.tree_util.tree_map(
            jax.device_put, self.opt_state, self._pipe_state_shardings)

    def _pipe_sharding_tree(self):
        repl = NamedSharding(self.mesh, P())
        shared_sh = jax.tree_util.tree_map(
            lambda _: repl, self._pipe.params['shared'])
        stage_sh = jax.tree_util.tree_map(
            lambda _, spec: NamedSharding(self.mesh, spec),
            self._pipe.params['stages'], self._pipe.stage_specs)
        return {'shared': shared_sh, 'stages': stage_sh}

    def _state_sharding_tree(self, state):
        """Optimizer slots follow their parameter's sharding when they
        share its shape (Adam moments etc.), else replicate.  With
        strategy.sharding (ZeRO composed with pipeline — reference
        sharding_optimizer stacking under pipeline), slots of
        pp-REPLICATED leaves (the shared embedding/LN — the vocab table
        dominates state bytes) additionally shard dim 0 over dp."""
        repl = NamedSharding(self.mesh, P())
        zero = bool(self.strategy and self.strategy.sharding)
        dp = dict(self.mesh.shape).get('dp', 1)

        def slot_sharding(p, sh):
            if not zero or dp <= 1:
                return sh
            spec = list(sh.spec) + [None] * (p.ndim - len(sh.spec))
            if p.ndim and spec[0] is None and p.shape[0] % dp == 0:
                return NamedSharding(self.mesh, P('dp', *spec[1:]))
            return sh

        flat_p, treedef = jax.tree_util.tree_flatten(self.params)
        flat_sh = treedef.flatten_up_to(self._pipe_shardings)
        flat_s = treedef.flatten_up_to(state)
        out = []
        for p, sh, st in zip(flat_p, flat_sh, flat_s):
            out.append({k: (slot_sharding(p, sh) if hasattr(v, 'shape')
                            and v.shape == p.shape else repl)
                        for k, v in st.items()})
        return jax.tree_util.tree_unflatten(treedef, out)

    def _build_pipe_step(self):
        from .pipeline_1f1b import pipeline_value_and_grad
        pipe = self._pipe
        opt = self.optimizer
        mesh = self.mesh
        cfgs = (self.strategy.pipeline_configs
                if self.strategy is not None else {})
        M = max(1, int(cfgs.get('accumulate_steps') or 1))

        # ZeRO-2 under pipeline: reduce-scatter the pp-replicated shared
        # grads over dp (constraint -> XLA emits reduce-scatter), update
        # on dp shards, params' out_sharding re-gathers
        zero2 = bool(self.strategy and self.strategy.sharding
                     and int(self.strategy.sharding_configs.get(
                         'stage', 1)) >= 2)
        dp_n = dict(mesh.shape).get('dp', 1)

        def shard_shared_grads(d_sh):
            if not zero2 or dp_n <= 1:
                return d_sh
            return {
                k: (jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, P(
                        'dp', *([None] * (g.ndim - 1)))))
                    if g.ndim and g.shape[0] % dp_n == 0 else g)
                for k, g in d_sh.items()}

        nan_guard = self.nan_guard

        def train_step(params, opt_state, step_no, ids, labels):
            B = ids.shape[0]
            assert B % M == 0, (B, M)
            ids_mb = ids.reshape((M, B // M) + ids.shape[1:])
            lb_mb = labels.reshape((M, B // M) + labels.shape[1:])
            out = pipeline_value_and_grad(
                params['shared'], params['stages'], ids_mb, lb_mb,
                mesh=mesh, first_fn=pipe.first_fn,
                stage_fn=pipe.stage_fn, last_fn=pipe.last_fn,
                stage_specs=pipe.stage_specs, with_finite=nan_guard)
            if nan_guard:
                loss, (d_sh, d_st), ok = out
            else:
                loss, (d_sh, d_st) = out
            grads = {'shared': shard_shared_grads(d_sh), 'stages': d_st}
            new_params, new_state = opt.apply_gradients(
                params, grads, opt_state, step_no)
            if nan_guard:
                # device-side skip, same contract as the dp path: a
                # non-finite microbatch (or non-finite reduced grads)
                # keeps the old params/opt inside the same XLA module;
                # only the boolean crosses to the host for the
                # sentinel's strike/rollback policy
                new_params = guard_update(ok, new_params, params)
                new_state = guard_update(ok, new_state, opt_state)
                return new_params, new_state, loss, ok
            return new_params, new_state, loss

        p_sh = self._pipe_shardings
        repl = NamedSharding(mesh, P())
        s_sh = self._pipe_state_shardings
        batch_sh = NamedSharding(mesh, P('dp'))
        out_sh = (p_sh, s_sh, repl) + ((repl,) if nan_guard else ())
        kwargs = {
            'in_shardings': (p_sh, s_sh, repl, batch_sh, batch_sh),
            'out_shardings': out_sh,
        }
        if self.donate:
            kwargs['donate_argnums'] = (0, 1)
        return jax.jit(train_step, **kwargs)

    def _pipe_step(self, *batch):
        import time as _time
        from .. import telemetry as _tel
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        assert len(vals) == 2, 'pipeline step expects (inputs, labels)'
        first_call = self._compiled is None
        if first_call:
            self._compiled = self._build_pipe_step()
        wd = self._ensure_watchdog()
        if wd is not None:
            wd.step_started(self._step_no + 1, first=first_call)
        _t0 = _time.perf_counter()
        try:
            if self.nan_guard:
                self.params, self.opt_state, loss, ok = self._compiled(
                    self.params, self.opt_state,
                    jnp.asarray(self._step_no + 1), *vals)
                self._note_step(first_call, _time.perf_counter() - _t0,
                                loss, _tel)
                ok = bool(ok)   # the one host sync nan_guard costs
            else:
                self.params, self.opt_state, loss = self._compiled(
                    self.params, self.opt_state,
                    jnp.asarray(self._step_no + 1), *vals)
        finally:
            if wd is not None:
                wd.step_finished(self._step_no + 1)
        if self.nan_guard:
            if ok:
                self._step_no += 1
            if self.sentinel.observe(finite=ok) == 'rollback':
                self._nan_rollback()
            return loss
        self._step_no += 1
        self._note_step(first_call, _time.perf_counter() - _t0, loss,
                        _tel)
        return loss

    # -- sharding placement --------------------------------------------------
    def _sharding_for(self, name, v, zero=False):
        spec = self.param_specs.get(name)
        if zero:
            return NamedSharding(self.mesh, _zero_spec(spec, v.shape,
                                                       self.mesh))
        return NamedSharding(self.mesh, make_spec(spec, v.ndim, self.mesh))

    def _place_state(self):
        zero = bool(self.strategy and self.strategy.sharding)
        self.params = {n: jax.device_put(v, self._sharding_for(n, v))
                       for n, v in self.params.items()}
        self.opt_state = {
            n: {k: (jax.device_put(s, self._sharding_for(n, s, zero=zero))
                    if hasattr(s, 'shape') and s.shape == self.params[n].shape
                    else s)
                for k, s in st.items()}
            for n, st in self.opt_state.items()}
        self.buffers = {n: jax.device_put(v, NamedSharding(self.mesh, P()))
                        for n, v in self.buffers.items()}

    # -- step builders -------------------------------------------------------
    def _forward_loss(self, params, buffers, key, batch):
        import contextlib
        from ..jit import functional_call
        from .. import amp as amp_mod
        xs, ys = batch[:self.n_inputs], batch[self.n_inputs:]
        amp_on = bool(self.strategy and self.strategy.amp)

        def autocast():
            if not amp_on:
                return contextlib.nullcontext()
            return amp_mod.auto_cast(
                level='O2' if self.strategy.amp_configs.get(
                    'use_pure_fp16') else 'O1')

        def run(params, xs):
            with autocast():
                out, new_buffers = functional_call(
                    self.model, params, buffers, xs, key=key,
                    training=True)
            return out, new_buffers

        if self.strategy and self.strategy.recompute:
            run = jax.checkpoint(run)
        out, new_buffers = run(params, xs)
        out_t = jax.tree_util.tree_map(
            lambda v: Tensor._from_value(v), out)
        ys_t = [Tensor._from_value(y) for y in ys]
        from ..core.autograd import no_grad
        # the loss runs under the SAME amp policy as the forward (the
        # reference decorates the whole step): the black list promotes
        # loss inputs to f32, so a bf16 forward cannot round the loss —
        # without this the CE out_dtype contract hands back a
        # bf16-quantized scalar (caught by the round-4 A/B trajectories
        # landing exactly on the bf16 grid)
        with no_grad(), autocast():
            loss = self.loss_fn(out_t, *ys_t)
        loss_v = loss.value if isinstance(loss, Tensor) else loss
        return loss_v.astype(jnp.float32).mean(), new_buffers

    def _build_step(self):
        opt = self.optimizer
        merge_k = (self.strategy.gradient_merge_configs.get('k_steps', 1)
                   if self.strategy and self.strategy.gradient_merge else 1)
        # ZeRO-2: reduce-scatter gradients over dp instead of all-reduce.
        # Reference: fleet/meta_optimizers/sharding_optimizer.py:43 —
        # there a Program rewrite inserts c_reduce_scatter; here a
        # sharding constraint on the grads makes XLA's SPMD partitioner
        # emit the reduce-scatter, the update runs on dp-shards, and the
        # out_sharding on params re-gathers (all-gather) afterwards.
        zero_stage = (self.strategy.sharding_configs.get('stage', 1)
                      if self.strategy and self.strategy.sharding else 0)
        zero2 = zero_stage >= 2 and self.mesh is not None
        self._grad_shardings = None
        if zero2:
            self._grad_shardings = {
                n: self._sharding_for(n, v, zero=True)
                for n, v in self.params.items()}

        def shard_grads(grads):
            if not zero2:
                return grads
            return {n: jax.lax.with_sharding_constraint(
                g, self._grad_shardings[n]) for n, g in grads.items()}

        quant_cfg = self._resolve_quant(merge_k)
        self._quant_active = quant_cfg
        quant_grads = self._build_quant_grads(quant_cfg) \
            if quant_cfg is not None else None

        def train_step(params, buffers, opt_state, step_no, key, *batch):
            if quant_grads is not None:
                # quantized wire: per-shard grads inside shard_map,
                # explicit int8 reduce (parallel.quant_collectives) —
                # the partitioner never sees a full-width grad psum
                loss, grads, new_buffers = quant_grads(
                    params, buffers, step_no, key, batch)
            elif merge_k > 1:
                # microbatch accumulation: batch dim 0 must divide by k
                def body(carry, mb):
                    g_acc, buf = carry
                    (loss, new_buf), g = jax.value_and_grad(
                        self._forward_loss, has_aux=True)(
                            params, buf, key, mb)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    return (g_acc, new_buf), loss
                stacked = tuple(
                    v.reshape((merge_k, v.shape[0] // merge_k) + v.shape[1:])
                    for v in batch)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, new_buffers), losses = jax.lax.scan(
                    body, (zeros, buffers), stacked)
                grads = jax.tree_util.tree_map(
                    lambda g: g / merge_k, grads)
                loss = losses.mean()
            else:
                (loss, new_buffers), grads = jax.value_and_grad(
                    self._forward_loss, has_aux=True)(
                        params, buffers, key, batch)
            grads = shard_grads(grads)
            if self.nan_guard:
                # device-side skip (resilience.finite_step/
                # guard_update): a non-finite loss/grad-norm step
                # keeps the old params/opt/buffers inside the same XLA
                # module; only the boolean crosses to the host where
                # the sentinel's strike/rollback policy runs
                ok = finite_step(loss, grads)
                new_params, new_state = opt.apply_gradients(
                    params, grads, opt_state, step_no)
                new_params = guard_update(ok, new_params, params)
                new_state = guard_update(ok, new_state, opt_state)
                new_buffers = guard_update(ok, new_buffers, buffers)
                return new_params, new_buffers, new_state, loss, ok
            new_params, new_state = opt.apply_gradients(
                params, grads, opt_state, step_no)
            return new_params, new_buffers, new_state, loss

        self._raw_step = train_step          # linted by _run_lint
        kwargs = {}
        self._jit_kwargs = kwargs            # HLO audit reuses these
        if self.mesh is not None:
            repl = NamedSharding(self.mesh, P())
            dp = NamedSharding(
                self.mesh,
                P(('dp',) if 'dp' in self.mesh.shape
                  and self.mesh.shape['dp'] > 1 else None))
            zero = bool(self.strategy and self.strategy.sharding)
            p_sh = {n: self._sharding_for(n, v)
                    for n, v in self.params.items()}
            s_sh = {n: {k: (self._sharding_for(n, s, zero=zero)
                            if hasattr(s, 'shape')
                            and s.shape == self.params[n].shape else repl)
                        for k, s in st.items()}
                    for n, st in self.opt_state.items()}
            b_sh = {n: repl for n in self.buffers}
            kwargs['in_shardings'] = (
                p_sh, b_sh, s_sh, repl, repl) + tuple(
                    dp for _ in range(self._n_batch))
            kwargs['out_shardings'] = (p_sh, b_sh, s_sh, repl) + (
                (repl,) if self.nan_guard else ())
        if self.donate:
            kwargs['donate_argnums'] = (0, 2)
        return jax.jit(train_step, **kwargs)

    # -- quantized wire (parallel.quant_collectives) -------------------------
    def _resolve_quant(self, merge_k=1):
        """The quantized-wire config THIS step build can honor, or
        None.  A requested config that cannot apply degrades to full
        width with a warning naming the reason — quantization must
        never be able to kill a train loop that would have run."""
        cfg = self.quant_collectives
        if cfg is None:
            return None
        import warnings

        def off(reason):
            warnings.warn(
                f'quant_collectives requested but {reason}; the DP '
                'grad sync runs full width', RuntimeWarning,
                stacklevel=4)
            return None

        if self.mesh is None:
            return off('no mesh is configured')
        shape = dict(self.mesh.shape)
        if shape.get('dp', 1) <= 1:
            return off('the mesh has no dp axis > 1')
        others = {a: s for a, s in shape.items()
                  if a != 'dp' and s > 1}
        if others:
            return off(f'non-dp mesh axes {others} are live (the '
                       'quantized decomposition covers the pure-DP '
                       'grad sync; TP activations keep their own '
                       'collectives)')
        live = set()
        for spec in self.param_specs.values():
            for part in (spec or ()):
                for ax in (part if isinstance(part, (tuple, list))
                           else (part,)):
                    if ax and ax != '...' and shape.get(ax, 1) > 1:
                        live.add(ax)
        if live:
            return off(f'param specs shard over {sorted(live)} — the '
                       'quantized step needs dp-replicated params')
        if merge_k > 1:
            return off('strategy.gradient_merge accumulates '
                       'microbatch grads inside the step')
        zero_stage = (self.strategy.sharding_configs.get('stage', 1)
                      if self.strategy and self.strategy.sharding
                      else 0)
        if zero_stage >= 2:
            return off('strategy.sharding stage>=2 (ZeRO-2) owns the '
                       'grad reduce-scatter — quantized grads would '
                       'arrive replicated and defeat it')
        return cfg

    def _build_quant_grads(self, cfg):
        """The quantized DP grad sync: forward+backward per dp shard
        inside ONE shard_map region, then the explicit block-scaled
        int8 all-reduce decomposition over the fused flat grad
        message.  Returns ``fn(params, buffers, step_no, key, batch)
        -> (loss, grads, new_buffers)`` with grads already mean-
        reduced (replicated), drop-in for the implicit-psum path."""
        from ..core.jaxcompat import shard_map
        from . import quant_collectives as _qc
        mesh = self.mesh
        dp_n = dict(mesh.shape)['dp']

        def body(params, buffers, step_no, key, *batch):
            # per-replica dropout stream, like the global batch would
            # draw distinct masks per example
            key = jax.random.fold_in(key, jax.lax.axis_index('dp'))
            # model-internal maybe_shard constraints read the env
            # mesh at trace time; inside shard_map everything is
            # already local, so they must be identity here
            prev = _env.get_mesh()
            _env.set_mesh(None)
            try:
                (loss, new_buf), g = jax.value_and_grad(
                    self._forward_loss, has_aux=True)(
                        params, buffers, key, batch)
            finally:
                _env.set_mesh(prev)
            qkey = _qc.step_key(cfg, step_no) if cfg.stochastic \
                else None
            g = _qc.quantized_allreduce_tree(
                g, 'dp', n=dp_n, cfg=cfg, key=qkey, op='mean')
            loss = jax.lax.pmean(loss, 'dp')
            new_buf = jax.tree_util.tree_map(
                lambda b: jax.lax.pmean(b, 'dp'), new_buf)
            return loss, g, new_buf

        def quant_grads(params, buffers, step_no, key, batch):
            repl_p = jax.tree_util.tree_map(lambda _: P(), params)
            repl_b = jax.tree_util.tree_map(lambda _: P(), buffers)
            sm = shard_map(
                body, mesh=mesh,
                in_specs=(repl_p, repl_b, P(), P())
                + (P('dp'),) * len(batch),
                out_specs=(P(), repl_p, repl_b),
                check_vma=False)
            return sm(params, buffers, step_no, key, *batch)

        return quant_grads

    # -- auto-sharding (analysis.planner) ------------------------------------
    def _auto_plan(self, vals):
        """Consult the planner with the real batch shapes, apply the
        winning (mesh, PartitionSpec) plan, and emit a
        ``plan_selected`` telemetry event run_report joins against
        the observed collective census.  Planner failure degrades to
        the hand-specified posture with a warning — auto_shard must
        never be able to kill a train loop that would have run."""
        import warnings
        from .. import telemetry as _tel
        from ..analysis import planner as _planner
        self._auto_planned = True
        devices = (list(self.mesh.devices.flat)
                   if self.mesh is not None else list(jax.devices()))
        kwargs = dict(self.auto_shard) \
            if isinstance(self.auto_shard, dict) else {}
        if kwargs.pop('include_pp', False):
            # a pp>1 winner would be applied as a plain mesh with no
            # 1F1B schedule behind it: pp-way redundant compute sold
            # at a pipeline price.  Configure strategy.pipeline by
            # hand to use pp.
            warnings.warn(
                'auto_shard cannot apply pipeline (pp>1) plans; '
                'include_pp is ignored', RuntimeWarning, stacklevel=3)
        kwargs['include_pp'] = False
        batch = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for v in vals[:self.n_inputs])
        try:
            result = _planner.plan_model(
                self.model, batch, chips=len(devices), devices=devices,
                hbm_budget_gb=self.hbm_budget_gb,
                calibration=self._resolved_calibration(),
                name=type(self.model).__name__, **kwargs)
            winner = result.winner
        except Exception as e:
            warnings.warn(
                f'auto_shard planning failed ({e!r}); keeping the '
                'hand-specified mesh/shardings', RuntimeWarning,
                stacklevel=3)
            self._place_unplanned()
            return
        if winner is None:
            warnings.warn(
                'auto_shard: no candidate plan fit the '
                f'{result.hbm_bytes / (1 << 30):.1f} GiB HBM budget '
                '(best peak '
                + (f'{result.candidates[0].peak_bytes / (1 << 30):.2f}'
                   ' GiB' if result.candidates else 'unknown')
                + '); keeping the hand-specified mesh/shardings',
                RuntimeWarning, stacklevel=3)
            self._place_unplanned()
            return
        self.plan = winner
        if winner.batch_scale < 1.0:
            warnings.warn(
                'auto_shard: only a reduced-batch fallback plan fit '
                'the HBM budget; the trainer keeps YOUR batch size — '
                'lower the global batch by '
                f'{1 / winner.batch_scale:.0f}x to match the plan',
                RuntimeWarning, stacklevel=3)
        self.mesh = _planner._build_mesh(devices, winner.mesh_axes)
        self.param_specs = dict(winner.param_specs)
        # model-internal maybe_shard constraints read the env mesh at
        # trace time: the planned mesh must be the live one
        _env.set_mesh(self.mesh)
        if winner.remat:
            if self.strategy is not None:
                self.strategy.recompute = True
            else:
                warnings.warn(
                    'auto_shard picked a remat fallback plan but no '
                    'strategy is configured to carry '
                    'strategy.recompute; the step runs without remat '
                    'and may exceed the HBM budget', RuntimeWarning,
                    stacklevel=3)
        self._place_state()
        _tel.event('plan_selected', **result.to_event())
        _tel.add('plan.candidates', len(result.candidates))

    def _place_unplanned(self):
        """Constructor placement semantics, deferred: the auto_shard
        path skipped them awaiting the plan — on planner failure the
        hand-specified posture must still hold (donate may not alias
        the live Layer's arrays)."""
        if self.mesh is not None:
            self._place_state()
        elif self.donate:
            self.params = {n: jnp.array(v, copy=True)
                           for n, v in self.params.items()}
            self.buffers = {n: jnp.array(v, copy=True)
                            for n, v in self.buffers.items()}

    # -- public API ----------------------------------------------------------
    def _ensure_compiled(self, batch):
        """Coerce the batch to raw arrays and latch the jitted step."""
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        if self._compiled is None:
            if self.auto_shard and not self._auto_planned:
                self._auto_plan(vals)
            self._n_batch = len(vals)
            # abstract shapes only — pinning the real batch arrays
            # would hold a full global batch in HBM for the trainer's
            # lifetime just in case the HLO audit runs
            self._example_vals = tuple(
                jax.ShapeDtypeStruct(v.shape, v.dtype) for v in vals)
            self._compiled = self._build_step()
            self._maybe_persistent_cache()
            if self.lint:
                self._run_lint(vals)
            # memory observatory: armed-only here (an extra
            # lower+compile; compiled_text() extracts for FREE when
            # anything else wants the HLO), plus the live sampler
            # (no-op unless PADDLE_TPU_MEMSTATS)
            from ..telemetry import memory as _mem
            _mem.ensure_sampler()
            if _mem.armed():
                _mem.maybe_note_compiled(
                    'ParallelTrainer.step', self._compiled,
                    self._step_example_args(), source='trainer')
        return vals

    # -- persistent compile cache (core.compile_cache) -----------------------
    def _step_example_args(self):
        """Abstract example args of the jitted step, in its signature
        order — shared by the cache fingerprint/export and
        compiled_text()."""
        return (self.params, self.buffers, self.opt_state,
                jnp.zeros((), jnp.int32), jax.random.PRNGKey(0)) \
            + tuple(self._example_vals)

    def _maybe_persistent_cache(self):
        """Swap the freshly-built jitted step for a deserialized
        executable when the persistent cache holds this exact program
        (same jaxpr, shardings, donation, mesh, jax, code); on a miss,
        export the cold step so the NEXT process (elastic restart,
        reshape restore, second worker) deserializes instead of
        recompiling.  A hit forgoes donation (jax.export artifacts do
        not donate) — correctness is identical, peak HBM grows by one
        params+opt generation; set PADDLE_TPU_COMPILE_CACHE=0 to keep
        strict donation.  Never raises."""
        from ..core import compile_cache as _cc
        self._cc_fp = None
        if not _cc.enabled():
            return
        try:
            args = self._step_example_args()
            self._cc_fp = _cc.jaxpr_fingerprint(
                'trainer-step', self._raw_step, args,
                extra=(repr(self._jit_kwargs),
                       tuple(sorted(dict(self.mesh.shape).items()))
                       if self.mesh is not None else None))
            self._compiled = _cc.through_cache(
                self._compiled, args, fp=self._cc_fp,
                name='ParallelTrainer.step')
        except Exception:       # cache plumbing must never kill a run
            self._cc_fp = None

    def compiled_text(self):
        """Compiled (post-partitioner) HLO text of the jitted step —
        lower+compile only, never executed.  Memoized in-process AND in
        the persistent cache's text tier, so the collective census,
        profiler.op_summary and fluid.contrib.memory_usage all share
        ONE lowering per step program, across processes."""
        text = getattr(self, '_hlo_text', None)
        if text is not None:
            return text
        if self._compiled is None:
            raise RuntimeError(
                'compiled_text() needs a compiled step: run one '
                'step() (or _ensure_compiled) first')
        from ..core import compile_cache as _cc
        fp = None
        if getattr(self, '_cc_fp', None) and _cc.enabled():
            fp = _cc.fingerprint('hlo-text', key=self._cc_fp)
            text = _cc.get_text(fp, name='ParallelTrainer.step')
            if text is not None:
                self._hlo_text = text
                return text
        compiled = self._compiled.lower(
            *self._step_example_args()).compile()
        text = compiled.as_text()
        # memory observatory rides the lowering we already paid for:
        # XLA memory_analysis + liveness prediction, free here
        from ..telemetry import memory as _mem
        _mem.note_compiled('ParallelTrainer.step', compiled,
                           hlo_text=text, source='trainer-hlo')
        try:
            # module-total cost analysis only exists on the live
            # compiled object — stash it for op_summary (a
            # cache-served text has none; the table then omits totals)
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            self._hlo_totals = {k: float(ca[k])
                                for k in ('flops', 'bytes accessed')
                                if ca.get(k)}
        except Exception:
            self._hlo_totals = {}
        if fp is not None:
            _cc.put_text(fp, text, name='ParallelTrainer.step')
        self._hlo_text = text
        return text

    def _run_lint(self, vals):
        """ParallelTrainer(lint=...): audit the exact step function
        _build_step handed to jax.jit, with the live mesh (so
        replicated-giant fires) and the real donation set — via
        safe_emit, so only LintError (the 'error'-mode verdict)
        escapes and analyzer crashes degrade to a warning.

        With a Mesh active the audit ESCALATES to the lowered-HLO
        pass (analysis.hlo): the step is lowered with the exact
        in/out shardings + donation _build_step gave jax.jit, and the
        post-partitioner rules (replicated-giant-hlo, collective-cost,
        resharding, peak-memory) extend the jaxpr report."""
        from .. import analysis

        def build():
            args = (self.params, self.buffers, self.opt_state,
                    jnp.zeros((), jnp.int32), jax.random.PRNGKey(0))
            report = analysis.lint(
                self._raw_step, *args, *vals, mesh=self.mesh,
                donate_argnums=(0, 2) if self.donate else (),
                source=False, name='ParallelTrainer.step')
            if self.mesh is not None:
                report.extend(analysis.lint_hlo(
                    self._raw_step, *args, *self._example_vals,
                    mesh=self.mesh, jit_kwargs=self._jit_kwargs,
                    global_shapes=getattr(report, 'global_big_shapes',
                                          None),
                    name='ParallelTrainer.step'))
            return report

        analysis.safe_emit(build, self.lint)

    def step(self, *batch):
        """batch: numpy/jax arrays (x, y, ...). Returns python float loss."""
        if self._pipeline:
            return self._pipe_step(*batch)
        import time as _time
        from .. import telemetry as _tel
        if self._pending_plan is not None:
            self._apply_pending_plan()
        first_call = self._compiled is None
        vals = self._ensure_compiled(batch)
        key = rng_mod.next_key()
        wd = self._ensure_watchdog()
        if wd is not None:
            # the deadline covers dispatch + (nan path) the device
            # sync — where a hung collective actually blocks the host
            wd.step_started(self._step_no + 1, first=first_call)
        self._note_ledger_step(self._step_no + 1)
        _t0 = _time.perf_counter()
        try:
            if self.nan_guard:
                (self.params, self.buffers, self.opt_state, loss,
                 ok) = self._compiled(
                    self.params, self.buffers, self.opt_state,
                    jnp.asarray(self._step_no + 1), key, *vals)
                self._note_step(first_call, _time.perf_counter() - _t0,
                                loss, _tel)
                ok = bool(ok)   # the one host sync nan_guard costs
            else:
                (self.params, self.buffers, self.opt_state,
                 loss) = self._compiled(
                    self.params, self.buffers, self.opt_state,
                    jnp.asarray(self._step_no + 1), key, *vals)
        finally:
            if wd is not None:
                wd.step_finished(self._step_no + 1)
        if self.nan_guard:
            if ok:
                self._step_no += 1
            if self.sentinel.observe(finite=ok) == 'rollback':
                self._nan_rollback()
            return loss
        self._step_no += 1
        self._note_step(first_call, _time.perf_counter() - _t0, loss,
                        _tel)
        # LR-scheduler advancement is the caller's job (hapi epoch loop)
        return loss

    # -- fused K-step chunks (core.scan_loop) --------------------------------
    def fused_chunk_len(self, k=None):
        """The chunk length callers should stage for
        :meth:`step_fused`: ``fused_steps`` clamped adaptively against
        the armed watchdog step budget (scan_loop.clamp_chunk) using
        the auto-shard plan's cost-model step estimate when one
        exists — a fused chunk must stay detectable within the
        deadline the operator armed.  Without a budget or an estimate
        K passes through unchanged."""
        from ..core import scan_loop as _scan
        k = self.fused_steps if k is None else int(k)
        wd = self._ensure_watchdog()
        budget = wd.budget if wd is not None else None
        est = None
        if self.plan is not None:
            est_us = ((getattr(self.plan, 'est_us', 0) or 0)
                      + (getattr(self.plan, 'compute_us', 0) or 0))
            if est_us > 0:
                est = est_us * 1e-6
        return _scan.clamp_chunk(k, budget, est)

    def _build_fused_step(self, k):
        """jit the K-step scan over the SAME raw step _build_step
        hands jax.jit, with the stacked-batch shardings (leading K dim
        unsharded, dp on dim 1) and the same donation posture."""
        from ..core import scan_loop as _scan
        self._build_step()      # latches _raw_step (+ shardings math)
        fused = _scan.fused_trainer_step(self._raw_step, k,
                                         nan_guard=self.nan_guard)
        kwargs = {}
        if self.mesh is not None:
            base = self._jit_kwargs
            p_sh, b_sh, s_sh, repl = base['in_shardings'][:4]
            batch_sh = base['in_shardings'][5:]

            def stack_sh(sh):
                return NamedSharding(self.mesh, P(None, *sh.spec))

            kwargs['in_shardings'] = (
                (p_sh, b_sh, s_sh, repl, repl)
                + tuple(stack_sh(s) for s in batch_sh))
            kwargs['out_shardings'] = (p_sh, b_sh, s_sh, repl, repl) \
                + ((repl,) if self.nan_guard else ())
        if self.donate:
            kwargs['donate_argnums'] = (0, 2)
        self._fused_jit_kwargs = kwargs
        return jax.jit(fused, **kwargs)

    def _fused_example_args(self, k, vals):
        return (self.params, self.buffers, self.opt_state,
                jnp.zeros((), jnp.int32),
                jnp.zeros((k, 2), jnp.uint32)) + tuple(
                    jax.ShapeDtypeStruct(v.shape, v.dtype)
                    for v in vals)

    def step_fused(self, *batch):
        """K optimizer steps in ONE compiled dispatch (whole-loop
        compilation, core.scan_loop): every array in `batch` carries a
        leading K dim (stage with ``scan_loop.stack_batches``, sized
        by :meth:`fused_chunk_len`).  Returns the K per-step losses as
        one DEVICE array — zero host syncs per chunk on the default
        path, exactly one (the finite-mask readback) under
        ``nan_guard``.  The per-step rng stream, step counter and
        update math are bit-exact with K calls of :meth:`step`;
        checkpoint/restore granularity becomes K steps (chunks end at
        step boundaries, so ``save_checkpoint`` between chunks commits
        exact step ids)."""
        if self._pipeline:
            raise NotImplementedError(
                'fused_steps under pipeline parallelism: the 1F1B '
                'schedule is already a fused multi-microbatch module')
        import time as _time
        import warnings
        from .. import telemetry as _tel
        from ..core import scan_loop as _scan
        if self._pending_plan is not None:
            # chunk boundary: the supervisor's queued plan lands
            # BEFORE this chunk compiles/dispatches
            self._apply_pending_plan()
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        k = int(vals[0].shape[0])
        ck = (k,) + tuple((tuple(v.shape), str(v.dtype)) for v in vals)
        first_call = ck not in self._fused_cache
        if first_call:
            if self.auto_shard and not self._auto_planned:
                self._auto_plan(tuple(v[0] for v in vals))
            self._n_batch = len(vals)
            fit = self.fused_chunk_len(k)
            if fit < k:
                warnings.warn(
                    f'fused chunk of {k} steps exceeds the watchdog '
                    f'step budget (fits {fit}); stage '
                    'fused_chunk_len() chunks so hang detection stays '
                    'inside the armed deadline', RuntimeWarning,
                    stacklevel=2)
                _tel.event('fused_clamp', requested=k, fits=fit)
            jitted = self._build_fused_step(k)
            from ..core import compile_cache as _cc
            self._fused_fp = None
            if _cc.enabled():
                try:
                    args = self._fused_example_args(k, vals)
                    self._fused_fp = _cc.jaxpr_fingerprint(
                        'trainer-fused-step', self._raw_fused(k), args,
                        extra=('fused', k,
                               repr(self._fused_jit_kwargs),
                               tuple(sorted(dict(self.mesh.shape)
                                            .items()))
                               if self.mesh is not None else None))
                    jitted = _cc.through_cache(
                        jitted, args, fp=self._fused_fp,
                        name='ParallelTrainer.step_fused')
                except Exception:   # cache plumbing never kills a run
                    self._fused_fp = None
            self._fused_cache[ck] = jitted
            if self.lint:
                self._run_lint_fused(vals, k)
        fn = self._fused_cache[ck]
        # K keys from the SAME host stream the unfused loop consumes —
        # fused and unfused runs see identical dropout
        keys = jnp.stack([rng_mod.next_key() for _ in range(k)])
        wd = self._ensure_watchdog()
        if wd is not None:
            # the budget covers the whole K-step chunk (compile rides
            # the first chunk's first step)
            b = wd.budget
            budget_s = None
            if b is not None:
                per = b.effective_step_s()
                head = b.effective_first_step_s() if first_call else per
                budget_s = head + (k - 1) * per
            wd.step_started(self._step_no + k, budget_s=budget_s,
                            first=first_call)
        self._note_ledger_step(self._step_no + 1, k=k)
        _t0 = _time.perf_counter()
        try:
            if self.nan_guard:
                (self.params, self.buffers, self.opt_state, _s,
                 losses, oks) = fn(
                    self.params, self.buffers, self.opt_state,
                    jnp.asarray(self._step_no, jnp.int32), keys, *vals)
            else:
                (self.params, self.buffers, self.opt_state, _s,
                 losses) = fn(
                    self.params, self.buffers, self.opt_state,
                    jnp.asarray(self._step_no, jnp.int32), keys, *vals)
        finally:
            if wd is not None:
                wd.step_finished(self._step_no + k)
        dt = _time.perf_counter() - _t0
        # telemetry rows are labeled by a monotone DISPATCH counter:
        # under nan_guard, _step_no advances only by the finite count,
        # so labeling rows _step_no-k+1.. would reuse ids across
        # chunks containing skips
        row_lo = getattr(self, '_fused_rows', 0) + 1
        self._fused_rows = row_lo + k - 1
        if self.nan_guard:
            # the chunk's ONE sanctioned host sync: the K-step mask
            mask = _scan.chunk_sync(oks)
            self._step_no += int(mask.sum())
            self._note_chunk(first_call, dt, losses, k, row_lo)
            for ok in mask:
                if self.sentinel.observe(finite=bool(ok)) == 'rollback':
                    self._nan_rollback()
                    break
            return losses
        self._step_no += k
        self._note_chunk(first_call, dt, losses, k, row_lo)
        return losses

    def _raw_fused(self, k):
        """The unjitted fused scan (fingerprint input)."""
        from ..core import scan_loop as _scan
        return _scan.fused_trainer_step(self._raw_step, k,
                                        nan_guard=self.nan_guard)

    def _run_lint_fused(self, vals, k):
        """Lint the per-step function in its fused posture: the
        ``chunk-break`` rule flags host callbacks/syncs that would
        force the K-chunk to split back into per-step dispatches."""
        from .. import analysis

        def build():
            args = (self.params, self.buffers, self.opt_state,
                    jnp.zeros((), jnp.int32), jax.random.PRNGKey(0))
            per_step = tuple(jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
                             for v in vals)
            return analysis.lint(
                self._raw_step, *args, *per_step, mesh=self.mesh,
                donate_argnums=(0, 2) if self.donate else (),
                source=False, fused_steps=k,
                name='ParallelTrainer.step_fused')

        analysis.safe_emit(build, self.lint)

    def _note_chunk(self, first_call, dt, losses, k, step_lo):
        """Telemetry for one fused chunk: the compile event on the
        first call, chunk rows (expanded to per-step stats at flush)
        on the steady state, and span-tagged profiler observes so a
        capture window attributes its collectives to exact step ids.
        ``step_lo`` is the monotone dispatch index of the chunk's
        first step (distinct from _step_no, which skips don't
        advance)."""
        from .. import telemetry as _tel
        prof = self._ensure_profiler(_tel)
        if prof is not None:
            n0 = getattr(self, '_profile_calls', -1) + 1
            self._profile_calls = n0 + k - 1
            prof.observe(n0, sync=losses, span=k)
        self._ensure_cluster_plane()
        self._ensure_supervisor()
        if first_call:
            _tel.event('compile', name='ParallelTrainer.step_fused',
                       dur_s=round(dt, 6), fused_steps=k)
            _tel.add('compile.count')
            _tel.add('compile.total_s', dt)
            return
        self._note_measured_step(dt, _tel, k=k)
        acc = getattr(self, '_tel_acc', None)
        if acc is None:
            acc = self._tel_acc = _tel.step_accumulator('parallel')
            if acc is None:
                return
        acc.observe_chunk(step_lo, k, step_time_s=dt, loss=losses)

    def _resolved_calibration(self):
        """The calibration= argument as a costmodel.Calibration (paths
        loaded lazily, once), or None — shared by the planner's cost
        scoring, the census prediction events and the profiler's
        census join, so all three predict with the same constants."""
        if not hasattr(self, '_calibration_obj'):
            cal = self.plan_calibration
            if isinstance(cal, str):
                from ..analysis import costmodel as _cm
                try:
                    cal = _cm.load_calibration(cal)
                except Exception as e:
                    import warnings
                    warnings.warn(
                        f'calibration table {cal!r} could not be '
                        f'loaded ({e!r}); predictions fall back to '
                        'the analytic cost model', RuntimeWarning,
                        stacklevel=3)
                    cal = None
            elif cal is not None and not hasattr(cal, 'per_op'):
                cal = None
            self._calibration_obj = cal
        return self._calibration_obj

    def _ensure_step_ledger(self):
        """Latch the per-rank collective ledger on first use; None
        when off.  The per-step cost is one attribute read + a host
        dict append (shard_map sync sites tagged by step) — no device
        reads, no KV writes: publication rides the host collectives
        and the watchdog heartbeat, off the step path."""
        if self._step_ledger_init:
            return self._step_ledger
        self._step_ledger_init = True
        try:
            from ..distributed.collective import (
                ledger_enabled, get_ledger)
            if ledger_enabled():
                import os as _os
                rank = int(_os.environ.get('PADDLE_TRAINER_ID', 0)
                           or 0)
                self._step_ledger = get_ledger(rank)
        except Exception:       # supervision must never kill a step
            self._step_ledger = None
        return self._step_ledger

    def _note_ledger_step(self, step_no, k=1):
        """Tag the ledger with the incoming step and append the
        trainer's shard_map sync site (the compiled dispatch is where
        in-trace collectives synchronize ranks).  Host metadata only."""
        led = self._ensure_step_ledger()
        if led is None:
            return
        led.note_step(step_no)
        led.record('shard_map_step' if k == 1 else 'shard_map_chunk',
                   f'step{step_no}' if k == 1
                   else f'step{step_no}..{step_no + k - 1}')

    def _ensure_watchdog(self):
        """Latch the straggler/hang watchdog on first use; None when
        off (the default) — the per-step cost is then one attribute
        read.  The step budget derives from the PR-6 cost model when
        the planner picked this trainer's plan (est_us + compute_us,
        × the budget's slack factor); a cluster KV transport (env
        PADDLE_TPU_KV / jax.distributed) additionally arms the
        heartbeat quorum."""
        if self._watchdog_init:
            return self._watchdog
        self._watchdog_init = True
        try:
            from ..resilience.watchdog import (
                resolve_watchdog, Budget, Watchdog)
            budget = resolve_watchdog(self.watchdog)
            if budget is None:
                return None
            if budget.step_s is None and self.plan is not None:
                est = ((getattr(self.plan, 'est_us', 0) or 0)
                       + (getattr(self.plan, 'compute_us', 0) or 0))
                if est > 0:
                    budget.step_s = Budget.from_costmodel(
                        est, slack=budget.slack).step_s
            from ..distributed.collective import get_kv_client
            mgr = getattr(self, '_ckpt_mgr', None)
            self._watchdog = Watchdog(
                budget=budget, name='parallel', kv=get_kv_client(),
                flight_dir=(mgr.directory if mgr is not None
                            else None)).start()
        except Exception:       # supervision must never kill a step
            self._watchdog = None
        return self._watchdog

    def stop_watchdog(self):
        """Stop the supervision thread (end of the step loop; tests).
        Final: later step() calls run unwatched — an explicit stop
        must not be silently undone by the next step re-latching a
        fresh escalation-armed thread.  Assign ``self.watchdog`` and
        reset ``_watchdog_init`` to re-arm deliberately.  No-op when
        the watchdog is off."""
        wd, self._watchdog = self._watchdog, None
        if wd is not None:
            wd.stop()

    def _ensure_cluster_plane(self):
        """Latch the cluster observability publisher (telemetry.
        cluster) on first use; None when off (the default) — the
        per-step cost is then one attribute read.  Rank 0
        additionally aggregates and registers the /cluster view on
        the process metrics server (or one the env port arms)."""
        if self._cluster_init:
            return self._cluster_plane
        self._cluster_init = True
        try:
            from ..telemetry.cluster import (
                resolve_cluster_stats, enable_cluster_plane)
            interval = resolve_cluster_stats(self.cluster_stats)
            if interval is None:
                return None
            self._cluster_plane = enable_cluster_plane(
                interval_s=interval)
        except Exception:   # observability must never kill a step
            self._cluster_plane = None
        return self._cluster_plane

    def stop_cluster_plane(self):
        """Tear down this trainer's cluster-plane handle (publisher
        subscription + /cluster source registration).  Final, like
        stop_watchdog(); no-op when the plane is off."""
        plane, self._cluster_plane = self._cluster_plane, None
        if plane is not None:
            plane.close()

    # -- self-healing supervisor (resilience.supervisor) ---------------------
    def _ensure_supervisor(self):
        """Latch the plan-supervisor actuator on first use; None when
        off (the default) — the per-step cost is then one attribute
        read.  The supervisor subscribes to THIS process's recorder
        and queues plan swaps in ``_pending_plan``; step()/
        step_fused() apply them at the next boundary."""
        if self._supervisor_init:
            return self._supervisor
        self._supervisor_init = True
        try:
            from ..resilience.supervisor import (
                resolve_supervisor, PlanSupervisor, TrainerHost)
            cfg = resolve_supervisor(self.supervisor)
            if cfg is None:
                return None
            self._supervisor = PlanSupervisor(
                TrainerHost(self), cfg).start()
        except Exception:     # the actuator must never kill a step
            self._supervisor = None
        return self._supervisor

    def stop_supervisor(self):
        """Stop the actuator thread.  Final, like stop_watchdog():
        later step() calls run unsupervised — assign
        ``self.supervisor`` and reset ``_supervisor_init`` to re-arm
        deliberately.  An already-queued swap still applies (the
        trainer owns it).  No-op when the supervisor is off."""
        sup, self._supervisor = self._supervisor, None
        if sup is not None:
            sup.stop()

    def precompile_plan(self, plan, devices):
        """AOT-compile `plan`'s REAL train step on a shadow of this
        trainer — abstract state only, the live arrays are never
        touched — and push it through the persistent compile cache
        under the SAME fingerprint the post-swap rebuild computes, so
        the swap's recompile deserializes instead of paying a cold
        compile (cache off: the candidate is still validated to
        trace+compile).  Runs on the supervisor's thread under the
        trace lock; raises on failure — the safety ladder's
        degrade-to-incumbent rung."""
        import copy
        from ..analysis import planner as _planner
        from ..core import compile_cache as _cc
        if self._compiled is None or not hasattr(self, '_example_vals'):
            raise RuntimeError(
                'precompile_plan needs a compiled incumbent step')

        def abstract(tree):
            return {n: (jax.ShapeDtypeStruct(v.shape, v.dtype)
                        if hasattr(v, 'shape') else v)
                    for n, v in tree.items()}

        shadow = copy.copy(self)
        shadow.plan = plan
        shadow.param_specs = dict(plan.param_specs)
        shadow.params = abstract(self.params)
        shadow.buffers = abstract(self.buffers)
        shadow.opt_state = {n: abstract(st)
                            for n, st in self.opt_state.items()}
        with self._trace_lock:
            prev = _env.get_mesh()
            try:
                shadow.mesh = _planner._build_mesh(
                    list(devices), plan.mesh_axes)
                # model-internal maybe_shard constraints read the env
                # mesh at trace time — restored before the lock drops
                _env.set_mesh(shadow.mesh)
                jitted = shadow._build_step()
                args = shadow._step_example_args()
                if _cc.enabled():
                    fp = _cc.jaxpr_fingerprint(
                        'trainer-step', shadow._raw_step, args,
                        extra=(repr(shadow._jit_kwargs),
                               tuple(sorted(dict(shadow.mesh.shape)
                                            .items()))))
                    _cc.through_cache(jitted, args, fp=fp,
                                      name='ParallelTrainer.step')
                else:
                    jitted.lower(*args).compile()
            finally:
                _env.set_mesh(prev)

    def _apply_pending_plan(self):
        """Apply the supervisor's queued plan at this step/chunk
        boundary: the PR-5 elastic-reshape restore posture, in
        process — state re-places onto the new mesh (a reshard, not a
        restart), the compiled artifacts drop (the precompiled
        candidate deserializes from the persistent cache), and the
        measured-step window + watchdog budget reset so the new plan
        re-learns from fresh profiles instead of inheriting the
        degraded plan's p95.  Emits ``plan_swap``; ANY failure
        reverts to the incumbent state and emits a degraded
        ``remediation`` — a swap can never kill a step loop that
        would have run."""
        import time as _time
        from .. import telemetry as _tel
        pending, self._pending_plan = self._pending_plan, None
        if pending is None or self._pipeline:
            return
        plan, devices, meta = pending
        from ..analysis import planner as _planner
        old_mesh = self.mesh
        old = (self.plan, self.mesh,
               dict(self.param_specs), self.params, self.buffers,
               self.opt_state, self._compiled, self._eval_compiled,
               self._fused_cache, getattr(self, '_hlo_text', None))
        t0 = _time.perf_counter()
        try:
            with self._trace_lock:
                mesh = _planner._build_mesh(
                    list(devices), plan.mesh_axes)
                self.plan = plan
                self.mesh = mesh
                self.param_specs = dict(plan.param_specs)
                _env.set_mesh(mesh)
                self._place_state()
                self._compiled = None
                self._eval_compiled = None
                self._fused_cache = {}
                self._hlo_text = None
            # fresh profiles for the new plan (satellite of the swap:
            # budgets must not inherit the degraded plan's p95)
            self._measured_dts.clear()
            self._measured_n = 0
            wd = self._watchdog
            if wd is not None and getattr(wd, 'budget', None) is not None:
                est = ((getattr(plan, 'est_us', 0) or 0)
                       + (getattr(plan, 'compute_us', 0) or 0))
                wd.budget.reset_measured(est_step_us=est or None)
            _tel.event(
                'plan_swap', step=self._step_no,
                from_mesh=(dict(old_mesh.shape)
                           if old_mesh is not None else None),
                to_mesh=dict(plan.mesh_axes),
                assignment=plan.assignment,
                trigger=(meta or {}).get('trigger'),
                policy=(meta or {}).get('policy'),
                dur_s=round(_time.perf_counter() - t0, 6))
        except Exception as e:
            (self.plan, self.mesh, self.param_specs, self.params,
             self.buffers, self.opt_state, self._compiled,
             self._eval_compiled, self._fused_cache,
             self._hlo_text) = old
            _env.set_mesh(self.mesh)
            _tel.event('remediation',
                       trigger=(meta or {}).get('trigger'),
                       policy=(meta or {}).get('policy'),
                       outcome='degraded', stage='swap',
                       error=repr(e))

    def _note_measured_step(self, dt, _tel, k=1):
        """Feed one measured step (or chunk) duration into the rolling
        profile and — every 32 observations — refresh an armed, non-
        explicit watchdog budget from it (Budget.note_measured: the
        measured p95 x slack replaces the analytic estimate; ROADMAP
        item-3 carry-over).  Host floats only; never raises."""
        try:
            self._measured_dts.append(dt / max(1, k))
            self._measured_n += 1
            if self._measured_n % 32:
                return
            wd = self._watchdog
            if wd is None:
                return
            new = wd.budget.note_measured(self._measured_dts)
            if new is not None:
                _tel.set_gauge('watchdog.measured_step_s',
                               round(new, 4))
        except Exception:
            pass

    def _ensure_profiler(self, _tel):
        """Latch the sampled step profiler (telemetry.profile) on
        first use.  None when profiling is off — the per-step cost is
        then a single attribute read.  The census join runs through
        compiled_text() so profiled collectives carry the compiled
        module's wire-byte/phase signature (pipeline steps profile
        without the join: their per-stage modules lower separately)."""
        if not self._profiler_init:
            self._profiler_init = True
            try:
                mesh_shape = (dict(self.mesh.shape)
                              if self.mesh is not None else None)
                n_parts = (int(np.prod(list(mesh_shape.values())))
                           if mesh_shape else 1)
                cal = self._resolved_calibration()
                text_fn = self._census_text \
                    if (self.mesh is not None
                        and not self._pipeline) else None
                self._profiler = _tel.step_profiler(
                    self.profile, name='parallel',
                    hlo_text_fn=text_fn, mesh_shape=mesh_shape,
                    num_partitions=n_parts, calibration=cal)
            except Exception:   # profiling must never kill a step
                self._profiler = None
        return self._profiler

    def _census_text(self):
        """compiled_text for the profiler's census join, or None when
        only the FUSED module exists: the per-step module was never
        compiled, and the scan module's instruction names would not
        join the per-step census anyway — fused windows keep the
        compute-vs-collective breakdown without the per-instruction
        attribution (a clean skip, not an error on the
        profile_capture event)."""
        if self._compiled is None:
            return None
        return self.compiled_text()

    def _note_step(self, first_call, dt, loss, _tel):
        """Telemetry for one step() call: the first call of a fresh
        compile is recorded as the compile cost (jit traces+compiles
        synchronously before dispatching); steady-state calls feed the
        sync-free accumulator — the loss stays a DEVICE scalar in the
        buffer and is read back only at flush_interval boundaries."""
        prof = self._ensure_profiler(_tel)
        if prof is not None:
            # a dedicated 0-based call counter: _step_no increments
            # before this hook on one path and after it on the
            # nan_guard path (and does not advance on skipped steps),
            # so window step labels would drift between them
            n = self._profile_calls = getattr(
                self, '_profile_calls', -1) + 1
            prof.observe(n, sync=loss)
        self._ensure_cluster_plane()
        self._ensure_supervisor()
        if first_call:
            _tel.event('compile', name='ParallelTrainer.step',
                       dur_s=round(dt, 6))
            _tel.add('compile.count')
            _tel.add('compile.total_s', dt)
            self._maybe_collective_census()
            return
        self._note_measured_step(dt, _tel)
        acc = getattr(self, '_tel_acc', None)
        if acc is None:
            acc = self._tel_acc = _tel.step_accumulator('parallel')
            if acc is None:
                return
        acc.observe(step=self._step_no, step_time_s=dt, loss=loss)

    def _maybe_collective_census(self):
        """EQuARX comms audit: when full telemetry is on, parse THIS
        step's optimized HLO (analysis.hlo's parser) and emit both the
        per-collective call/byte census (``collectives``) and the
        cost-model PREDICTION (``collective_cost``: ring wire bytes +
        latency/bandwidth time estimate per op) so run_report can show
        predicted vs observed traffic side by side.  Costs one AOT
        lower+compile of the already-jitted step (deduped by the
        persistent XLA cache); never raises."""
        from .. import telemetry as _tel
        if not _tel.enabled() or self.mesh is None:
            return
        try:
            from ..analysis import hlo as _hlo
            with _tel.span('hlo_audit'):
                text = self.compiled_text()
            census = _hlo.collective_census(
                _hlo.parse_module(text), mesh_shape=dict(self.mesh.shape),
                calibration=self._resolved_calibration())
            per_op = {base: {'calls': r['calls'], 'bytes': r['bytes'],
                             'wire_dtype': r.get('wire_dtype')}
                      for base, r in census.items()}
            total = sum(r['bytes'] for r in per_op.values())
            _tel.event('collectives', name='ParallelTrainer.step',
                       mesh=dict(self.mesh.shape), per_op=per_op,
                       total_bytes=total)
            _tel.add('collective.bytes', total)
            predicted = {base: {'calls': r['calls'],
                                'wire_bytes': r['wire_bytes'],
                                'est_us': r['est_us'],
                                'phases': r['phases'],
                                'group_size': r['group_size'],
                                'wire_dtype': r.get('wire_dtype')}
                         for base, r in census.items()}
            quant = self._quant_active
            _tel.event('collective_cost', name='ParallelTrainer.step',
                       mesh=dict(self.mesh.shape), per_op=predicted,
                       wire_bytes_total=sum(
                           r['wire_bytes'] for r in predicted.values()),
                       est_us_total=round(sum(
                           r['est_us'] for r in predicted.values()), 3),
                       quant_collectives=(quant.dtype
                                          if quant is not None
                                          else None))
        except Exception:       # audit is evidence, never a blocker
            pass

    def finish_profile(self, sync=None):
        """Finalize the sampled profiler at the end of a step loop: a
        still-open capture window is stopped, parsed and emitted (pass
        the last loss as `sync` so the traced async steps complete
        first).  No-op when profiling is off.  Without this, a window
        that opened on the run's final steps would leave jax.profiler
        tracing and its evidence unparsed.  Returns the window
        summaries gathered so far."""
        prof = self._profiler
        if prof is None:
            return []
        prof.close(sync=sync)
        return prof.windows

    def _nan_rollback(self):
        """Sentinel-demanded rollback: reload the last COMMITTED
        sharded checkpoint (the save_checkpoint directory).  Without a
        checkpoint there is nothing to restore — the device-side skip
        already kept the params finite, so training simply continues
        (and the sentinel escalates to FloatingPointError if the NaNs
        persist across rollback budgets)."""
        import os
        import warnings
        from ..telemetry import dump_flight
        mgr = getattr(self, '_ckpt_mgr', None)
        if mgr is None:
            warnings.warn(
                'NanSentinel requested a rollback but no checkpoint '
                'directory is configured (call save_checkpoint '
                'periodically); continuing with skipped updates',
                RuntimeWarning, stacklevel=2)
            return False
        # durable post-mortem next to the checkpoint we are about to
        # restore: the flight ring already holds the nan_skip strikes
        # and the nan_rollback event that led here
        dump_flight(os.path.join(mgr.directory,
                                 f'flightrec-{self._step_no}.json'))
        mgr.wait()   # the in-flight save must commit before we read
        got = self.restore_checkpoint(mgr.directory)
        if got < 0:
            warnings.warn(
                'NanSentinel rollback found no committed checkpoint '
                f'under {mgr.directory}; continuing with skipped '
                'updates', RuntimeWarning, stacklevel=2)
            return False
        return True

    def op_summary(self, *batch, sorted_by='total', **kwargs):
        """Per-op table of THIS trainer's compiled train step
        (profiler.op_summary) — never executed, never touches the
        global RNG stream.  The lowered module is shared through
        compiled_text(): the collective census, this table and
        fluid.contrib.memory_usage pay at most ONE lowering between
        them, and none at all when the persistent compile cache
        already holds this step's HLO text."""
        from ..profiler import op_summary
        if self._pipeline:
            raise NotImplementedError(
                'op_summary under pipeline parallelism: profile the '
                'per-stage module instead')
        self._ensure_compiled(batch)
        text = self.compiled_text()
        return op_summary(self._compiled, hlo_text=text,
                          totals=getattr(self, '_hlo_totals', None),
                          sorted_by=sorted_by, **kwargs)

    def eval_step(self, *batch):
        if self._pipeline:
            raise NotImplementedError(
                'eval under pipeline parallelism: sync_to_model() and '
                'evaluate on the dp/tp path (the reference also '
                'evaluates outside the 1F1B schedule)')
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        if self._eval_compiled is None:
            def estep(params, buffers, key, *batch):
                from ..jit import functional_call
                out, _ = functional_call(self.model, params, buffers,
                                         batch[:self.n_inputs], key=key,
                                         training=False)
                out_t = jax.tree_util.tree_map(
                    lambda v: Tensor._from_value(v), out)
                ys_t = [Tensor._from_value(y) for y in batch[self.n_inputs:]]
                from ..core.autograd import no_grad
                with no_grad():
                    loss = self.loss_fn(out_t, *ys_t)
                loss_v = loss.value if isinstance(loss, Tensor) else loss
                return out, loss_v.astype(jnp.float32).mean()
            self._eval_compiled = jax.jit(estep)
        key = rng_mod.next_key()
        return self._eval_compiled(self.params, self.buffers, key, *vals)

    def sync_to_model(self):
        """Write compiled-state params/buffers back into the live Layer
        (for state_dict/save after training).  Copies when donating:
        the next step() would otherwise delete the Layer's arrays."""
        if self._pipeline:
            params = jax.tree_util.tree_map(
                lambda v: jnp.array(v, copy=True), self.params) \
                if self.donate else self.params
            self._pipe.restore(params)
            return
        params, buffers = self.params, self.buffers
        if self.donate:
            params = {n: jnp.array(v, copy=True) for n, v in params.items()}
            buffers = {n: jnp.array(v, copy=True)
                       for n, v in buffers.items()}
        self.model.load_functional_state(params, buffers)

    def loss_float(self, loss):
        return float(np.asarray(loss))

    # -- sharded checkpointing ----------------------------------------------
    def train_state(self):
        """The full resumable state as one pytree (mesh-sharded leaves
        stay sharded — no host gather)."""
        return {'params': self.params, 'buffers': self.buffers,
                'opt_state': self.opt_state,
                'step': jnp.asarray(self._step_no)}

    def save_checkpoint(self, directory, keep=3, async_save=True):
        """Write the sharded train state via orbax (per-shard artifacts,
        async by default).  Reference: framework/io.py:494 at scale."""
        import os
        from ..distributed.checkpoint import CheckpointManager
        mgr = getattr(self, '_ckpt_mgr', None)
        if (mgr is None or mgr.directory != os.path.abspath(directory)
                or mgr.keep != keep or mgr.async_save != async_save):
            if mgr is not None:
                mgr.wait()  # drain in-flight async saves before swapping
            mgr = CheckpointManager(directory, keep=keep,
                                    async_save=async_save)
            self._ckpt_mgr = mgr
        return mgr.save(self.train_state(), self._step_no)

    def restore_checkpoint(self, directory, step=None):
        """Restore the newest (or given) COMMITTED checkpoint directly
        onto the mesh; returns the restored step or -1.  Torn dirs
        (async save killed before its manifest) are quarantined and
        skipped — see distributed.checkpoint.CheckpointManager."""
        import os
        from ..distributed.checkpoint import CheckpointManager
        mgr = getattr(self, '_ckpt_mgr', None)
        if mgr is not None:
            # drain the in-flight async save BEFORE any swap: dropping
            # the handle would leave its manifest uncommitted forever
            # (the newest step would read as torn) and leak the orbax
            # checkpointer
            mgr.wait()
        if mgr is None or mgr.directory != os.path.abspath(directory):
            mgr = CheckpointManager(directory)
            self._ckpt_mgr = mgr
        state, got = mgr.restore(self.train_state(), step=step)
        if state is None:
            return -1
        self.params = state['params']
        self.buffers = state['buffers']
        self.opt_state = state['opt_state']
        self._step_no = int(np.asarray(state['step']))
        return got
