"""paddle_tpu.parallel — the SPMD compilation engine.

This package has no single reference analogue: it replaces the C++
ParallelExecutor + fleet meta_optimizer Program-rewrite machinery
(/root/reference/paddle/fluid/framework/parallel_executor.cc,
python/paddle/distributed/fleet/meta_optimizers/) with the TPU-native
recipe: pick a Mesh → annotate NamedShardings → jit ONE train step →
XLA inserts/schedules collectives over ICI.
"""
from .api import (  # noqa: F401
    maybe_shard, collect_param_shardings, named_sharding, make_spec)
from .engine import ParallelTrainer  # noqa: F401
from .localsgd import LocalSGDTrainer  # noqa: F401
from .pipeline import gpipe, gpipe_spmd  # noqa: F401
from .quant_collectives import (  # noqa: F401
    QuantCollectiveConfig, resolve_quant_collectives)

__all__ = ['maybe_shard', 'collect_param_shardings', 'named_sharding',
           'make_spec', 'ParallelTrainer', 'LocalSGDTrainer', 'gpipe',
           'gpipe_spmd', 'QuantCollectiveConfig',
           'resolve_quant_collectives']
