"""GPipe pipeline parallelism over the `pp` mesh axis.

Reference analogue: fleet's pipeline_optimizer + meta_parallel/
pipeline_parallel.py (section programs + P2P sends over NCCL).
TPU-native redesign: stages are the SAME jitted block function applied
to a pp-stacked parameter pytree (transformer stacks are homogeneous, so
one stage = a slice of blocks); microbatch activations rotate stage to
stage with `lax.ppermute` inside `shard_map`, and the whole GPipe
schedule — fill, steady state, drain — is one `lax.scan` the compiler
pipelines over ICI.  Backward flows through the same ppermutes reversed
(XLA transposes them automatically), giving 1F1B-style overlap without
hand-written P2P kernels.

Schedule (S stages, M microbatches, T = M + S - 1 ticks):
tick t: stage s computes microbatch (t - s) if 0 <= t - s < M.
Stage 0 injects microbatch t; stage S-1 emits finished outputs.
"""
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ['gpipe', 'gpipe_spmd', 'PipelineLayerModule']


def gpipe(stage_params, x_mb, stage_fn, axis_name):
    """Run inside shard_map: `stage_params` is THIS stage's param slice
    (leading pp dim stripped to 1 locally), `x_mb` is [M, mb, ...] input
    microbatches (only stage 0's copy is consumed).

    Returns [M, mb, ...] outputs (only stage S-1's copy is meaningful).
    """
    sp = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    m = x_mb.shape[0]
    ticks = m + sp - 1
    # rotate activations stage s -> s+1 (ring; the wrap-around edge
    # carries junk that the validity masking ignores)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    params_local = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    out_struct = jax.eval_shape(
        stage_fn, params_local,
        jax.tree_util.tree_map(lambda a: a[0], x_mb))
    zero_out = jnp.zeros(out_struct.shape, out_struct.dtype)

    def tick(carry, t):
        prev_act, outputs = carry
        mb_idx = t - rank
        valid = (mb_idx >= 0) & (mb_idx < m)
        # stage 0 reads its own microbatch; others read the rotated
        # activation from the previous stage
        my_in = jax.lax.cond(
            rank == 0,
            lambda: jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(mb_idx, 0, m - 1), 0, keepdims=False),
            lambda: prev_act)
        y = stage_fn(params_local, my_in)
        y = jnp.where(valid, y, zero_out)
        # last stage records finished microbatches
        outputs = jax.lax.cond(
            (rank == sp - 1) & valid,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(mb_idx, 0, m - 1), 0),
            lambda o: o,
            outputs)
        # ship activations to the next stage for tick t+1
        nxt = jax.lax.ppermute(y, axis_name, perm)
        return (nxt, outputs), None

    init = (zero_out,
            jnp.zeros((m,) + zero_out.shape, zero_out.dtype))
    (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
    return outputs


def gpipe_spmd(stacked_params, x, stage_fn, mesh, num_microbatches,
               pp_axis='pp', batch_axes=()):
    """jit-level wrapper.  `stacked_params`: pytree whose leaves have a
    leading dim = pp size (stage-major).  `x`: [B, ...] global batch,
    split into `num_microbatches` along dim 0.  `stage_fn(params, x)`
    applies ONE stage.  Returns [B, ...] outputs from the last stage
    (replicated on pp)."""
    sp = dict(mesh.shape)[pp_axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        assert leaf.shape[0] == sp, (
            f'stacked params lead dim {leaf.shape[0]} != pp size {sp}; '
            f'fold extra stages into stage_fn (stages-per-device > 1)')
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    x_mb = x.reshape((num_microbatches, b // num_microbatches)
                     + x.shape[1:])

    p_spec = P(pp_axis)

    def run(params, xmb):
        out = gpipe(params, xmb, stage_fn=stage_fn, axis_name=pp_axis)
        return out[None]  # per-stage leading dim; only stage S-1 is real

    from ..core.jaxcompat import shard_map
    out = shard_map(
        run, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: p_spec,
                                         stacked_params), P()),
        out_specs=P(pp_axis),
        check_vma=False)(stacked_params, x_mb)
    out_mb = out[sp - 1]  # last stage's buffer
    return out_mb.reshape((b,) + out_mb.shape[2:])


class PipelineLayerModule:
    """Generic pipeline adapter for fleet's PipelineLayer — the engine
    behind the reference idiom ``PipelineLayer(descs, num_stages=S)`` +
    ``fleet.distributed_model`` (reference: meta_parallel/pp_layers.py
    feeding pipeline_parallel.py's schedule).

    Heterogeneous stages are routed with ``lax.switch`` on the pp
    coordinate inside the 1F1B engine; every device therefore carries a
    replicated copy of ALL stages' parameters (correctness-first
    fallback — the flagship memory-efficient path stacks homogeneous
    blocks P('pp'), see models/gpt_pipe.py).  Constraints: activations
    entering/leaving every stage share one shape/dtype (the microbatch
    input's), and tp must be 1 (stage compute is tp-replicated here, so
    a tp-psum of grads would double count).
    """

    def __init__(self, pipe_layer, mesh, loss_fn=None, tp_axis='tp'):
        assert dict(mesh.shape).get(tp_axis, 1) == 1, (
            'PipelineLayerModule requires tp==1; use a model-specific '
            'pipeline module (e.g. GPTPipeModule) for tp x pp')
        self.layer = pipe_layer
        self.mesh = mesh
        self.S = pipe_layer.num_stages
        self.loss_fn = loss_fn or pipe_layer.loss_fn
        assert self.loss_fn is not None, 'PipelineLayer needs a loss_fn'
        # per-stage functional param trees, all pp-replicated
        shared = {}
        for s in range(self.S):
            sp = {}
            for li, sub in enumerate(pipe_layer.stage_layers(s)):
                params, buffers = sub.functional_state()
                assert not buffers, (
                    'pipeline stages with buffers (BN running stats) '
                    'are not supported in the compiled pipeline step')
                sp[str(li)] = params
            shared[f'stage{s}'] = sp
        self.params = {'shared': shared, 'stages': {}}
        self.stage_specs = {}

    def restore(self, params):
        for s in range(self.S):
            sp = params['shared'][f'stage{s}']
            for li, sub in enumerate(self.layer.stage_layers(s)):
                sub.load_functional_state(sp[str(li)], {})

    def _apply_stage(self, shared, s, x):
        from ..jit import functional_call
        out = x
        for li, sub in enumerate(self.layer.stage_layers(s)):
            out, _ = functional_call(
                sub, shared[f'stage{s}'][str(li)], {}, (out,),
                training=True)
        return out

    def first_fn(self, shared, x_1mb):
        """The raw microbatch IS the pipeline activation."""
        del shared
        return x_1mb

    def stage_fn(self, shared, stage_p, x, rank):
        del stage_p
        branches = [functools.partial(self._apply_stage, shared, s)
                    for s in range(self.S)]
        return jax.lax.switch(jnp.clip(rank, 0, self.S - 1), branches, x)

    def last_fn(self, shared, y, labels_1mb):
        del shared
        loss = self.loss_fn(y, labels_1mb)
        val = getattr(loss, 'value', loss)
        return jnp.mean(val).astype(jnp.float32)
