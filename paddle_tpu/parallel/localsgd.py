"""LocalSGD — k divergent local steps per data-parallel replica, then a
parameter average over the `dp` axis.

Reference analogue: fleet meta_optimizers/localsgd_optimizer.py (skips
the per-step allreduce, periodically broadcasts averaged params over
NCCL).  TPU-native: replica-private params are a LEADING dp dim sharded
P('dp') — inside shard_map each device owns its slice and steps
independently with zero per-step collectives; `sync()` (host-called
every k steps) is one jitted mean-over-dp, which XLA lowers to a single
fused all-reduce over ICI.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..core import rng as rng_mod
from ..distributed import env as _env

__all__ = ['LocalSGDTrainer']


class LocalSGDTrainer:
    def __init__(self, model, optimizer, loss_fn, mesh=None, k_steps=4,
                 n_inputs=1, dp_axis='dp', quant_collectives=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.k_steps = max(1, int(k_steps))
        self.n_inputs = n_inputs
        self.dp_axis = dp_axis
        # quant_collectives: ship the periodic model average on a
        # block-scaled int8 wire (parallel.quant_collectives) — the
        # natural fit for LocalSGD, whose whole point is trading sync
        # fidelity for wire frequency.  Same resolve posture as
        # ParallelTrainer (env default OFF, False beats env).
        from . import quant_collectives as _qc
        self.quant_collectives = _qc.resolve_quant_collectives(
            quant_collectives)
        self.mesh = mesh or _env.get_mesh()
        assert self.mesh is not None and \
            dict(self.mesh.shape).get(dp_axis, 1) > 1, \
            'LocalSGD needs a mesh with a dp axis > 1'
        self.dp = dict(self.mesh.shape)[dp_axis]
        self._step_no = 0
        self._compiled = None
        self._sync_fn = None

        params, buffers = model.functional_state()
        self.buffers = buffers

        def stack(v):
            arr = jnp.broadcast_to(v[None], (self.dp,) + v.shape)
            spec = P(dp_axis, *([None] * v.ndim))
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        self.params = jax.tree_util.tree_map(stack, params)
        self.opt_state = jax.tree_util.tree_map(
            stack, optimizer.init(params))

    # -- local forward/loss (replica-private) --------------------------------
    def _local_loss(self, params, buffers, key, batch):
        from ..jit import functional_call
        xs, ys = batch[:self.n_inputs], batch[self.n_inputs:]
        out, new_buf = functional_call(self.model, params, buffers, xs,
                                       key=key, training=True)
        out_t = jax.tree_util.tree_map(
            lambda v: Tensor._from_value(v), out)
        ys_t = [Tensor._from_value(y) for y in ys]
        from ..core.autograd import no_grad
        with no_grad():
            loss = self.loss_fn(out_t, *ys_t)
        loss_v = loss.value if isinstance(loss, Tensor) else loss
        return loss_v.astype(jnp.float32).mean()

    def _build(self):
        opt, dp_axis = self.optimizer, self.dp_axis
        spec_p = jax.tree_util.tree_map(lambda _: P(dp_axis), self.params)
        spec_s = jax.tree_util.tree_map(lambda _: P(dp_axis),
                                        self.opt_state)
        spec_b = jax.tree_util.tree_map(lambda _: P(), self.buffers)

        def local_step(params, buffers, state, step_no, key, *batch):
            p_local = jax.tree_util.tree_map(lambda a: a[0], params)
            s_local = jax.tree_util.tree_map(lambda a: a[0], state)
            # distinct dropout stream per dp replica — LocalSGD's value
            # comes from replica divergence between syncs
            key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
            loss, grads = jax.value_and_grad(self._local_loss)(
                p_local, buffers, key, batch)
            new_p, new_s = opt.apply_gradients(p_local, grads, s_local,
                                               step_no)
            lift = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda a: a[None], t)
            return (lift(new_p), lift(new_s),
                    jax.lax.pmean(loss, dp_axis))

        batch_spec = P(dp_axis)

        def step(params, buffers, state, step_no, key, *batch):
            from ..core.jaxcompat import shard_map
            return shard_map(
                local_step, mesh=self.mesh,
                in_specs=(spec_p, spec_b, spec_s, P(), P())
                + (batch_spec,) * len(batch),
                out_specs=(spec_p, spec_s, P()),
                check_vma=False)(params, buffers, state, step_no, key,
                                 *batch)

        self._compiled = jax.jit(step, donate_argnums=(0, 2))

        if self.quant_collectives is None:
            def sync(params, step_no):
                # mean over the replica dim, broadcast back: ONE
                # all-reduce
                del step_no
                return jax.tree_util.tree_map(
                    lambda a: jnp.broadcast_to(
                        a.mean(0, keepdims=True), a.shape), params)
        else:
            from . import quant_collectives as _qc
            cfg = self.quant_collectives
            n = self.dp

            def sync_body(params, step_no):
                local = jax.tree_util.tree_map(lambda a: a[0], params)
                qkey = _qc.step_key(cfg, step_no) if cfg.stochastic \
                    else None
                avg = _qc.quantized_allreduce_tree(
                    local, dp_axis, n=n, cfg=cfg, key=qkey, op='mean')
                return jax.tree_util.tree_map(lambda a: a[None], avg)

            def sync(params, step_no):
                from ..core.jaxcompat import shard_map
                return shard_map(
                    sync_body, mesh=self.mesh,
                    in_specs=(spec_p, P()), out_specs=spec_p,
                    check_vma=False)(params, step_no)

        self._sync_fn = jax.jit(sync, donate_argnums=0)

    def step(self, *batch):
        """One local step per replica; auto-syncs every k_steps.
        Batch dim 0 shards over dp.  Returns mean loss (device array)."""
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        if self._compiled is None:
            self._build()
        key = rng_mod.next_key()
        self.params, self.opt_state, loss = self._compiled(
            self.params, self.buffers, self.opt_state,
            jnp.asarray(self._step_no + 1), key, *vals)
        self._step_no += 1
        if self._step_no % self.k_steps == 0:
            self.params = self._sync_fn(
                self.params, jnp.asarray(self._step_no))
        return loss

    def sync(self):
        """Force a parameter average now."""
        if self._sync_fn is None:
            self._build()
        self.params = self._sync_fn(
            self.params, jnp.asarray(self._step_no))

    def sync_to_model(self):
        """Average replicas and write back into the live Layer."""
        self.sync()
        flat = jax.tree_util.tree_map(lambda a: jnp.array(a[0], copy=True),
                                      self.params)
        self.model.load_functional_state(flat, self.buffers)
