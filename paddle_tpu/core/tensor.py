"""paddle_tpu.Tensor — eager tensor wrapping a jax.Array.

Reference analogue: the C++ VarBase in
/root/reference/paddle/fluid/imperative/layer.h plus the Python-side
monkey-patched methods in python/paddle/fluid/dygraph/math_op_patch.py.
TPU-native: the storage IS a jax.Array (already on device, async
dispatch); autograd state is two fields (grad_node, grad_index) pointing
into the tape (core/autograd.py).  Most methods are patched on by
paddle_tpu.tensor at import time, mirroring the reference's patch
approach so the op library lives in one place.
"""
import numpy as np
import jax
import jax.numpy as jnp

from . import autograd, dispatch
from .dtype import convert_dtype, get_default_dtype, dtype_name, is_floating


class _HookHandle:
    """Removable registration of a gradient hook (torch/paddle style)."""

    _next_id = 0

    def __init__(self, hooks, hook):
        self._hooks = hooks
        self._id = _HookHandle._next_id
        _HookHandle._next_id += 1
        hooks[self._id] = hook

    def remove(self):
        self._hooks.pop(self._id, None)


class Tensor:
    __array_priority__ = 100  # beat numpy in mixed binary ops

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        dtype = convert_dtype(dtype)
        if isinstance(data, Tensor):
            value = data.value
            if dtype is not None and value.dtype != dtype:
                value = value.astype(dtype)
        elif isinstance(data, jax.Array):
            value = data if dtype is None else data.astype(dtype)
        else:
            arr = np.asarray(data)
            if dtype is None and arr.dtype == np.float64:
                dtype = get_default_dtype()  # paddle-style float default
            value = jnp.asarray(arr, dtype=dtype)
        self.value = value
        self.stop_gradient = stop_gradient
        self.name = name
        self.persistable = False
        self._grad = None
        self.grad_node = None
        self.grad_index = 0

    # -- construction helpers ------------------------------------------------
    @classmethod
    def _from_value(cls, value, stop_gradient=True):
        t = cls.__new__(cls)
        t.value = value
        t.stop_gradient = stop_gradient
        t.name = None
        t.persistable = False
        t._grad = None
        t.grad_node = None
        t.grad_index = 0
        return t

    # -- basic attributes ----------------------------------------------------
    @property
    def shape(self):
        return list(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    def dim(self):
        # method in the reference API (t.dim()), unlike the ndim property
        return self.value.ndim

    def rank(self):
        return self.value.ndim

    @property
    def size(self):
        return int(np.prod(self.value.shape)) if self.value.shape else 1

    @property
    def place(self):
        from . import device
        return device.get_place()

    @property
    def T(self):
        # paddle semantics: reverse ALL dims (paddle.t is the ≤2-D one)
        return dispatch.apply(lambda v: jnp.transpose(v), self, op_name='T')

    def numel(self):
        return self.size

    # -- autograd ------------------------------------------------------------
    @property
    def grad(self):
        if self._grad is None:
            return None
        return Tensor._from_value(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, g):
        self._grad = None if g is None else (
            g.value if isinstance(g, Tensor) else jnp.asarray(g))

    def _accumulate_grad(self, g):
        if g.dtype != self.value.dtype:
            g = g.astype(self.value.dtype)
        self._grad = g if self._grad is None else self._grad + g

    def register_hook(self, hook):
        """Register `hook(grad) -> modified grad | None`, fired ONCE on
        this tensor's fully-accumulated gradient during a backward walk
        (reference varbase_patch_methods.py:283); the modified value is
        what propagates further and lands in `.grad`.  Returns a handle
        with `.remove()`."""
        if self.stop_gradient:
            raise RuntimeError(
                'cannot register a gradient hook on a tensor with '
                'stop_gradient=True')
        hooks = getattr(self, '_grad_hooks', None)
        if hooks is None:
            hooks = self._grad_hooks = {}
        return _HookHandle(hooks, hook)

    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self):
        return Tensor._from_value(self.value, stop_gradient=True)

    def detach_(self):
        self.grad_node = None
        self.stop_gradient = True
        return self

    def clone(self):
        return dispatch.apply(lambda v: v + 0, self, op_name='clone')

    # -- host interop --------------------------------------------------------
    def numpy(self):
        v = self.value
        if v.dtype == jnp.bfloat16:
            return np.asarray(v.astype(jnp.float32))
        return np.asarray(v)

    def item(self, *args):
        return self.value.item(*args) if args else np.asarray(self.value).item()

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(np.asarray(self.value))

    def __int__(self):
        return int(np.asarray(self.value))

    def __float__(self):
        return float(np.asarray(self.value))

    def __index__(self):
        return int(np.asarray(self.value))

    def __hash__(self):
        return id(self)

    def __repr__(self):
        try:
            # honors paddle.set_printoptions (tensor/to_string.py)
            from ..tensor.to_string import to_string
            return to_string(self)
        except Exception:
            grad_flag = f", stop_gradient={self.stop_gradient}"
            return (f"Tensor(shape={self.shape}, "
                    f"dtype={dtype_name(self.dtype)}"
                    f"{grad_flag},\n       {np.asarray(self.numpy())!r})")

    # -- dtype / value management -------------------------------------------
    def astype(self, dtype):
        d = convert_dtype(dtype)
        return dispatch.apply(lambda v: v.astype(d), self, op_name='cast')

    cast = astype

    def set_value(self, value):
        """In-place value replacement (optimizer updates, state loading)."""
        if getattr(value, 'kind', None) is not None and \
                hasattr(value, 'program'):
            # static-mode Variable: record a Program side effect; the
            # Executor writes the computed value back after run()
            value.program.side_effects.append((self, value))
            return self
        v = value.value if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(v.shape) != tuple(self.value.shape):
            raise ValueError(
                f"set_value shape mismatch {v.shape} vs {self.value.shape}")
        self.value = v.astype(self.value.dtype)
        self._inplace_version = self.inplace_version + 1
        return self

    def _snapshot(self):
        """Pre-mutation view that keeps the tape edge to the old producer.

        In-place ops record their GradNode against this snapshot, NOT
        against self — otherwise the node's input would be self itself
        (a self-edge) and the original producer would fall off the tape.
        """
        t = Tensor._from_value(self.value, stop_gradient=self.stop_gradient)
        t.grad_node = self.grad_node
        t.grad_index = self.grad_index
        return t

    def _replace(self, other):
        """Adopt another tensor's value + tape edge (in-place op result).

        stop_gradient is deliberately NOT copied: mutating a Parameter
        under no_grad() (weight init patterns) must not silently flip it
        to untrainable.
        """
        self.value = other.value
        self.grad_node = other.grad_node
        self.grad_index = other.grad_index
        self._inplace_version = self.inplace_version + 1
        return self

    @property
    def inplace_version(self):
        """Count of in-place mutations (reference
        varbase_patch_methods.py:428)."""
        return getattr(self, '_inplace_version', 0)

    def __array__(self, dtype=None, copy=None):
        """numpy interop: np.asarray(tensor) yields the values (the
        reference patches the same onto VarBase)."""
        a = np.asarray(self.value)
        if dtype is not None:
            a = a.astype(dtype)
        elif copy:
            a = a.copy()
        return a

    def __deepcopy__(self, memo):
        """Detached copy preserving the concrete class (Parameter
        keeps being a Parameter — transformer stacks deepcopy layers)
        and the exact dtype.  The jax buffer is immutable, so the copy
        SHARES it: zero host round-trips; in-place ops rebind `value`
        rather than mutate, so sharing is safe.  The tape edge is not
        cloned (the copy is simply detached)."""
        t = type(self).__new__(type(self))
        t.__dict__.update({k: v for k, v in self.__dict__.items()
                           if k not in ('_grad', 'grad_node',
                                        '_grad_hooks')})
        t._grad = None
        t.grad_node = None
        t.grad_index = 0
        memo[id(self)] = t
        return t

    # -- indexing ------------------------------------------------------------
    def _norm_index(self, idx):
        if isinstance(idx, tuple):
            return tuple(i.value if isinstance(i, Tensor) else i for i in idx)
        return idx.value if isinstance(idx, Tensor) else idx

    def __getitem__(self, idx):
        idx = self._norm_index(idx)
        return dispatch.apply(lambda v: v[idx], self, op_name='getitem')

    def __setitem__(self, idx, val):
        idx = self._norm_index(idx)
        old = self._snapshot()
        if isinstance(val, Tensor):
            out = dispatch.apply(
                lambda v, u: v.at[idx].set(u.astype(v.dtype)), old, val,
                op_name='setitem')
        else:
            out = dispatch.apply(lambda v: v.at[idx].set(val), old,
                                 op_name='setitem')
        self._replace(out)


def _register_pytree(cls):
    jax.tree_util.register_pytree_node(
        cls,
        lambda t: ((t.value,), t.stop_gradient),
        lambda sg, ch: cls._from_value(ch[0], stop_gradient=sg))


_register_pytree(Tensor)


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, tracked by nn.Layer.

    Reference analogue: python/paddle/fluid/framework.py ParamBase.
    """

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable)
        self.name = name
        self.persistable = True
        self.trainable = trainable

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v


_register_pytree(Parameter)
