"""Whole-loop compilation: K train steps fused into ONE XLA module.

The Julia full-compilation thesis (PAPERS.md, arxiv 1810.09868): on a
TPU the *program*, not the op or the step, is the compilation unit.
PRs 1-10 made the per-step module cheap to plan, cache and supervise,
but the epoch stayed a Python loop — per-step dispatch, callback
checks and telemetry ``observe()`` each ride a host round-trip, which
bounds step rate for exactly the small, high-QPS models (lenet,
widedeep-class) the north star cares about.

This module fuses K steps into one ``lax.scan``:

* the DataLoader's prefetched batches are STACKED with a leading K
  dim and the whole chunk is one dispatch;
* loss/metric scalars accumulate ON DEVICE inside the scan carry and
  come back as K-length stacked arrays, flushed once per chunk
  (``telemetry.StepAccumulator.observe_chunk`` expands them to
  per-step rows so run_report percentiles stay per-step);
* the NaN guard survives fusion: params ride the carry, the per-step
  finite mask rides the scan outputs, and :func:`cond_carry` keeps a
  non-finite step's update out of the carry with a ``lax.cond``
  rollback — ``nan_guard`` semantics are bit-identical to the
  unfused loop;
* the per-chunk step count comes back exact, so checkpoint and
  telemetry step ids never blur (preemption granularity becomes K
  steps — chunks end at the same boundaries checkpoints commit at);
* K composes with the PR-10 watchdog (:func:`clamp_chunk`: the chunk
  either fits inside the armed per-step budget or the budget is
  scaled to cover K steps) and with the PR-7 compile cache (callers
  fold K into the fingerprint so a fused module never collides with
  the per-step one).

``fused_steps`` is OFF by default everywhere; the
``PADDLE_TPU_FUSED_STEPS`` env var supplies a default K for runs that
cannot change code, and K=1 is bit-exact with today's per-step loop
(pinned by tests/test_fused_loop.py).
"""
import os
import queue
import threading
import time

__all__ = ['ENV_VAR', 'resolve_fused_steps', 'clamp_chunk',
           'cond_carry', 'stack_batches', 'chunk_sync',
           'fused_hapi_step', 'fused_trainer_step', 'fused_surrogate',
           'ChunkPrefetcher']

ENV_VAR = 'PADDLE_TPU_FUSED_STEPS'

_OFF = ('', '0', 'off', 'false', 'none', 'no')


def resolve_fused_steps(arg=None):
    """The chunk length a loop should fuse: an explicit ``fused_steps=``
    value wins (``False``/``0`` force off); ``None`` defers to the
    ``PADDLE_TPU_FUSED_STEPS`` env var — so any run can be fused
    without a code change.  Returns an int K >= 1, or 0 (off)."""
    if arg is None:
        arg = os.environ.get(ENV_VAR)
        if arg is None:
            return 0
    if arg is False:
        return 0
    if isinstance(arg, str):
        if arg.strip().lower() in _OFF:
            return 0
        arg = int(arg)
    k = int(arg)
    if k < 0:
        raise ValueError(f'fused_steps must be >= 0, got {k}')
    return k


def clamp_chunk(k, budget=None, est_step_s=None):
    """Adaptively clamp K against a watchdog step budget.

    The watchdog's contract is "one host-visible step completes within
    ``step_s``"; a fused chunk is one host-visible step that does K
    steps of work.  When a per-step wall estimate exists (the PR-6
    plan's ``est_us + compute_us``, or a measured step time), the
    chunk shrinks so K x estimate still fits inside the armed per-step
    deadline — detection latency for a hung chunk stays bounded by the
    budget the operator armed.  Without an estimate the caller instead
    scales the deadline to cover K steps (see
    ``ParallelTrainer.step_fused``).  Returns the (possibly smaller)
    chunk length, always >= 1."""
    k = max(1, int(k))
    if budget is None or not est_step_s or est_step_s <= 0:
        return k
    step_s = getattr(budget, 'step_s', None)
    if not step_s:
        return k
    return max(1, min(k, int(step_s // float(est_step_s))))


def cond_carry(ok, new_carry, old_carry):
    """In-loop rollback: select the new scan carry when the step was
    finite, else keep the old one — a ``lax.cond`` so a poisoned
    step's params/opt/buffers never enter the carry.  Both branches
    close over already-computed values, so under the scan this lowers
    to a select with no recompute; the semantics are the guarantee:
    ``nan_guard``'s skip contract survives fusion."""
    import jax
    return jax.lax.cond(ok, lambda: new_carry, lambda: old_carry)


def stack_batches(batches):
    """A list of K per-step batches (each a tuple/list of arrays) ->
    one tuple of arrays with a leading K dim, staged onto device.
    Host (numpy) fields stack on host and pay ONE device transfer per
    field; device fields stack ON DEVICE (no device->host readback —
    this is the hot staging path fusion exists to keep cheap)."""
    import numpy as np
    import jax.numpy as jnp
    if not batches:
        raise ValueError('stack_batches needs at least one batch')
    n_fields = len(batches[0])
    out = []
    for j in range(n_fields):
        col = [b[j] for b in batches]
        if all(isinstance(x, (np.ndarray, np.generic)) for x in col):
            out.append(jnp.asarray(np.stack(col)))
        else:
            out.append(jnp.stack([jnp.asarray(x) for x in col]))
    return tuple(out)


def chunk_sync(x):
    """THE one sanctioned host sync of a fused chunk: materialize the
    chunk's per-step finite mask (or any chunk-level device scalar)
    exactly once.  Runs inside an explicit transfer-guard allow block
    so the fused loops can be proven sync-free under
    ``transfer_guard_device_to_host('disallow')`` everywhere EXCEPT
    this call — and counted (``fused.chunk_syncs``) so the
    one-sync-per-chunk contract is testable, not aspirational."""
    import numpy as np
    import jax
    from .. import telemetry as _tel
    _tel.add('fused.chunk_syncs')
    with jax.transfer_guard_device_to_host('allow'):
        return np.asarray(x)


# -- fused step builders ------------------------------------------------------

def fused_hapi_step(step_fn, k):
    """Fuse hapi's per-step ``step_fn(params, buffers, opt_state,
    base_key, prev_step, lr, *arrays)`` into one K-step scan.

    The carry is (params, buffers, opt_state, step): the per-step
    dropout key (``fold_in(base_key, step)``) and the
    advance-on-finite step counter both live inside ``step_fn``, so
    the rng stream and the skip contract are bit-identical to K calls
    of the unfused module.  Outputs: final state + step, plus K-length
    stacked (losses, finite mask, metric stats) — the chunk's entire
    host-visible surface."""
    import jax

    def fused(params, buffers, opt_state, base_key, prev_step, lr,
              *stacked):
        def body(carry, xs):
            p, b, o, s = carry
            new_p, new_b, new_o, new_s, loss, ok, metrics = step_fn(
                p, b, o, base_key, s, lr, *xs)
            # step_fn already guards its own outputs (guard_update);
            # the cond re-states the rollback at the carry boundary so
            # a non-finite step can never advance the fused state
            new_carry = cond_carry(
                ok, (new_p, new_b, new_o, new_s), (p, b, o, s))
            return new_carry, (loss, ok, metrics)

        (p, b, o, s), (losses, oks, metrics) = jax.lax.scan(
            body, (params, buffers, opt_state, prev_step), stacked,
            length=k)
        return p, b, o, s, losses, oks, metrics

    return fused


def fused_trainer_step(step_fn, k, nan_guard=False):
    """Fuse ParallelTrainer's per-step ``step_fn(params, buffers,
    opt_state, step_no, key, *batch)`` into one K-step scan.

    Per-step PRNG keys arrive pre-split as a stacked (K, ...) array —
    the host draws them from the SAME ``rng_mod.next_key()`` stream
    the unfused loop consumes, so fused and unfused runs see identical
    dropout.  The optimizer step counter rides the carry and advances
    per finite step (Adam bias correction stays exact under skips)."""
    import jax

    def fused(params, buffers, opt_state, step_no0, keys, *stacked):
        def body(carry, xs):
            p, b, o, s = carry
            key, batch = xs[0], xs[1:]
            out = step_fn(p, b, o, s + 1, key, *batch)
            if nan_guard:
                new_p, new_b, new_o, loss, ok = out
                new_carry = cond_carry(
                    ok, (new_p, new_b, new_o, s + 1), (p, b, o, s))
                return new_carry, (loss, ok)
            new_p, new_b, new_o, loss = out
            return (new_p, new_b, new_o, s + 1), loss

        carry, ys = jax.lax.scan(
            body, (params, buffers, opt_state, step_no0),
            (keys,) + stacked, length=k)
        p, b, o, s = carry
        if nan_guard:
            losses, oks = ys
            return p, b, o, s, losses, oks
        return p, b, o, s, ys

    return fused


def fused_surrogate(step_fn, k):
    """Fuse an audit/AOT surrogate step (``analysis.targets.
    surrogate_step``: forward + loss + grad, no optimizer) into a
    K-step scan with on-device loss/grad accumulation — what
    ``tools/precompile.py --fused-steps`` lowers so a deploy's fused
    train module is warm before the first chunk runs."""
    import jax
    import jax.numpy as jnp

    def fused(params, buffers, key, *stacked):
        def body(carry, xs):
            g_acc, i = carry
            loss, grads = step_fn(params, buffers,
                                  jax.random.fold_in(key, i), *xs)
            g_acc = jax.tree_util.tree_map(jnp.add, g_acc, grads)
            return (g_acc, i + 1), loss

        zeros = jax.tree_util.tree_map(
            lambda v: jnp.zeros(v.shape, v.dtype), params)
        (grads, _), losses = jax.lax.scan(
            body, (zeros, jnp.zeros((), jnp.int32)), stacked, length=k)
        return losses, grads

    return fused


# -- chunk staging ------------------------------------------------------------

class ChunkPrefetcher:
    """Double-buffered device staging of K-batch chunks.

    Pulls K batches at a time from ``batch_iter``, runs ``stage_fn``
    (split + stack + device transfer) on a background thread so chunk
    N+1's host->device copy overlaps chunk N's execution, and yields
    ``(staged, n, wait_s)`` — ``wait_s`` is how long the consumer
    blocked on staging (the overlap gauge: ~0 when the double buffer
    hides the transfer).  A short tail (n < k) is yielded UNSTAGED as
    the raw batch list so the caller can run it through the per-step
    path instead of compiling a one-off K'-module.

    ``background=False`` (the num_workers=0 posture — there is no
    loader thread to overlap with) stages inline on the consumer
    thread; the iteration contract is identical.
    """

    def __init__(self, batch_iter, k, stage_fn, background=True,
                 depth=2):
        self.batch_iter = iter(batch_iter)
        self.k = max(1, int(k))
        self.stage_fn = stage_fn
        self.background = bool(background)
        self.depth = max(1, int(depth))
        self._q = None
        self._thread = None
        self._err = []
        self._closed = False

    def _pull_chunk(self):
        out = []
        for _ in range(self.k):
            try:
                out.append(next(self.batch_iter))
            except StopIteration:
                break
        return out

    def _stage(self, batches):
        if len(batches) == self.k:
            return (self.stage_fn(batches), self.k)
        return (batches, len(batches))       # unstaged tail

    def _put(self, item):
        while not self._closed:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self):
        try:
            while not self._closed:
                batches = self._pull_chunk()
                if not batches:
                    break
                if not self._put(self._stage(batches)):
                    return
        except BaseException as e:   # surface in the consumer
            self._err.append(e)
        finally:
            self._put(None)

    def __iter__(self):
        _perf = time.perf_counter
        if not self.background:
            while True:
                t0 = _perf()
                batches = self._pull_chunk()
                if not batches:
                    return
                staged, n = self._stage(batches)
                yield staged, n, _perf() - t0
            return
        self._q = queue.Queue(maxsize=self.depth)
        self._thread = threading.Thread(target=self._producer,
                                        daemon=True)
        self._thread.start()
        try:
            while True:
                t0 = _perf()
                item = self._q.get()
                wait_s = _perf() - t0
                if item is None:
                    if self._err:
                        raise self._err[0]
                    return
                staged, n = item
                yield staged, n, wait_s
        finally:
            # release a producer parked on a full queue so the daemon
            # thread exits with the epoch instead of leaking
            self._closed = True
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            # bounded join: the producer's put-poll loop re-checks
            # _closed every 0.1s, so it exits within one poll tick —
            # the timeout only guards against a stage_fn wedged on a
            # device transfer
            self._thread.join(timeout=2.0)
