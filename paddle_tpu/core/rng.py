"""Global PRNG state for eager (dygraph) mode.

Reference analogue: the global generator in
/root/reference/python/paddle/fluid/framework.py (Program.random_seed) and
paddle.seed.  TPU-native: JAX has no stateful RNG, so eager mode keeps one
explicit PRNGKey that is split per draw; compiled/functional paths thread
keys explicitly (see nn/functional dropout and jit.functional_call).
"""
import jax


class _RngState:
    """Lazy: the PRNGKey is materialized on first draw, so importing
    paddle_tpu never forces JAX backend initialization."""

    def __init__(self, seed=0):
        self.seed_value = seed
        self._key = None

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self.seed_value)
        return self._key

    @key.setter
    def key(self, k):
        self._key = k

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


_state = _RngState(0)

# Functional-key stack: paddle_tpu.jit pushes a traced PRNGKey here while
# tracing a Layer into a pure function, so stochastic ops (dropout) stay
# correct under jax.jit instead of baking in a constant eager key.
_functional_keys = []


class functional_key_scope:
    def __init__(self, key):
        self.key = key

    def __enter__(self):
        _functional_keys.append(self)
        return self

    def __exit__(self, *exc):
        _functional_keys.pop()

    def next(self):
        import jax
        self.key, sub = jax.random.split(self.key)
        return sub


def seed(s):
    """paddle.seed — reseed the global eager generator.

    Also seeds stdlib random and numpy so host-side data augmentation
    (vision.transforms) is reproducible from the same call."""
    import random as _pyrandom
    import numpy as _np
    global _state
    _state = _RngState(int(s))
    _pyrandom.seed(int(s))
    _np.random.seed(int(s) % (2 ** 32))
    return _state


def next_key():
    if _functional_keys:
        return _functional_keys[-1].next()
    return _state.next_key()


def get_seed():
    return _state.seed_value


def get_cuda_rng_state():
    """API-compat shim for paddle.get_cuda_rng_state (reference
    framework/random.py): there is no CUDA generator on TPU, so this
    returns the framework generator's state (a list, matching the
    reference's list-of-states shape — one entry per device class)."""
    return [_state.key]


def set_cuda_rng_state(state_list):
    """Restore the state captured by get_cuda_rng_state."""
    if not isinstance(state_list, (list, tuple)) or not state_list:
        raise ValueError('expected the list returned by '
                         'get_cuda_rng_state')
    _state.key = state_list[0]
