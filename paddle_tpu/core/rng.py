"""Global PRNG state for eager (dygraph) mode.

Reference analogue: the global generator in
/root/reference/python/paddle/fluid/framework.py (Program.random_seed) and
paddle.seed.  TPU-native: JAX has no stateful RNG, so eager mode keeps one
explicit PRNGKey that is split per draw; compiled/functional paths thread
keys explicitly (see nn/functional dropout and jit.functional_call).
"""
import jax


class _RngState:
    def __init__(self, seed=0):
        self.seed_value = seed
        self.key = jax.random.PRNGKey(seed)

    def next_key(self):
        self.key, sub = jax.random.split(self.key)
        return sub


_state = _RngState(0)


def seed(s):
    """paddle.seed — reseed the global eager generator."""
    global _state
    _state = _RngState(int(s))
    return _state


def next_key():
    return _state.next_key()


def get_seed():
    return _state.seed_value
