"""Device/place management.

Reference analogue: /root/reference/python/paddle/device.py (CPUPlace /
CUDAPlace / set_device).  TPU-native: places map onto jax devices; XLA
owns streams + memory, so a "place" is just a jax.Device handle plus a
default-placement policy — there is no per-op stream scheduling to do.
"""
import jax


class Place:
    def __init__(self, kind, device_id=0):
        self.kind = kind
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.kind}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place) and self.kind == other.kind
                and self.device_id == other.device_id)

    def jax_device(self):
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]


def CPUPlace():
    return Place('cpu')


def TPUPlace(device_id=0):
    return Place('tpu', device_id)


# CUDA alias kept for API familiarity; resolves to the accelerator.
def CUDAPlace(device_id=0):
    return Place('tpu', device_id)


def XPUPlace(device_id=0):
    return Place('tpu', device_id)


def _kind_of(dev):
    p = dev.platform.lower()
    if p in ('tpu', 'axon'):
        return 'tpu'
    if p in ('gpu', 'cuda', 'rocm'):
        return 'gpu'
    return 'cpu'


_current_place = None


def set_device(device):
    """set_device('tpu') / 'cpu' / 'tpu:0'."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    kind, _, idx = device.partition(':')
    kind = {'gpu': 'tpu', 'cuda': 'tpu', 'xpu': 'tpu'}.get(kind, kind)
    _current_place = Place(kind, int(idx) if idx else 0)
    return _current_place


def get_device():
    p = get_place()
    return f"{p.kind}:{p.device_id}"


def get_place():
    global _current_place
    if _current_place is None:
        kinds = {_kind_of(d) for d in jax.devices()}
        _current_place = Place('tpu' if 'tpu' in kinds else
                               ('gpu' if 'gpu' in kinds else 'cpu'))
    return _current_place


def NPUPlace(device_id=0):
    """Ascend NPU place — documented non-goal (SURVEY §2); resolves to
    the accelerator like CUDAPlace so place-typed code still runs."""
    return Place('tpu', device_id)


def CUDAPinnedPlace():
    """Pinned-host place. XLA owns host staging buffers on TPU; this is
    an API-compat alias for the CPU place."""
    return Place('cpu')


def is_compiled_with_cuda():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_xpu():
    return any(_kind_of(d) == 'tpu' for d in jax.devices())


def get_cudnn_version():
    """No cuDNN on TPU (reference device.py returns None when CUDA is
    absent — same contract here)."""
    return None


def device_count():
    return len(jax.devices())
