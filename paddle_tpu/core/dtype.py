"""Dtype registry.

TPU-native analogue of the reference's framework dtype enum
(/root/reference/python/paddle/fluid/core_*.py VarDesc.VarType): we map
string dtype names straight onto jax/numpy dtypes instead of protobuf
enum values.
"""
import jax
import jax.numpy as jnp
import numpy as np

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64

# 64-bit note: TPUs have no int64/float64 ALUs and jax truncates them
# silently unless x64 mode is on.  We alias 64-bit names to 32-bit
# OPENLY (the reference runs int64 indices everywhere; on TPU int32 is
# the native index type).  Call enable_x64() to get true 64-bit.
int64 = jnp.int32
float64 = jnp.float32
complex128 = jnp.complex64

# paddle.dtype — the reference exposes its VarType enum class under this
# name; here dtypes ARE numpy/jax dtypes, so the constructor is np.dtype.
dtype = np.dtype


def enable_x64():
    """Opt into true 64-bit dtypes (CPU debugging; not for TPU perf)."""
    global int64, float64, complex128
    jax.config.update('jax_enable_x64', True)
    int64 = jnp.int64
    float64 = jnp.float64
    complex128 = jnp.complex128
    _STR2DTYPE.update(int64=jnp.int64, float64=jnp.float64,
                      complex128=jnp.complex128)


_STR2DTYPE = {
    'float16': float16, 'bfloat16': bfloat16, 'float32': float32,
    'float64': float64, 'int8': int8, 'int16': int16, 'int32': int32,
    'int64': int64, 'uint8': uint8, 'bool': bool_,
    'complex64': complex64, 'complex128': complex128,
}

_default_dtype = jnp.float32


def convert_dtype(dtype):
    """Accept a string name, numpy/jnp dtype, or None → canonical np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"unknown dtype {dtype!r}")
        return np.dtype(_STR2DTYPE[dtype])
    return np.dtype(dtype)


def dtype_name(dtype):
    return np.dtype(dtype).name


def set_default_dtype(dtype):
    global _default_dtype
    d = convert_dtype(dtype)
    if not is_floating(d):
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    return np.dtype(_default_dtype)


def is_floating(dtype):
    return np.issubdtype(np.dtype(dtype), np.floating) or \
        np.dtype(dtype) == np.dtype(jnp.bfloat16)


def is_integer(dtype):
    return np.issubdtype(np.dtype(dtype), np.integer)
