from . import autograd, device, dispatch, dtype, rng
from .tensor import Tensor, Parameter
from .autograd import no_grad, enable_grad, is_grad_enabled

__all__ = ['Tensor', 'Parameter', 'no_grad', 'enable_grad',
           'is_grad_enabled', 'autograd', 'device', 'dispatch', 'dtype',
           'rng']
