"""Persistent compilation cache + AOT warm start.

Whole-program XLA compilation is this framework's core bet, but until
now every elastic restart, reshape restore, planner candidate and
inference cold-start re-paid the full trace+lower+compile.  This
module makes compiled work durable across processes:

* **exec tier** — serialized ``jax.export`` artifacts (StableHLO +
  calling convention) of a jitted function.  A warm process
  deserializes and runs ``jax.jit(exported.call)`` instead of
  re-tracing the Python model; the XLA backend compile underneath is
  additionally persisted via jax's own compilation cache, which this
  module points at ``<cache>/xla`` — so a restarted worker skips BOTH
  the trace/lower and the XLA optimization passes.
* **text tier** — compiled (post-partitioner) HLO text keyed by the
  planner/audit lowering keys, so repeated ``tpu_lint --plan``/
  ``--hlo`` invocations on unchanged targets read disk instead of
  compiling dozens of candidates again.

Every entry is ONE file written through the resilience/manifest commit
discipline (``manifest.atomic_write``: tmp + fsync + os.replace) with
an embedded size+sha256 of the payload.  A reader that finds a torn or
corrupted entry (external damage, chaos-injected torn writes) moves it
aside to ``<entry>.quarantine`` and treats the lookup as a miss — a
torn entry can NEVER be loaded.  Writes are multi-process safe: two
processes racing on the same fingerprint both perform atomic replaces
of identical content.

Keys are content fingerprints over (jaxpr text with memory addresses
normalized out, static arguments, mesh axes, in/out shardings,
donation mask, jax version, backend, device count, and a hash of the
package sources — any code edit invalidates conservatively).

Enable/disable: the ``PADDLE_TPU_COMPILE_CACHE`` env var.  Unset ->
``~/.cache/paddle_tpu/compile`` (on).  A path -> that directory.
``0``/``off``/``false``/empty -> disabled entirely (the escape hatch;
the test suite defaults to this so tier-1 timing is cache-independent).

Telemetry: every hit/miss/serialize/deserialize/quarantine emits a
``compile_cache`` event with bytes and latency; ``tools/run_report``
renders hit rates and estimated compile time saved.

Warm start: ``tools/precompile.py`` compiles a declared bucket set at
export time and writes a sidecar ``_PADDLE_PRECOMPILE.json`` next to a
checkpoint; ``warm_start(dir)`` (called by auto_checkpoint /
CheckpointManager.restore) pre-loads those entries so a restarted
worker's first step deserializes instead of recompiling, and
``tools/check_ckpt.py --deep`` audits the manifest against the cache.

This module imports jax lazily so stdlib-only consumers (check_ckpt)
can verify entries without a jax install.
"""
import hashlib
import json
import os
import re
import time

__all__ = [
    'enabled', 'cache_dir', 'fingerprint', 'jaxpr_text',
    'jaxpr_fingerprint', 'get', 'put', 'get_text', 'put_text',
    'lookup_executable', 'store_executable', 'export_jit',
    'through_cache', 'bucket_pow2', 'stats', 'reset_stats',
    'PRECOMPILE_MANIFEST', 'write_precompile_manifest',
    'read_precompile_manifest', 'verify_precompile_manifest',
    'warm_start',
]

ENV_VAR = 'PADDLE_TPU_COMPILE_CACHE'
_DISABLE_VALUES = ('0', 'off', 'false', 'no', '')
DEFAULT_DIR = os.path.join('~', '.cache', 'paddle_tpu', 'compile')
PRECOMPILE_MANIFEST = '_PADDLE_PRECOMPILE.json'
_FORMAT = 1
_ADDR_RE = re.compile(r'0x[0-9a-fA-F]+')

_stats = {}
_code_token_memo = None
_extra_dirs = []    # sidecar-recorded cache dirs (warm_start) lookups
#                     fall back to when the local dir misses
_xla_wired_dir = None
_xla_set_value = None


def enabled():
    """True iff the persistent cache is active for this process."""
    return cache_dir() is not None


def cache_dir():
    """The cache directory (created lazily by put), or None when the
    escape hatch (PADDLE_TPU_COMPILE_CACHE=0/off/false) is set."""
    raw = os.environ.get(ENV_VAR)
    if raw is None:
        d = os.path.expanduser(DEFAULT_DIR)
    elif raw.strip().lower() in _DISABLE_VALUES:
        _unwire_xla_cache()
        return None
    else:
        d = os.path.abspath(os.path.expanduser(raw))
    _wire_xla_cache(d)
    return d


def _unwire_xla_cache():
    """Disabling the cache must also release jax's XLA cache IF we set
    it — otherwise a formerly-enabled dir (e.g. a test fixture's
    deleted tmpdir) stays latched for the process lifetime."""
    global _xla_wired_dir, _xla_set_value
    if _xla_set_value is None:
        return
    _xla_wired_dir = None
    value, _xla_set_value = _xla_set_value, None
    try:
        import sys
        if 'jax' not in sys.modules:
            return
        import jax
        if getattr(jax.config, 'jax_compilation_cache_dir',
                   None) == value:
            jax.config.update('jax_compilation_cache_dir', None)
    except Exception:       # pragma: no cover - defensive
        pass


def _wire_xla_cache(d):
    """Point jax's own persistent compilation cache under ours: the
    exec tier removes trace+lower, this removes the XLA backend
    compile — together a warm start deserializes instead of compiling.
    A user-configured JAX_COMPILATION_CACHE_DIR (tools/_env) or a
    config value we did not set ourselves wins; a cache-dir change
    WE own (per-test tmpdirs, in-process reconfiguration) re-wires so
    the two tiers can never silently diverge."""
    global _xla_wired_dir, _xla_set_value
    if d == _xla_wired_dir:
        return
    _xla_wired_dir = d
    try:
        import jax
        if os.environ.get('JAX_COMPILATION_CACHE_DIR'):
            return
        current = getattr(jax.config, 'jax_compilation_cache_dir', None)
        if current and current != _xla_set_value:
            return      # someone else configured it — theirs wins
        _xla_set_value = os.path.join(d, 'xla')
        jax.config.update('jax_compilation_cache_dir', _xla_set_value)
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          0.0)
        try:
            jax.config.update('jax_persistent_cache_min_entry_size_bytes',
                              -1)
        except Exception:
            pass
        try:
            # jax latches its cache-enabled decision at the FIRST
            # compile; an eager op before this ran would have latched
            # "no cache" — reset so the next compile re-reads config
            from jax.experimental.compilation_cache import (
                compilation_cache as _jcc)
            _jcc.reset_cache()
        except Exception:
            pass
    except Exception:       # cache plumbing must never break a run
        pass


# -- stats / telemetry --------------------------------------------------------

def stats():
    """Process-lifetime cache counters: {action_tier: count, ...} plus
    'saved_s' (estimated trace+lower seconds avoided by hits)."""
    out = dict(_stats)
    out.setdefault('saved_s', 0.0)
    return out


def reset_stats():
    _stats.clear()


def _note(action, tier, *, nbytes=None, dur_s=None, saved_s=None,
          name=None, fp=None):
    _stats[f'{action}_{tier}'] = _stats.get(f'{action}_{tier}', 0) + 1
    if saved_s:
        _stats['saved_s'] = round(_stats.get('saved_s', 0.0) + saved_s, 6)
    try:
        from .. import telemetry
        fields = {'action': action, 'tier': tier}
        if name:
            fields['name'] = name
        if fp:
            fields['key'] = fp[:16]
        if nbytes is not None:
            fields['bytes'] = int(nbytes)
        if dur_s is not None:
            fields['dur_s'] = round(dur_s, 6)
        if saved_s is not None:
            fields['saved_s'] = round(saved_s, 6)
        telemetry.event('compile_cache', **fields)
        telemetry.add(f'compile_cache.{action}')
    except Exception:       # pragma: no cover - defensive
        pass


# -- fingerprints -------------------------------------------------------------

def _code_token():
    """sha256 over every .py source of the paddle_tpu package: ANY code
    edit invalidates the cache (the conservative direction — a stale
    executable can never outlive the code that produced it)."""
    global _code_token_memo
    if _code_token_memo is not None:
        return _code_token_memo
    h = hashlib.sha256()
    try:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames.sort()
            dirnames[:] = [d for d in dirnames if d != '__pycache__']
            for f in sorted(filenames):
                if not f.endswith('.py'):
                    continue
                p = os.path.join(dirpath, f)
                h.update(os.path.relpath(p, root).encode())
                try:
                    with open(p, 'rb') as fh:
                        h.update(fh.read())
                except OSError:
                    continue
    except Exception:
        pass
    _code_token_memo = h.hexdigest()
    return _code_token_memo


def fingerprint(kind, **parts):
    """Stable hex fingerprint of (kind, parts) + the ambient compile
    environment (jax version, backend, device count, package sources).
    Values are hashed via repr — pass only shape/spec/flag data that
    reprs deterministically.  Returns None when anything goes wrong
    (callers then skip the cache)."""
    try:
        import jax
        h = hashlib.sha256()
        h.update(b'ptcc1\0')
        h.update(str(kind).encode())
        h.update(jax.__version__.encode())
        h.update(jax.default_backend().encode())
        h.update(str(jax.device_count()).encode())
        h.update(_code_token().encode())
        for k in sorted(parts):
            h.update(b'\0' + str(k).encode() + b'=')
            v = parts[k]
            h.update(v if isinstance(v, bytes) else repr(v).encode())
        return h.hexdigest()
    except Exception:
        return None


def jaxpr_text(fn, *example_args, **example_kwargs):
    """Abstract-trace `fn` and return its jaxpr pretty-print with
    memory addresses normalized out — the cross-process-stable content
    key for a traced program.  None on any trace failure."""
    try:
        import jax
        txt = str(jax.make_jaxpr(fn)(*example_args, **example_kwargs))
        return _ADDR_RE.sub('0x', txt)
    except Exception:
        return None


def jaxpr_fingerprint(kind, fn, example_args, extra=None):
    """fingerprint() over `fn`'s normalized jaxpr — the shared key
    helper every compile choke point (to_static / hapi / trainer /
    gptgen) routes through."""
    txt = jaxpr_text(fn, *example_args)
    if txt is None:
        return None
    return fingerprint(kind, jaxpr=txt.encode(), extra=extra)


def bucket_pow2(n, cap=None):
    """Next power of two >= n (>=1), optionally capped: the decode
    prompt-length bucketing that keeps the compiled-module set finite."""
    n = max(1, int(n))
    p = 1 << (n - 1).bit_length()
    if cap is not None:
        p = min(p, int(cap))
    return max(p, n)


# -- entry store (one atomic file per entry) ----------------------------------

def _entry_path(tier, fp):
    d = cache_dir()
    if d is None:
        return None
    return os.path.join(d, f'{tier}-{fp}.ptcc')


def _quarantine(path):
    try:
        os.replace(path, path + '.quarantine')
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass


def put(tier, fp, payload, meta=None, name=None):
    """Atomically commit one cache entry.  The write goes through
    ``resilience.manifest.atomic_write`` — the same tmp+fsync+replace
    commit discipline (and the same chaos fault seam) as checkpoint
    manifests — with the payload's size+sha256 embedded in the header
    so readers can prove integrity.  Never raises; False on failure."""
    path = _entry_path(tier, fp) if fp else None
    if path is None or payload is None:
        return False
    t0 = time.perf_counter()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        header = {
            'format': _FORMAT, 'tier': tier, 'fingerprint': fp,
            'payload_size': len(payload),
            'payload_sha256': hashlib.sha256(payload).hexdigest(),
            'meta': dict(meta or {}),
        }
        hb = json.dumps(header, sort_keys=True).encode()
        from ..resilience import manifest as _manifest
        _manifest.atomic_write(
            path, lambda f: (f.write(hb), f.write(b'\n'),
                             f.write(payload)),
            mode='wb', prefix='.cc_tmp')
    except Exception:
        return False
    _note('serialize', tier, nbytes=len(payload),
          dur_s=time.perf_counter() - t0, name=name, fp=fp)
    return True


def get(tier, fp, name=None):
    """-> (payload_bytes, header) or None.  A torn/corrupt entry is
    quarantined (renamed aside) and reads as a miss — it never loads."""
    if fp is None:
        return None
    path = _entry_path(tier, fp)
    if path is None:
        return None
    t0 = time.perf_counter()
    data = None
    try:
        with open(path, 'rb') as f:
            data = f.read()
    except OSError:
        # a restore may have registered the precompile host's cache
        # dir (warm_start): a cross-host AOT set still deserializes
        alt = _find_entry(_extra_dirs, tier, fp)
        if alt is not None:
            try:
                with open(alt, 'rb') as f:
                    data = f.read()
                path = alt
            except OSError:
                data = None
    if data is None:
        _note('miss', tier, name=name, fp=fp)
        return None
    got = _parse_entry(data, tier, fp)
    if got is None:
        _quarantine(path)
        _note('quarantine', tier, nbytes=len(data), name=name, fp=fp)
        # the caller proceeds to recompile, so a quarantined lookup is
        # ALSO a miss — otherwise hit rates exclude damaged entries
        # from the denominator and overstate cache health exactly when
        # the cache is broken
        _note('miss', tier, name=name, fp=fp)
        return None
    payload, header = got
    # saved_s rides only on the exec tier's 'deserialize' event (one
    # per warm lookup) — carrying it here too would double-count the
    # compile time saved in stats() and run_report
    _note('hit', tier, nbytes=len(payload),
          dur_s=time.perf_counter() - t0, name=name, fp=fp)
    return payload, header


def _parse_entry(data, tier, fp):
    """Verify one entry's framing + integrity; None = torn/corrupt."""
    try:
        nl = data.index(b'\n')
        header = json.loads(data[:nl].decode())
        payload = data[nl + 1:]
        if header.get('format') != _FORMAT:
            return None
        if header.get('tier') != tier or header.get('fingerprint') != fp:
            return None
        if len(payload) != header.get('payload_size'):
            return None
        if hashlib.sha256(payload).hexdigest() != \
                header.get('payload_sha256'):
            return None
        return payload, header
    except Exception:
        return None


def get_text(fp, name=None):
    got = get('hlo', fp, name=name)
    if got is None:
        return None
    try:
        return got[0].decode()
    except UnicodeDecodeError:
        return None


def put_text(fp, text, meta=None, name=None):
    return put('hlo', fp, text.encode(), meta=meta, name=name)


# -- executable (jax.export) tier ---------------------------------------------

def _abstract(tree):
    import jax

    def leaf(v):
        if hasattr(v, 'shape') and hasattr(v, 'dtype'):
            # keep mesh shardings on the avals: the export (and the
            # aot_compile seeding) must describe the SAME partitioned
            # program the warm process will call with sharded arrays
            sh = getattr(v, 'sharding', None)
            if sh is not None and hasattr(sh, 'mesh'):
                try:
                    return jax.ShapeDtypeStruct(v.shape, v.dtype,
                                                sharding=sh)
                except Exception:
                    pass
            return jax.ShapeDtypeStruct(v.shape, v.dtype)
        return v

    return jax.tree_util.tree_map(leaf, tree)


def lookup_executable(fp, name=None):
    """exec-tier lookup: deserialize the jax.export artifact and wrap
    it as a jitted callable.  None on miss or deserialize failure.

    The returned callable runs the EXACT serialized StableHLO (same
    numerics as the original compile) but does not donate its inputs —
    the warm path trades that sliver of HBM for skipping the trace."""
    got = get('exec', fp, name=name)
    if got is None:
        return None
    payload, header = got
    t0 = time.perf_counter()
    try:
        import jax
        from jax import export as _jexport
        exp = _jexport.deserialize(bytearray(payload))
        fn = jax.jit(exp.call)
    except Exception:
        # verified bytes that no longer deserialize = environment
        # drift the fingerprint missed; drop them so the next miss
        # re-serializes a loadable artifact
        path = _entry_path('exec', fp)
        if path:
            _quarantine(path)
        _note('quarantine', 'exec', name=name, fp=fp)
        return None
    _note('deserialize', 'exec', nbytes=len(payload),
          dur_s=time.perf_counter() - t0,
          saved_s=(header.get('meta') or {}).get('export_s'),
          name=name, fp=fp)
    # warm starts skip every compile choke point downstream, so the
    # memory observatory would go blind on exactly the restarted
    # processes that need it — armed-only (extra lower+compile,
    # amortized by the XLA persistent cache the aot store warmed)
    from ..telemetry import memory as _mem
    if _mem.armed():
        try:
            avals = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in exp.in_avals]
            _mem.maybe_note_compiled(name or f'fp:{str(fp)[:12]}',
                                     fn, avals, source='warm_start')
        except Exception:
            pass
    return fn


def store_executable(fp, jitted, example_args, name=None, meta=None,
                     aot_compile=False):
    """Export `jitted` (a jax.jit object) over abstract versions of
    `example_args`, serialize, and commit under `fp`.  The export pays
    one extra trace+lower — the population cost a warm process saves.
    Never raises; False on failure (e.g. non-exportable custom calls).

    aot_compile=True additionally XLA-compiles the deserialized form
    (lower+compile, no execution) so the BACKEND executable lands in
    jax's persistent cache too — tools/precompile.py pays this once at
    export time and a restarted worker's first step then skips trace,
    lower AND the XLA optimization passes."""
    if fp is None or not enabled():
        return False
    try:
        import jax
        from jax import export as _jexport
        t0 = time.perf_counter()
        abstract = _abstract(tuple(example_args))
        exp = _jexport.export(jitted)(*abstract)
        blob = exp.serialize()
        export_s = time.perf_counter() - t0
        if aot_compile:
            compiled = jax.jit(exp.call).lower(*abstract).compile()
            # memory observatory rides the AOT compile we just paid
            # for — FREE extraction on every cold-miss population
            from ..telemetry import memory as _mem
            _mem.note_compiled(name or f'fp:{str(fp)[:12]}', compiled,
                               source='compile_cache')
    except Exception:
        return False
    doc = dict(meta or {})
    doc.setdefault('name', name)
    doc['export_s'] = round(export_s, 6)
    return put('exec', fp, bytes(blob), meta=doc, name=name)


def _with_fallback(warm, cold, name=None):
    """Wrap a deserialized executable so an aval mismatch (the warm
    module is shape-rigid where jax.jit would have retraced — ragged
    last batch, new to_static shapes, x64 flips) degrades to the cold
    jit instead of crashing; the cold path then retraces per shape
    exactly as an uncached run would.  `.lower` passes through to the
    warm module for the AOT consumers (compiled_text / census)."""
    state = {'warm': True}

    def call(*args, **kwargs):
        if state['warm']:
            try:
                return warm(*args, **kwargs)
            except Exception:
                # one-way: any failure of the deserialized module
                # (wrong avals, environment drift) retires it for this
                # callable — purity makes the retry safe (warm hits
                # never donate their inputs)
                state['warm'] = False
                _note('fallback', 'exec', name=name)
        return cold(*args, **kwargs)

    call.lower = warm.lower
    return call


def through_cache(jitted, example_args, *, fp, name=None):
    """The standard choke-point pattern: on a hit, the deserialized
    executable replaces `jitted` (with `jitted` kept as the aval-
    mismatch fallback); on a miss, `jitted` is exported into the cache
    and returned unchanged (the cold path keeps its exact current
    semantics, donation included).  Never raises."""
    if fp is None or not enabled():
        return jitted
    try:
        hit = lookup_executable(fp, name=name)
        if hit is not None:
            return _with_fallback(hit, jitted, name=name)
        # aot_compile: also XLA-compile the deserialized form now, so
        # the warm process's module is already in jax's persistent XLA
        # cache — the first-ever population pays ~one extra backend
        # compile; every later restart skips trace, lower AND XLA
        store_executable(fp, jitted, example_args, name=name,
                         aot_compile=True)
        return jitted
    except Exception:
        return jitted


def export_jit(fn, example_args, *, fp, name=None, jit_kwargs=None):
    """Export-primary jit: trace ONCE through jax.export, persist the
    artifact, and execute via the deserially-identical wrapped call.
    For giant traces (gptgen decode) this avoids the double trace
    ``through_cache`` pays on a miss.  Falls back to plain jax.jit
    when the cache is off or export fails."""
    import jax
    jitted = jax.jit(fn, **(jit_kwargs or {}))
    if fp is None or not enabled():
        return jitted
    try:
        from jax import export as _jexport
        t0 = time.perf_counter()
        exp = _jexport.export(jitted)(*_abstract(tuple(example_args)))
        blob = exp.serialize()
        export_s = time.perf_counter() - t0
        put('exec', fp, bytes(blob),
            meta={'name': name, 'export_s': round(export_s, 6)},
            name=name)
        return _with_fallback(jax.jit(exp.call), jitted, name=name)
    except Exception:
        return jitted


# -- AOT warm start: precompile sidecar manifests -----------------------------

def write_precompile_manifest(directory, entries, meta=None):
    """Commit a sidecar manifest next to a checkpoint recording the
    AOT bucket set precompiled for it: [{'tier', 'fingerprint',
    'description'}, ...].  Atomic (same discipline as cache entries);
    check_ckpt --deep audits it, warm_start() preloads it."""
    from ..resilience import manifest as _manifest
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    doc = {'format': _FORMAT, 'entries': list(entries),
           'cache_dir': cache_dir()}
    if meta:
        doc.update(meta)
    _manifest.atomic_write(
        os.path.join(directory, PRECOMPILE_MANIFEST),
        lambda f: json.dump(doc, f, indent=1, sort_keys=True),
        prefix='.pc_tmp')
    return doc


def read_precompile_manifest(directory):
    try:
        with open(os.path.join(os.path.abspath(directory),
                               PRECOMPILE_MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _candidate_dirs(doc):
    """Cache dirs an AOT entry may live in: the locally-configured one
    plus the one the precompile host recorded in the sidecar — a
    checkpoint audited/restored on a different host must not read as
    'broken AOT set' just because the env var points elsewhere."""
    dirs = []
    for d in (cache_dir(), (doc or {}).get('cache_dir')):
        if d and d not in dirs:
            dirs.append(d)
    return dirs


def _find_entry(dirs, tier, fp):
    for d in dirs:
        p = os.path.join(d, f'{tier}-{fp}.ptcc')
        if os.path.isfile(p):
            return p
    return None


def verify_precompile_manifest(directory):
    """-> (ok, errors): every manifest-listed entry must resolve to a
    committed, integrity-verified cache entry — in the locally
    configured cache or the one the sidecar records (jax-free: only
    file reads + sha256, so check_ckpt can audit a restore target's
    AOT set from any machine)."""
    doc = read_precompile_manifest(directory)
    if doc is None:
        return False, [f'missing or unreadable {PRECOMPILE_MANIFEST}']
    dirs = _candidate_dirs(doc)
    if not dirs:
        return False, [f'{ENV_VAR} is disabled and the sidecar records '
                       'no cache dir: the AOT set cannot be audited '
                       '(or used) on this host']
    errors = []
    for e in doc.get('entries', []):
        tier, fp = e.get('tier'), e.get('fingerprint')
        tag = e.get('description') or f'{tier}-{str(fp)[:16]}'
        path = _find_entry(dirs, tier, fp) if fp else None
        if path is None:
            errors.append(f'{tag}: cache entry missing')
            continue
        try:
            with open(path, 'rb') as f:
                data = f.read()
        except OSError as err:
            errors.append(f'{tag}: unreadable ({err})')
            continue
        if _parse_entry(data, tier, fp) is None:
            errors.append(f'{tag}: torn or corrupt cache entry')
    return not errors, errors


def warm_start(directory, name=None):
    """Verify-and-prewarm the sidecar manifest's AOT set: each listed
    entry is read once (quarantining torn ones and pulling the rest
    into the OS page cache) so the restarted worker's first compile
    lookups are disk-warm.  Nothing is retained in process RAM — a
    stale sidecar (code/jax drift re-keyed the fingerprints) must not
    pin hundreds of MB of serialized artifacts that will never be
    looked up.  Called from auto_checkpoint / CheckpointManager
    restore; silent no-op without a manifest.  Returns the count of
    verified entries."""
    if not enabled():
        return 0
    doc = read_precompile_manifest(directory)
    if doc is None:
        return 0
    dirs = _candidate_dirs(doc)
    local = cache_dir()
    for d in dirs:
        if d != local and d not in _extra_dirs:
            # remember the precompile host's cache dir so later
            # lookups fall back to it when the local dir misses
            _extra_dirs.append(d)
    n = 0
    t0 = time.perf_counter()
    for e in doc.get('entries', []):
        tier, fp = e.get('tier'), e.get('fingerprint')
        if not tier or not fp:
            continue
        path = _find_entry(dirs, tier, fp)
        if path is None:
            continue
        try:
            with open(path, 'rb') as f:
                data = f.read()
        except OSError:
            continue
        if _parse_entry(data, tier, fp) is None:
            _quarantine(path)
            _note('quarantine', tier, fp=fp)
            continue
        n += 1
    if n:
        _stats['warm_start'] = _stats.get('warm_start', 0) + n
        try:
            from .. import telemetry
            telemetry.event(
                'compile_cache', action='warm_start', tier='exec',
                count=n, dur_s=round(time.perf_counter() - t0, 6),
                name=name or os.path.basename(os.path.abspath(directory)))
            telemetry.add('compile_cache.warm_start', n)
        except Exception:       # pragma: no cover - defensive
            pass
    return n
