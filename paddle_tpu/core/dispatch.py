"""Eager op dispatch: one choke point between the paddle-like API and jnp.

Reference analogue: /root/reference/paddle/fluid/imperative/tracer.cc
(Tracer::TraceOp) + the per-op GradOpMaker registry in
paddle/fluid/operators/.  TPU-native: instead of a registry of hand-written
grad kernels, `apply` captures the cotangent closure of the *actual jnp
computation* with jax.vjp, so forward and backward always agree and both
run through XLA.

AMP (paddle_tpu.amp.auto_cast) installs a cast hook here, mirroring how
the reference's AMP lists (amp/auto_cast.py) wrap the dygraph tracer.
"""
import jax
import jax.numpy as jnp

from . import autograd
from .autograd import GradNode

# Installed by paddle_tpu.amp; signature: hook(fn_name, vals) -> vals
_amp_hook = None

# Installed by paddle_tpu.static when static mode is enabled; signature:
# handler(fn, args, kwargs, op_name) -> Variable | NotImplemented.
_static_handler = None

# Installed by paddle_tpu.analysis.runtime.amp_audit; signature:
# hook(op_name, vals) -> None.  A pure observer of the op stream —
# invoked BEFORE the amp hook, so vals are the raw arrays the caller
# fed the op (the audit diagnoses mixed dtypes the amp hook would
# re-cast every step).  Costs one None check when absent.
_audit_hook = None


def set_amp_hook(hook):
    global _amp_hook
    _amp_hook = hook


def set_static_handler(handler):
    global _static_handler
    _static_handler = handler


def set_audit_hook(hook):
    global _audit_hook
    _audit_hook = hook


def get_audit_hook():
    return _audit_hook


def _raw(x):
    from .tensor import Tensor
    if isinstance(x, Tensor):
        return x.value
    return x


def apply(fn, *args, op_name=None, **kwargs):
    """Run `fn` on unwrapped values; record a GradNode if needed.

    Tensor args anywhere in `args` are differentiated-through; Tensors in
    kwargs are unwrapped without gradient tracking (keep differentiable
    operands positional).
    """
    from .tensor import Tensor

    if _static_handler is not None:
        recorded = _static_handler(fn, args, kwargs, op_name)
        if recorded is not NotImplemented:
            return recorded

    kwargs = {k: _raw(v) for k, v in kwargs.items()}
    tpos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    vals = [args[i].value for i in tpos]

    if _audit_hook is not None:
        # pre-AMP observation: the audit diagnoses what the user FED
        # the op (mixed dtypes the amp hook will re-cast every step)
        _audit_hook(op_name or getattr(fn, '__name__', ''), vals)

    if _amp_hook is not None:
        vals = _amp_hook(op_name or getattr(fn, '__name__', ''), vals)

    def pure(*vs):
        full = list(args)
        for i, v in zip(tpos, vs):
            full[i] = v
        out = fn(*full, **kwargs)
        return tuple(out) if isinstance(out, (tuple, list)) else out

    requires = (autograd.is_grad_enabled()
                and any(not args[i].stop_gradient for i in tpos))

    if requires:
        out_vals, vjp_fn = jax.vjp(pure, *vals)
        flat, single = _flatten(out_vals)
        avals = [(v.shape, v.dtype) for v in flat]
        node = GradNode(
            vjp_fn,
            [args[i] if not args[i].stop_gradient else None for i in tpos],
            avals,
            name=op_name or getattr(fn, '__name__', ''),
            out_is_seq=not single,
            pure=pure, in_vals=vals)
        outs = [Tensor._from_value(v, stop_gradient=False) for v in flat]
        for i, t in enumerate(outs):
            t.grad_node = node
            t.grad_index = i
        return outs[0] if single else type(out_vals)(outs)
    else:
        out_vals = pure(*vals)
        flat, single = _flatten(out_vals)
        outs = [Tensor._from_value(v, stop_gradient=True) for v in flat]
        return outs[0] if single else type(out_vals)(outs)


def _flatten(out):
    if isinstance(out, (tuple, list)):
        return list(out), False
    return [out], True
