"""Dygraph autograd engine: a define-by-run tape over jax.vjp.

Reference analogue: /root/reference/paddle/fluid/imperative/ (tracer.cc,
basic_engine.cc, gradient_accumulator.cc).  The reference records a graph
of GradOpMaker nodes per C++ op kernel; here each eager op call captures
its own cotangent closure via jax.vjp, so every op automatically has a
correct gradient without per-op GradOpMaker code.  backward() is a
reverse-topological walk that accumulates cotangents; XLA executes the
actual math.

The compiled path (paddle_tpu.jit) does NOT use this tape — it traces a
pure function and uses jax.grad, which is the TPU-fast route.

Like the reference's dygraph engine (and unlike torch), cotangents are
accumulated into `.grad` of EVERY reachable stop_gradient=False tensor,
not only leaves — reference code frequently reads intermediate
`.gradient()`s.  The memory cost lasts only until the tensors die; the
tape itself is freed at the end of backward().
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp


_grad_enabled = True


def is_grad_enabled():
    return _grad_enabled


@contextlib.contextmanager
def no_grad():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = False
    try:
        yield
    finally:
        _grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = True
    try:
        yield
    finally:
        _grad_enabled = prev


def _float0_zeros(shape):
    return np.zeros(shape, dtype=jax.dtypes.float0)


class GradNode:
    """One recorded op: holds the vjp closure and graph edges."""

    __slots__ = ('vjp_fn', 'inputs', 'out_avals', 'out_grads', 'name',
                 'out_is_seq')

    def __init__(self, vjp_fn, inputs, out_avals, name='', out_is_seq=False):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # Tensors that required grad (strong refs)
        self.out_avals = out_avals    # [(shape, dtype)] per output
        self.out_grads = [None] * len(out_avals)
        self.name = name
        self.out_is_seq = out_is_seq  # fn returned a tuple (vjp wants tuple)

    def seed_grad(self, index, grad):
        if self.out_grads[index] is None:
            self.out_grads[index] = grad
        else:
            self.out_grads[index] = self.out_grads[index] + grad

    def cotangents(self):
        cts = []
        for g, (shape, dtype) in zip(self.out_grads, self.out_avals):
            if g is not None:
                # vjp requires cotangent dtype == output dtype; under AMP
                # the seed may arrive fp32 against a bf16 output.
                if g.dtype != dtype:
                    g = g.astype(dtype)
                cts.append(g)
            elif np.issubdtype(dtype, np.inexact) or dtype == jnp.bfloat16:
                cts.append(jnp.zeros(shape, dtype))
            else:
                cts.append(_float0_zeros(shape))
        return tuple(cts) if self.out_is_seq else cts[0]


def backward(tensor, grad=None, retain_graph=False):
    """Run reverse-mode accumulation from `tensor`.

    Matches paddle.Tensor.backward(): scalar outputs seed with ones; the
    resulting cotangents land in `.grad` of every reachable tensor with
    stop_gradient=False.  (Single-root form of backward_multi.)
    """
    backward_multi([tensor], [grad], retain_graph=retain_graph)


def backward_multi(tensors, grads=None, retain_graph=False):
    """backward() from one or more roots in ONE reverse walk, so shared
    subgraphs are differentiated once and freed exactly once (no forced
    graph retention between roots).  Seeds are consumed per walk — even
    under retain_graph — so a later backward()/grad() on the retained
    graph starts from zero instead of double-counting."""
    if grads is None:
        grads = [None] * len(tensors)
    roots = []
    for t, g in zip(tensors, grads):
        g = jnp.ones_like(t.value) if g is None else _val(g)
        if not t.stop_gradient:
            t._accumulate_grad(g)
        if t.grad_node is not None:
            t.grad_node.seed_grad(t.grad_index, g)
            roots.append(t.grad_node)

    order = _topo_order_multi(roots)
    for node in order:
        if all(g is None for g in node.out_grads):
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                f'trying to differentiate through op {node.name!r} whose '
                'graph was already freed by a previous backward()/grad() '
                'call; pass retain_graph=True to the earlier call')
        in_grads = node.vjp_fn(node.cotangents())
        node.out_grads = [None] * len(node.out_avals)
        for t, g in zip(node.inputs, in_grads):
            if t is None or g is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            t._accumulate_grad(g)
            if t.grad_node is not None:
                t.grad_node.seed_grad(t.grad_index, g)
        if not retain_graph:
            node.vjp_fn = None
    if not retain_graph:
        for t in tensors:
            _detach_graph(t)


class set_grad_enabled:
    """Context manager enabling/disabling the tape, effective immediately.

    Matches paddle.set_grad_enabled (reference
    python/paddle/framework/__init__.py): the mode flips at construction
    so it also works as a plain statement, and restores on __exit__.
    """

    def __init__(self, mode):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """Sum of gradients of `outputs` w.r.t. each of `inputs`.

    Matches paddle.grad (reference
    python/paddle/fluid/dygraph/base.py:407): returns a list of Tensors
    (None for unreachable inputs when allow_unused), WITHOUT touching any
    `.grad` accumulators.  `no_grad_vars` cuts gradient flow at those
    tensors.

    create_graph=True (double grad) is not supported on the eager tape —
    the TPU-fast route for higher-order derivatives is the compiled path,
    where plain jax.grad composition (jax.grad(jax.grad(f))) applies; see
    paddle_tpu.jit.
    """
    from .tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            'paddle.grad(create_graph=True) is not supported on the eager '
            'tape; compose jax.grad via paddle_tpu.jit for higher-order '
            'derivatives')
    if not only_inputs:
        raise NotImplementedError('only_inputs=False is not supported '
                                  '(matches the reference, which also '
                                  'rejects it)')
    outputs = list(outputs) if isinstance(outputs, (list, tuple)) \
        else [outputs]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
        else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    else:
        grad_outputs = list(grad_outputs) if isinstance(
            grad_outputs, (list, tuple)) else [grad_outputs]
        if len(grad_outputs) != len(outputs):
            raise ValueError('grad_outputs must match outputs in length')
    if retain_graph is None:
        retain_graph = create_graph
    cut_ids = {id(t) for t in (no_grad_vars or [])}
    input_ids = {id(t): i for i, t in enumerate(inputs)}

    acc = {}                   # id(input tensor) -> accumulated cotangent

    def _acc_input(t, g):
        k = id(t)
        acc[k] = g if k not in acc else acc[k] + g

    roots = []
    for out, go in zip(outputs, grad_outputs):
        g = jnp.ones_like(out.value) if go is None else _val(go)
        if id(out) in input_ids and not out.stop_gradient:
            _acc_input(out, g)
        if out.grad_node is not None:
            out.grad_node.seed_grad(out.grad_index, g)
            roots.append(out.grad_node)

    order = _topo_order_multi(roots)
    visited = []
    for node in order:
        visited.append(node)
        if all(g is None for g in node.out_grads):
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                f'trying to differentiate through op {node.name!r} whose '
                'graph was already freed by a previous backward()/grad() '
                'call; pass retain_graph=True to the earlier call')
        in_grads = node.vjp_fn(node.cotangents())
        node.out_grads = [None] * len(node.out_avals)
        for t, g in zip(node.inputs, in_grads):
            if t is None or g is None or id(t) in cut_ids:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if id(t) in input_ids and not t.stop_gradient:
                _acc_input(t, g)
            if t.grad_node is not None:
                t.grad_node.seed_grad(t.grad_index, g)
    if not retain_graph:
        for node in visited:
            node.vjp_fn = None

    results = []
    for t in inputs:
        g = acc.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    'one of the inputs is unreachable from outputs (or has '
                    'stop_gradient=True); pass allow_unused=True to get '
                    'None instead')
            results.append(None)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results


def _topo_order_multi(roots):
    """Reverse-topological order of GradNodes reachable from any root."""
    order, state = [], {}
    for r in roots:
        _visit(r, order, state)
    return list(reversed(order))


def _visit(node, order, state):
    """Iterative DFS postorder append of GradNodes into `order`."""
    stack = [(node, iter(_parent_nodes(node)))]
    while stack:
        n, it = stack[-1]
        if state.get(id(n)) == 2:
            stack.pop()
            continue
        state[id(n)] = 1
        advanced = False
        for p in it:
            if state.get(id(p), 0) == 0:
                stack.append((p, iter(_parent_nodes(p))))
                advanced = True
                break
        if not advanced:
            state[id(n)] = 2
            order.append(n)
            stack.pop()


def _topo_order(root):
    """Reverse-topological order of GradNodes reachable from root."""
    order, state = [], {}
    _visit(root, order, state)
    return list(reversed(order))


def _parent_nodes(node):
    seen = []
    for t in node.inputs:
        if t is not None and t.grad_node is not None:
            seen.append(t.grad_node)
    return seen


def _detach_graph(tensor):
    # Drop the root edge so Python can free the tape.
    tensor.grad_node = None


def _val(x):
    from .tensor import Tensor
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)
