"""Dygraph autograd engine: a define-by-run tape over jax.vjp.

Reference analogue: /root/reference/paddle/fluid/imperative/ (tracer.cc,
basic_engine.cc, gradient_accumulator.cc).  The reference records a graph
of GradOpMaker nodes per C++ op kernel; here each eager op call captures
its own cotangent closure via jax.vjp, so every op automatically has a
correct gradient without per-op GradOpMaker code.  backward() is a
reverse-topological walk that accumulates cotangents; XLA executes the
actual math.

The compiled path (paddle_tpu.jit) does NOT use this tape — it traces a
pure function and uses jax.grad, which is the TPU-fast route.

Like the reference's dygraph engine (and unlike torch), cotangents are
accumulated into `.grad` of EVERY reachable stop_gradient=False tensor,
not only leaves — reference code frequently reads intermediate
`.gradient()`s.  The memory cost lasts only until the tensors die; the
tape itself is freed at the end of backward().
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp


_grad_enabled = True


def is_grad_enabled():
    return _grad_enabled


@contextlib.contextmanager
def no_grad():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = False
    try:
        yield
    finally:
        _grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = True
    try:
        yield
    finally:
        _grad_enabled = prev


def _float0_zeros(shape):
    return np.zeros(shape, dtype=jax.dtypes.float0)


class GradNode:
    """One recorded op: holds the vjp closure and graph edges."""

    __slots__ = ('vjp_fn', 'inputs', 'out_avals', 'out_grads', 'name',
                 'out_is_seq')

    def __init__(self, vjp_fn, inputs, out_avals, name='', out_is_seq=False):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # Tensors that required grad (strong refs)
        self.out_avals = out_avals    # [(shape, dtype)] per output
        self.out_grads = [None] * len(out_avals)
        self.name = name
        self.out_is_seq = out_is_seq  # fn returned a tuple (vjp wants tuple)

    def seed_grad(self, index, grad):
        if self.out_grads[index] is None:
            self.out_grads[index] = grad
        else:
            self.out_grads[index] = self.out_grads[index] + grad

    def cotangents(self):
        cts = []
        for g, (shape, dtype) in zip(self.out_grads, self.out_avals):
            if g is not None:
                # vjp requires cotangent dtype == output dtype; under AMP
                # the seed may arrive fp32 against a bf16 output.
                if g.dtype != dtype:
                    g = g.astype(dtype)
                cts.append(g)
            elif np.issubdtype(dtype, np.inexact) or dtype == jnp.bfloat16:
                cts.append(jnp.zeros(shape, dtype))
            else:
                cts.append(_float0_zeros(shape))
        return tuple(cts) if self.out_is_seq else cts[0]


def backward(tensor, grad=None, retain_graph=False):
    """Run reverse-mode accumulation from `tensor`.

    Matches paddle.Tensor.backward(): scalar outputs seed with ones; the
    resulting cotangents land in `.grad` of every reachable tensor with
    stop_gradient=False.
    """
    from .tensor import Tensor

    if tensor.grad_node is None:
        if not tensor.stop_gradient:
            g = jnp.ones_like(tensor.value) if grad is None else _val(grad)
            tensor._accumulate_grad(g)
        return
    if grad is None:
        grad = jnp.ones_like(tensor.value)
    else:
        grad = _val(grad)

    if not tensor.stop_gradient:
        tensor._accumulate_grad(grad)  # root keeps its seed, like the ref
    root = tensor.grad_node
    root.seed_grad(tensor.grad_index, grad)

    order = _topo_order(root)
    for node in order:
        if all(g is None for g in node.out_grads):
            continue
        in_grads = node.vjp_fn(node.cotangents())
        for t, g in zip(node.inputs, in_grads):
            if t is None or g is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            t._accumulate_grad(g)
            if t.grad_node is not None:
                t.grad_node.seed_grad(t.grad_index, g)
        if not retain_graph:
            node.vjp_fn = None
            node.out_grads = [None] * len(node.out_avals)

    if not retain_graph:
        _detach_graph(tensor)


def _topo_order(root):
    """Reverse-topological order of GradNodes reachable from root."""
    order, state = [], {}

    def visit(node):
        stack = [(node, iter(_parent_nodes(node)))]
        while stack:
            n, it = stack[-1]
            if state.get(id(n)) == 2:
                stack.pop()
                continue
            state[id(n)] = 1
            advanced = False
            for p in it:
                if state.get(id(p), 0) == 0:
                    stack.append((p, iter(_parent_nodes(p))))
                    advanced = True
                    break
            if not advanced:
                state[id(n)] = 2
                order.append(n)
                stack.pop()

    visit(root)
    return list(reversed(order))


def _parent_nodes(node):
    seen = []
    for t in node.inputs:
        if t is not None and t.grad_node is not None:
            seen.append(t.grad_node)
    return seen


def _detach_graph(tensor):
    # Drop the root edge so Python can free the tape.
    tensor.grad_node = None


def _val(x):
    from .tensor import Tensor
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)
