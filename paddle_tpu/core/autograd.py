"""Dygraph autograd engine: a define-by-run tape over jax.vjp.

Reference analogue: /root/reference/paddle/fluid/imperative/ (tracer.cc,
basic_engine.cc, gradient_accumulator.cc).  The reference records a graph
of GradOpMaker nodes per C++ op kernel; here each eager op call captures
its own cotangent closure via jax.vjp, so every op automatically has a
correct gradient without per-op GradOpMaker code.  backward() is a
reverse-topological walk that accumulates cotangents; XLA executes the
actual math.

The compiled path (paddle_tpu.jit) does NOT use this tape — it traces a
pure function and uses jax.grad, which is the TPU-fast route.

Like the reference's dygraph engine (and unlike torch), cotangents are
accumulated into `.grad` of EVERY reachable stop_gradient=False tensor,
not only leaves — reference code frequently reads intermediate
`.gradient()`s.  The memory cost lasts only until the tensors die; the
tape itself is freed at the end of backward().
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp


_grad_enabled = True


def is_grad_enabled():
    return _grad_enabled


@contextlib.contextmanager
def no_grad():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = False
    try:
        yield
    finally:
        _grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = True
    try:
        yield
    finally:
        _grad_enabled = prev


def _float0_zeros(shape):
    return np.zeros(shape, dtype=jax.dtypes.float0)


class GradNode:
    """One recorded op: holds the vjp closure and graph edges.

    `pure`/`in_vals` (the forward fn over input VALUES and those
    values) are kept so create_graph=True can re-derive the vjp as a
    TAPED op — the returned gradients then carry their own graph for
    higher-order differentiation.  Nodes built outside dispatch.apply
    (PyLayer) may leave them None; such nodes differentiate normally
    but their gradient is a leaf for double-grad."""

    __slots__ = ('vjp_fn', 'inputs', 'out_avals', 'out_grads', 'name',
                 'out_is_seq', 'pure', 'in_vals')

    def __init__(self, vjp_fn, inputs, out_avals, name='', out_is_seq=False,
                 pure=None, in_vals=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # Tensors that required grad (strong refs)
        self.out_avals = out_avals    # [(shape, dtype)] per output
        self.out_grads = [None] * len(out_avals)
        self.name = name
        self.out_is_seq = out_is_seq  # fn returned a tuple (vjp wants tuple)
        self.pure = pure
        self.in_vals = in_vals

    def seed_grad(self, index, grad):
        if self.out_grads[index] is None:
            self.out_grads[index] = grad
        else:
            self.out_grads[index] = self.out_grads[index] + grad

    def cotangent_list(self):
        cts = []
        for g, (shape, dtype) in zip(self.out_grads, self.out_avals):
            if g is not None:
                # vjp requires cotangent dtype == output dtype; under AMP
                # the seed may arrive fp32 against a bf16 output.
                if g.dtype != dtype:
                    g = g.astype(dtype)
                cts.append(g)
            elif np.issubdtype(dtype, np.inexact) or dtype == jnp.bfloat16:
                cts.append(jnp.zeros(shape, dtype))
            else:
                cts.append(_float0_zeros(shape))
        return cts

    def cotangents(self):
        cts = self.cotangent_list()
        return tuple(cts) if self.out_is_seq else cts[0]


def _has_hooks(t):
    return bool(getattr(t, '_grad_hooks', None))


def _fire_hooks(t, g):
    """Run t's gradient hooks on the COMPLETE accumulated gradient.
    Hooks receive/return Tensors (reference API); raw cotangents are
    wrapped for the call and unwrapped back."""
    from .tensor import Tensor
    was_tensor = isinstance(g, Tensor)
    for hook in list(t._grad_hooks.values()):
        arg = g if isinstance(g, Tensor) else Tensor(g,
                                                     stop_gradient=True)
        r = hook(arg)
        if r is not None:
            g = r
    if isinstance(g, Tensor) and not was_tensor:
        return g.value
    return g


class _HookPending:
    """Defers gradient contributions for hooked tensors so the hook
    fires once on the fan-in total: a tensor's gradient is complete
    exactly when its producer node is reached in reverse-topo order
    (or at walk end for leaves)."""

    def __init__(self):
        self.by_id = {}

    def defer(self, t, g):
        e = self.by_id.get(id(t))
        if e is None:
            self.by_id[id(t)] = [t, g]
        else:
            e[1] = e[1] + g

    def flush_for_node(self, node):
        """(tensor, hooked_grad) pairs whose producer is `node`."""
        if not self.by_id:       # the common, hook-free fast path
            return ()
        out = []
        for k in [k for k, (t, _) in self.by_id.items()
                  if t.grad_node is node]:
            t, g = self.by_id.pop(k)
            out.append((t, _fire_hooks(t, g)))
        return out

    def flush_rest(self):
        out = [(t, _fire_hooks(t, g)) for t, g in self.by_id.values()]
        self.by_id.clear()
        return out


def backward(tensor, grad=None, retain_graph=False):
    """Run reverse-mode accumulation from `tensor`.

    Matches paddle.Tensor.backward(): scalar outputs seed with ones; the
    resulting cotangents land in `.grad` of every reachable tensor with
    stop_gradient=False.  (Single-root form of backward_multi.)
    """
    backward_multi([tensor], [grad], retain_graph=retain_graph)


def backward_multi(tensors, grads=None, retain_graph=False):
    """backward() from one or more roots in ONE reverse walk, so shared
    subgraphs are differentiated once and freed exactly once (no forced
    graph retention between roots).  Seeds are consumed per walk — even
    under retain_graph — so a later backward()/grad() on the retained
    graph starts from zero instead of double-counting."""
    if grads is None:
        grads = [None] * len(tensors)
    roots = []
    pending = _HookPending()
    for t, g in zip(tensors, grads):
        g = jnp.ones_like(t.value) if g is None else _val(g)
        if _has_hooks(t):
            pending.defer(t, g)
            if t.grad_node is not None:
                roots.append(t.grad_node)
            continue
        if not t.stop_gradient:
            t._accumulate_grad(g)
        if t.grad_node is not None:
            t.grad_node.seed_grad(t.grad_index, g)
            roots.append(t.grad_node)

    order = _topo_order_multi(roots)
    for node in order:
        for t, g in pending.flush_for_node(node):
            if not t.stop_gradient:
                t._accumulate_grad(g)
            node.seed_grad(t.grad_index, g)
        if all(g is None for g in node.out_grads):
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                f'trying to differentiate through op {node.name!r} whose '
                'graph was already freed by a previous backward()/grad() '
                'call; pass retain_graph=True to the earlier call')
        in_grads = node.vjp_fn(node.cotangents())
        node.out_grads = [None] * len(node.out_avals)
        for t, g in zip(node.inputs, in_grads):
            if t is None or g is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if _has_hooks(t):
                pending.defer(t, g)
                continue
            t._accumulate_grad(g)
            if t.grad_node is not None:
                t.grad_node.seed_grad(t.grad_index, g)
        if not retain_graph:
            node.vjp_fn = None
            node.pure = None
            node.in_vals = None
    for t, g in pending.flush_rest():
        if not t.stop_gradient:
            t._accumulate_grad(g)
    if not retain_graph:
        for t in tensors:
            _detach_graph(t)


class set_grad_enabled:
    """Context manager enabling/disabling the tape, effective immediately.

    Matches paddle.set_grad_enabled (reference
    python/paddle/framework/__init__.py): the mode flips at construction
    so it also works as a plain statement, and restores on __exit__.
    """

    def __init__(self, mode):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def _is_diff_dtype(dt):
    """Differentiable dtypes: floats (incl. bfloat16) AND complex —
    jax vjps carry complex cotangents fine."""
    from .dtype import is_floating
    return is_floating(dt) or np.issubdtype(np.dtype(dt), np.complexfloating)


def _taped_vjp(node):
    """Differentiable backward of one node: re-derives the vjp from the
    node's recorded pure fn + forward values, records the computation
    as a NEW GradNode (whose inputs are the original input tensors AND
    any cotangent tensors), and returns per-input gradients as graph-
    carrying Tensors.  This is what makes create_graph=True exact to
    arbitrary order — the grad op itself went through jax.vjp."""
    from .tensor import Tensor

    cts = node.cotangent_list()
    node_inputs = node.inputs
    in_vals = node.in_vals
    n_in = len(in_vals)
    diff_in = [_is_diff_dtype(v.dtype) for v in in_vals]
    # float0 cotangents (int outputs) are not valid traced values —
    # close over them; trace only the float cotangents
    ct_traced = [not (isinstance(c, np.ndarray)
                      and c.dtype == jax.dtypes.float0) for c in cts]
    ct_vals = [c.value if isinstance(c, Tensor) else c for c in cts]
    traced_ct_vals = [v for v, m in zip(ct_vals, ct_traced) if m]
    static_cts = [None if m else v for v, m in zip(ct_vals, ct_traced)]

    def gradop(*flat):
        ins = flat[:n_in]
        dyn = list(flat[n_in:])
        full_cts = [s if s is not None else dyn.pop(0)
                    for s in static_cts]
        ct = tuple(full_cts) if node.out_is_seq else full_cts[0]
        _, vjp_fn = jax.vjp(node.pure, *ins)
        gs = vjp_fn(ct)
        return tuple(g for g, m in zip(gs, diff_in) if m)

    flat_vals = list(in_vals) + traced_ct_vals
    out_vals, vjp2 = jax.vjp(gradop, *flat_vals)
    avals = [(v.shape, v.dtype) for v in out_vals]
    edge_inputs = list(node_inputs) + [
        c if isinstance(c, Tensor) and not c.stop_gradient else None
        for c, m in zip(cts, ct_traced) if m]
    node2 = GradNode(vjp2, edge_inputs, avals,
                     name=(node.name or 'op') + '_grad',
                     out_is_seq=True, pure=gradop, in_vals=flat_vals)
    outs = []
    for i, v in enumerate(out_vals):
        t = Tensor(v, stop_gradient=False)
        t.grad_node = node2
        t.grad_index = i
        outs.append(t)
    # scatter back to per-input slots (None for non-float inputs)
    it = iter(outs)
    return [next(it) if m else None for m in diff_in]


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """Sum of gradients of `outputs` w.r.t. each of `inputs`.

    Matches paddle.grad (reference
    python/paddle/fluid/dygraph/base.py:407): returns a list of Tensors
    (None for unreachable inputs when allow_unused), WITHOUT touching any
    `.grad` accumulators.  `no_grad_vars` cuts gradient flow at those
    tensors.

    create_graph=True records the backward computation itself on the
    tape (each node's vjp re-derived from its pure fn via jax.vjp, as
    a new taped op), so the returned gradients are differentiable to
    arbitrary order — WGAN-GP-style gradient penalties work eagerly.
    PyLayer nodes (built outside dispatch) differentiate once but
    their gradients are leaves.  The TPU-fast route for higher-order
    derivatives remains the compiled path (jax.grad composition via
    paddle_tpu.jit).
    """
    from .tensor import Tensor

    if not only_inputs:
        raise NotImplementedError('only_inputs=False is not supported '
                                  '(matches the reference, which also '
                                  'rejects it)')
    outputs = list(outputs) if isinstance(outputs, (list, tuple)) \
        else [outputs]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
        else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    else:
        grad_outputs = list(grad_outputs) if isinstance(
            grad_outputs, (list, tuple)) else [grad_outputs]
        if len(grad_outputs) != len(outputs):
            raise ValueError('grad_outputs must match outputs in length')
    if retain_graph is None:
        retain_graph = create_graph
    cut_ids = {id(t) for t in (no_grad_vars or [])}
    input_ids = {id(t): i for i, t in enumerate(inputs)}

    acc = {}                   # id(input tensor) -> accumulated cotangent

    def _acc_input(t, g):
        k = id(t)
        acc[k] = g if k not in acc else acc[k] + g

    roots = []
    pending = _HookPending()

    def _consume(t, g, node=None):
        """Route one complete contribution for t (hook already fired
        if any) into the input accumulator and the producer seed."""
        if id(t) in input_ids and not t.stop_gradient:
            _acc_input(t, g)
        if node is not None:
            node.seed_grad(t.grad_index, g)
        elif t.grad_node is not None:
            t.grad_node.seed_grad(t.grad_index, g)

    for out, go in zip(outputs, grad_outputs):
        if go is None:
            g = jnp.ones_like(out.value)
        elif create_graph:
            g = go if isinstance(go, Tensor) else Tensor(jnp.asarray(go))
        else:
            g = _val(go)
        if create_graph and not isinstance(g, Tensor):
            g = Tensor(g, stop_gradient=True)
        if _has_hooks(out):
            pending.defer(out, g)
        else:
            if id(out) in input_ids and not out.stop_gradient:
                _acc_input(out, g)
            if out.grad_node is not None:
                out.grad_node.seed_grad(out.grad_index, g)
        if out.grad_node is not None:
            roots.append(out.grad_node)

    order = _topo_order_multi(roots)
    visited = []
    for node in order:
        visited.append(node)
        for t, g in pending.flush_for_node(node):
            _consume(t, g, node=node)
        if all(g is None for g in node.out_grads):
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                f'trying to differentiate through op {node.name!r} whose '
                'graph was already freed by a previous backward()/grad() '
                'call; pass retain_graph=True to the earlier call')
        if create_graph and node.pure is not None:
            in_grads = _taped_vjp(node)
        else:
            cts = node.cotangents()
            if create_graph:
                # under create_graph cotangents are seeded/accumulated
                # as Tensors, but a raw closure (PyLayer) expects
                # arrays — it wraps them itself, so a Tensor here
                # would be double-wrapped and crash the user backward
                if node.out_is_seq:
                    cts = tuple(c.value if isinstance(c, Tensor) else c
                                for c in cts)
                elif isinstance(cts, Tensor):
                    cts = cts.value
            in_grads = node.vjp_fn(cts)
            if create_graph:
                # PyLayer fallback: differentiable once, leaf beyond
                in_grads = [None if g is None
                            or (isinstance(g, np.ndarray)
                                and g.dtype == jax.dtypes.float0)
                            else Tensor(g, stop_gradient=True)
                            for g in in_grads]
        node.out_grads = [None] * len(node.out_avals)
        for t, g in zip(node.inputs, in_grads):
            if t is None or g is None or id(t) in cut_ids:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if _has_hooks(t):
                pending.defer(t, g)
                continue
            if id(t) in input_ids and not t.stop_gradient:
                _acc_input(t, g)
            if t.grad_node is not None:
                t.grad_node.seed_grad(t.grad_index, g)
    for t, g in pending.flush_rest():
        _consume(t, g)
    if not retain_graph:
        for node in visited:
            node.vjp_fn = None
            node.pure = None
            node.in_vals = None

    results = []
    for t in inputs:
        g = acc.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    'one of the inputs is unreachable from outputs (or has '
                    'stop_gradient=True); pass allow_unused=True to get '
                    'None instead')
            results.append(None)
        elif isinstance(g, Tensor):
            results.append(g)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results


def _topo_order_multi(roots):
    """Reverse-topological order of GradNodes reachable from any root."""
    order, state = [], {}
    for r in roots:
        _visit(r, order, state)
    return list(reversed(order))


def _visit(node, order, state):
    """Iterative DFS postorder append of GradNodes into `order`."""
    stack = [(node, iter(_parent_nodes(node)))]
    while stack:
        n, it = stack[-1]
        if state.get(id(n)) == 2:
            stack.pop()
            continue
        state[id(n)] = 1
        advanced = False
        for p in it:
            if state.get(id(p), 0) == 0:
                stack.append((p, iter(_parent_nodes(p))))
                advanced = True
                break
        if not advanced:
            state[id(n)] = 2
            order.append(n)
            stack.pop()


def _topo_order(root):
    """Reverse-topological order of GradNodes reachable from root."""
    order, state = [], {}
    _visit(root, order, state)
    return list(reversed(order))


def _parent_nodes(node):
    seen = []
    for t in node.inputs:
        if t is not None and t.grad_node is not None:
            seen.append(t.grad_node)
    return seen


def _detach_graph(tensor):
    # Drop the root edge so Python can free the tape.
    tensor.grad_node = None


def _val(x):
    from .tensor import Tensor
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)
