"""Version compatibility shims for jax APIs the codebase rides.

One home instead of per-module try/excepts, imported only by the call
sites that need each shim (this module must stay import-light: jax
only, no paddle_tpu dependencies).
"""
import jax

__all__ = ['shard_map']


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map(..., check_vma=)``; the pinned
    build only has ``jax.experimental.shard_map.shard_map`` whose
    equivalent flag is ``check_rep=``.  Every manual-SPMD engine
    (1F1B pipeline, GPipe, LocalSGD, flash/ring attention) routes
    through here so a jax upgrade is one-line.
    """
    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as _sm
        return _sm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_rep=check_vma)
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_vma=check_vma)
