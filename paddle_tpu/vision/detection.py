"""Detection op suite — priors/anchors, box coding, NMS, proposals,
RoI pooling.

Reference analogue: /root/reference/python/paddle/fluid/layers/detection.py
(box_coder:818, prior_box:1764, anchor_generator:2399,
generate_proposals:2894, multiclass_nms:3262) backed by the C++ kernels
in /root/reference/paddle/fluid/operators/detection/
(prior_box_op.h, anchor_generator_op.h, box_coder_op.h,
multiclass_nms_op.cc, generate_proposals_op.cc, bbox_util.h) and
/root/reference/paddle/fluid/operators/roi_align_op.h / roi_pool_op.h.

TPU-native redesign — no LoD, no per-box scalar loops:

  * prior_box / anchor_generator are pure broadcasted grid math
    (the reference's h/w/prior triple loop becomes one [H,W,P,4]
    array expression XLA fuses);
  * box_coder is a vectorized encode/decode over [N,M,4];
  * NMS is the fixed-shape TPU formulation: top-k sort, one [K,K]
    IoU matrix, then a `lax.fori_loop` greedy scan that keeps a
    suppression mask — O(K²) array work instead of the reference's
    data-dependent while loop, identical keep set;
  * variable-length outputs (the reference returns LoD tensors)
    become PADDED fixed-shape arrays + a count (`rois_num`), the
    same contract the reference's *_v2 ops adopt via RoisNum — that
    is the only jit-compatible shape discipline;
  * roi_align / roi_pool vectorize the bilinear/max pooling over
    [R, C, ph, pw] with static sampling grids; both differentiate
    through jax.grad (the reference needs hand-written backward
    kernels).

Quad/polygon boxes (box_size 8..32, reference PolyIoU via gpc.cc) are
out of scope: only [xmin, ymin, xmax, ymax] boxes are supported, and
passing wider boxes raises.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import apply
from ..tensor._helpers import wrap

__all__ = [
    'iou_similarity', 'prior_box', 'anchor_generator', 'box_coder',
    'box_clip', 'multiclass_nms', 'generate_proposals', 'roi_align',
    'roi_pool', 'nms',
]

# reference bbox_util.h kBBoxClipDefault: std::log(1000.0 / 16.0)
_BBOX_CLIP = math.log(1000.0 / 16.0)


def _check_boxes4(b, name):
    if b.shape[-1] != 4:
        raise ValueError(
            f'{name}: only [xmin, ymin, xmax, ymax] boxes are '
            f'supported (last dim 4, got {b.shape[-1]}); polygon '
            'boxes (8..32 coords) are out of scope on TPU')


def _iou_matrix(a, b, normalized=True):
    """Pairwise IoU [N, M] (reference JaccardOverlap, bbox_util.h):
    un-normalized boxes count the right/bottom edge pixel (+1)."""
    off = 0.0 if normalized else 1.0
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.maximum(ix2 - ix1 + off, 0.0)
    ih = jnp.maximum(iy2 - iy1 + off, 0.0)
    inter = iw * ih
    area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """IoU matrix [N, M] between two box sets (reference
    fluid/layers/detection.py iou_similarity / iou_similarity_op)."""
    def fn(a, b):
        _check_boxes4(a, 'iou_similarity')
        return _iou_matrix(a, b, normalized=box_normalized)
    return apply(fn, wrap(x), wrap(y), op_name='iou_similarity')


# -- priors / anchors ----------------------------------------------------


def _expand_aspect_ratios(aspect_ratios, flip):
    """Reference prior_box_op.h ExpandAspectRatios: prepend 1.0, drop
    near-duplicates, optionally add reciprocals."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - e) < 1e-6 for e in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes over a feature map grid (reference
    detection.py:1764 / prior_box_op.h).

    input: [N, C, H, W] feature map; image: [N, C, imH, imW].
    Returns (boxes [H, W, P, 4] normalized xyxy, variances same shape).
    The per-cell prior order matches the reference exactly, including
    the `min_max_aspect_ratios_order` flag.
    """
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] \
        if max_sizes is not None else []
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError('max_sizes must pair with min_sizes')
    ars = _expand_aspect_ratios(list(np.atleast_1d(aspect_ratios)), flip)
    var = [float(v) for v in variance]

    # per-cell (width, height) half-extents, in reference emit order
    whs = []
    for si, mn in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                s = math.sqrt(mn * max_sizes[si]) / 2.0
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((mn * math.sqrt(ar) / 2.0,
                            mn / math.sqrt(ar) / 2.0))
        else:
            for ar in ars:
                whs.append((mn * math.sqrt(ar) / 2.0,
                            mn / math.sqrt(ar) / 2.0))
            if max_sizes:
                s = math.sqrt(mn * max_sizes[si]) / 2.0
                whs.append((s, s))
    wh = np.asarray(whs, np.float32)                     # [P, 2]

    def fn(feat, img):
        H, W = feat.shape[2], feat.shape[3]
        imH, imW = img.shape[2], img.shape[3]
        step_w = float(steps[0]) or imW / W
        step_h = float(steps[1]) or imH / H
        dt = jnp.promote_types(feat.dtype, jnp.float32)
        cx = (jnp.arange(W, dtype=dt) + offset) * step_w     # [W]
        cy = (jnp.arange(H, dtype=dt) + offset) * step_h     # [H]
        cxg = cx[None, :, None]                              # [1,W,1]
        cyg = cy[:, None, None]                              # [H,1,1]
        bw = jnp.asarray(wh[:, 0], dt)                       # [P]
        bh = jnp.asarray(wh[:, 1], dt)
        P = bw.shape[0]
        parts = [(cxg - bw) / imW, (cyg - bh) / imH,
                 (cxg + bw) / imW, (cyg + bh) / imH]
        boxes = jnp.stack([jnp.broadcast_to(p, (H, W, P))
                           for p in parts], axis=-1)         # [H,W,P,4]
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        vs = jnp.broadcast_to(jnp.asarray(var, dt), boxes.shape)
        return boxes, vs

    return apply(fn, wrap(input), wrap(image), op_name='prior_box')


def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5, name=None):
    """RCNN/RPN anchors over a feature map grid (reference
    detection.py:2399 / anchor_generator_op.h).

    Returns (anchors [H, W, A, 4] in INPUT-IMAGE pixels, variances
    same shape).  A = len(aspect_ratios) * len(anchor_sizes), ordered
    ratio-major like the reference.
    """
    sizes = [float(s) for s in np.atleast_1d(anchor_sizes)]
    ratios = [float(r) for r in np.atleast_1d(aspect_ratios)]
    var = [float(v) for v in variances]
    sw, sh = float(stride[0]), float(stride[1])

    # reference rounds the base box to integer pixels before scaling
    whs = []
    for ar in ratios:
        for size in sizes:
            area = sw * sh
            base_w = round(math.sqrt(area / ar))
            base_h = round(base_w * ar)
            whs.append((size / sw * base_w, size / sh * base_h))
    wh = np.asarray(whs, np.float32)                     # [A, 2]

    def fn(feat):
        H, W = feat.shape[2], feat.shape[3]
        dt = jnp.promote_types(feat.dtype, jnp.float32)
        xc = jnp.arange(W, dtype=dt) * sw + offset * (sw - 1)
        yc = jnp.arange(H, dtype=dt) * sh + offset * (sh - 1)
        xg = xc[None, :, None]
        yg = yc[:, None, None]
        aw = jnp.asarray(wh[:, 0], dt)
        ah = jnp.asarray(wh[:, 1], dt)
        A = aw.shape[0]
        parts = [xg - 0.5 * (aw - 1), yg - 0.5 * (ah - 1),
                 xg + 0.5 * (aw - 1), yg + 0.5 * (ah - 1)]
        anchors = jnp.stack([jnp.broadcast_to(p, (H, W, A))
                             for p in parts], axis=-1)
        vs = jnp.broadcast_to(jnp.asarray(var, dt), anchors.shape)
        return anchors, vs

    return apply(fn, wrap(input), op_name='anchor_generator')


# -- box coding ----------------------------------------------------------


def box_coder(prior_box, prior_box_var, target_box,
              code_type='encode_center_size', box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference detection.py:818 /
    box_coder_op.h).

    encode: target [N, 4] x prior [M, 4] -> deltas [N, M, 4].
    decode: target [N, M, 4] deltas, prior [M, 4] (axis=0) or [N, 4]
    (axis=1) -> boxes [N, M, 4].  prior_box_var: [M, 4] tensor, a
    4-list shared by all priors, or None.
    """
    off = 0.0 if box_normalized else 1.0
    var_list = None
    var_is_tensor = False
    if prior_box_var is None:
        pass
    elif isinstance(prior_box_var, (list, tuple)):
        var_list = np.asarray(prior_box_var, np.float32)
        if var_list.shape != (4,):
            raise ValueError('prior_box_var list must have 4 elements')
    else:
        var_is_tensor = True

    def _prior_cwh(p):
        pw = p[..., 2] - p[..., 0] + off
        ph = p[..., 3] - p[..., 1] + off
        pcx = p[..., 0] + pw / 2
        pcy = p[..., 1] + ph / 2
        return pcx, pcy, pw, ph

    def encode(t, p, pvar):
        _check_boxes4(t, 'box_coder')
        pcx, pcy, pw, ph = _prior_cwh(p)                  # [M]
        tcx = (t[:, 2] + t[:, 0]) / 2                     # [N]
        tcy = (t[:, 3] + t[:, 1]) / 2
        tw = t[:, 2] - t[:, 0] + off
        th = t[:, 3] - t[:, 1] + off
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)        # [N,M,4]
        if pvar is not None:
            out = out / pvar[None, :, :]
        elif var_list is not None:
            out = out / jnp.asarray(var_list, out.dtype)
        return out

    def decode(t, p, pvar):
        _check_boxes4(p, 'box_coder')
        # p: [M,4] broadcast over rows (axis=0) or [N,4] over cols
        bdim = 0 if axis == 0 else 1
        pcx, pcy, pw, ph = _prior_cwh(p)
        exp = (lambda a: jnp.expand_dims(a, bdim))
        pcx, pcy, pw, ph = exp(pcx), exp(pcy), exp(pw), exp(ph)
        if pvar is not None:
            v = jnp.expand_dims(pvar, bdim)
            vx, vy, vw, vh = v[..., 0], v[..., 1], v[..., 2], v[..., 3]
        elif var_list is not None:
            vx, vy, vw, vh = [jnp.asarray(v, t.dtype) for v in var_list]
        else:
            vx = vy = vw = vh = jnp.asarray(1.0, t.dtype)
        tcx = vx * t[..., 0] * pw + pcx
        tcy = vy * t[..., 1] * ph + pcy
        tw = jnp.exp(vw * t[..., 2]) * pw
        th = jnp.exp(vh * t[..., 3]) * ph
        return jnp.stack([tcx - tw / 2, tcy - th / 2,
                          tcx + tw / 2 - off,
                          tcy + th / 2 - off], axis=-1)

    fn = encode if code_type == 'encode_center_size' else decode
    if code_type not in ('encode_center_size', 'decode_center_size'):
        raise ValueError(f'unknown code_type {code_type!r}')
    if var_is_tensor:
        return apply(lambda t, p, v: fn(t, p, v),
                     wrap(target_box), wrap(prior_box),
                     wrap(prior_box_var), op_name='box_coder')
    return apply(lambda t, p: fn(t, p, None),
                 wrap(target_box), wrap(prior_box),
                 op_name='box_coder')


def box_clip(input, im_info, name=None):
    """Clip boxes to image boundaries (reference box_clip_op.h): the
    im_info row is (height, width, scale); boxes are clipped to
    [0, dim/scale - 1]."""
    def fn(b, info):
        _check_boxes4(b, 'box_clip')
        h = info[..., 0] / info[..., 2] - 1
        w = info[..., 1] / info[..., 2] - 1
        x1 = jnp.clip(b[..., 0], 0, w)
        y1 = jnp.clip(b[..., 1], 0, h)
        x2 = jnp.clip(b[..., 2], 0, w)
        y2 = jnp.clip(b[..., 3], 0, h)
        return jnp.stack([x1, y1, x2, y2], axis=-1)
    return apply(fn, wrap(input), wrap(im_info), op_name='box_clip')


# -- NMS -----------------------------------------------------------------


def _nms_core(boxes, scores, iou_threshold, top_k, score_threshold,
              eta=1.0, normalized=True):
    """Greedy NMS, fixed shapes (reference NMSFast,
    multiclass_nms_op.cc:139).  boxes [K, 4] / scores [K] MUST already
    be the score-sorted top-k candidates.  Returns (keep [K] bool in
    selection order == score order, adaptive thresholds are applied
    like the reference's eta decay)."""
    K = scores.shape[0]
    iou = _iou_matrix(boxes, boxes, normalized=normalized)
    alive0 = scores > score_threshold

    def body(i, st):
        keep, alive, thresh = st
        # candidates are score-sorted, so position i is exactly the
        # reference's next front-of-queue candidate
        ok = alive[i]
        keep = keep.at[i].set(ok)
        # suppress later candidates overlapping this kept box
        sup = (iou[i] > thresh) & ok
        alive = alive & ~sup
        thresh = jnp.where(ok & (eta < 1.0) & (thresh > 0.5),
                           thresh * eta, thresh)
        return keep, alive, thresh

    keep = jnp.zeros((K,), bool)
    keep, _, _ = lax.fori_loop(
        0, K, body, (keep, alive0,
                     jnp.asarray(iou_threshold, scores.dtype)))
    if top_k is not None and top_k < K:
        keep = keep & (jnp.cumsum(keep) <= top_k)
    return keep


def nms(boxes, scores, iou_threshold=0.3, top_k=None,
        score_threshold=None, category_idxs=None, categories=None,
        name=None):
    """Single-class (or category-batched) hard NMS.  Returns the kept
    indices sorted by score, PADDED with -1 to a fixed length (boxes
    count, or top_k when given) — the jit-safe analogue of the
    reference's variable-length index output."""
    def fn(b, s, *cat):
        _check_boxes4(b, 'nms')
        N = s.shape[0]
        order = jnp.argsort(-s)
        bs, ss = b[order], s[order]
        thr = -jnp.inf if score_threshold is None else score_threshold
        if cat:
            # category-aware: offset boxes per category so cross-class
            # pairs never overlap (the standard batched-NMS trick —
            # one IoU matrix instead of a per-class loop)
            c = cat[0][order].astype(b.dtype)
            span = (jnp.max(b) - jnp.min(b)) + 1.0
            bs = bs + (c * span)[:, None]
        keep = _nms_core(bs, ss, iou_threshold, top_k, thr)
        k = top_k if (top_k is not None and top_k < N) else N
        # stable-compact kept positions into k slots; dropped rows
        # scatter out of bounds and are discarded
        pos = jnp.where(keep, jnp.cumsum(keep) - 1, k)
        return jnp.full((k,), -1, jnp.int32).at[pos].set(
            order.astype(jnp.int32), mode='drop')
    args = [wrap(boxes), wrap(scores)]
    if category_idxs is not None:
        args.append(wrap(category_idxs))
    return apply(fn, *args, op_name='nms')


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0,
                   return_index=False, name=None):
    """Per-class NMS + cross-class top-k (reference detection.py:3262 /
    multiclass_nms_op.cc).

    bboxes: [N, M, 4] (shared boxes) and scores: [N, C, M].
    Returns (out [N, keep_top_k, 6] rows (label, score, x1, y1, x2,
    y2) padded with label -1, nms_rois_num [N] int32[, index
    [N, keep_top_k] flattened box indices when return_index]).
    The reference emits LoD-packed rows; fixed-shape padding + count
    is the jit-safe equivalent (its own *_v2/RoisNum contract).
    """
    def fn(bb, sc):
        out, num, idx = _mcnms_core(
            bb, sc, score_threshold, nms_top_k, keep_top_k,
            nms_threshold, normalized, nms_eta, background_label)
        if return_index:
            # flatten per-image box index into the [N*M] space like
            # the reference's Index output
            M = sc.shape[2]
            base = (jnp.arange(out.shape[0]) * M)[:, None]
            idx = jnp.where(idx >= 0, idx + base, -1)
            return out, num, idx
        return out, num

    return apply(fn, wrap(bboxes), wrap(scores),
                 op_name='multiclass_nms')


def _mcnms_core(bb, sc, score_threshold, nms_top_k, keep_top_k,
                nms_threshold, normalized, nms_eta,
                background_label):
    """Batched per-class NMS + cross-class top-k; the shared engine
    behind multiclass_nms, detection_output and
    retinanet_detection_output.  Returns (out [N, kk, 6], num [N],
    bidx [N, kk])."""
    _check_boxes4(bb, 'multiclass_nms')
    N, C, M = sc.shape
    K = min(int(nms_top_k), M) if nms_top_k > 0 else M

    def one_class(b, s):
        # top-K candidates by score, then greedy NMS
        top_s, top_i = lax.top_k(s, K)
        keep = _nms_core(b[top_i], top_s, nms_threshold, None,
                         score_threshold, eta=nms_eta,
                         normalized=normalized)
        return top_s, top_i, keep

    def one_image(b, s):
        ts, ti, kp = jax.vmap(one_class, in_axes=(None, 0))(b, s)
        # [C, K] each
        cls = jnp.broadcast_to(
            jnp.arange(C)[:, None], (C, K))
        if background_label >= 0:
            kp = kp & (cls != background_label)
        flat_s = jnp.where(kp, ts, -jnp.inf).reshape(-1)
        kk = min(int(keep_top_k), flat_s.shape[0]) \
            if keep_top_k > 0 else flat_s.shape[0]
        sel_s, sel = lax.top_k(flat_s, kk)
        valid = jnp.isfinite(sel_s)
        lab = jnp.where(valid, cls.reshape(-1)[sel], -1)
        bidx = ti.reshape(-1)[sel]
        bsel = b[bidx]
        out = jnp.concatenate([
            lab[:, None].astype(b.dtype),
            jnp.where(valid, sel_s, 0.0)[:, None],
            jnp.where(valid[:, None], bsel, 0.0)], axis=1)
        num = jnp.sum(valid).astype(jnp.int32)
        return out, num, jnp.where(valid, bidx, -1).astype(
            jnp.int32)

    return jax.vmap(one_image)(bb, sc)


def generate_proposals(scores, bbox_deltas, im_info, anchors,
                       variances, pre_nms_top_n=6000,
                       post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, name=None):
    """RPN proposal generation (reference detection.py:2894 /
    generate_proposals_op.cc): per image, take top pre_nms_top_n
    scores, decode deltas against anchors (variance-scaled, clipped at
    log(1000/16)), clip to the image, filter tiny boxes (at IMAGE
    scale, via im_info[2]), NMS, keep post_nms_top_n.

    scores: [N, A, H, W]; bbox_deltas: [N, A*4, H, W];
    im_info: [N, 3] (h, w, scale); anchors/variances: [H, W, A, 4].
    Returns (rois [N, post_nms_top_n, 4] padded, roi_probs
    [N, post_nms_top_n, 1], rois_num [N] int32) — fixed-shape padded
    per image instead of the reference's LoD packing.
    """
    def fn(sc, bd, info, anc, var):
        N, A, H, W = sc.shape
        total = A * H * W
        pre = min(int(pre_nms_top_n), total) \
            if pre_nms_top_n > 0 else total
        post = min(int(post_nms_top_n), pre) \
            if post_nms_top_n > 0 else pre

        # layout: anchors [H,W,A,4] flatten to [H*W*A]; scores are
        # [A,H,W] — transpose to [H,W,A] to align (the reference
        # permutes scores/deltas to NHWC the same way)
        anc_f = anc.reshape(-1, 4)
        var_f = var.reshape(-1, 4)

        def one_image(s, d, inf):
            s_f = jnp.transpose(s, (1, 2, 0)).reshape(-1)     # [HWA]
            d_f = jnp.transpose(
                d.reshape(A, 4, H, W), (2, 3, 0, 1)).reshape(-1, 4)
            top_s, top_i = lax.top_k(s_f, pre)
            a = anc_f[top_i]
            v = var_f[top_i]
            t = d_f[top_i]
            # bbox_util.h BoxCoder with pixel_offset=True
            aw = a[:, 2] - a[:, 0] + 1.0
            ah = a[:, 3] - a[:, 1] + 1.0
            acx = a[:, 0] + 0.5 * aw
            acy = a[:, 1] + 0.5 * ah
            cx = v[:, 0] * t[:, 0] * aw + acx
            cy = v[:, 1] * t[:, 1] * ah + acy
            w = jnp.exp(jnp.minimum(v[:, 2] * t[:, 2],
                                    _BBOX_CLIP)) * aw
            h = jnp.exp(jnp.minimum(v[:, 3] * t[:, 3],
                                    _BBOX_CLIP)) * ah
            prop = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                              cx + 0.5 * w - 1, cy + 0.5 * h - 1],
                             axis=-1)
            # ClipTiledBoxes (pixel_offset=False variant used by the
            # reference here clips to [0, dim - 1])
            imh, imw = inf[0], inf[1]
            prop = jnp.stack([
                jnp.clip(prop[:, 0], 0, imw - 1),
                jnp.clip(prop[:, 1], 0, imh - 1),
                jnp.clip(prop[:, 2], 0, imw - 1),
                jnp.clip(prop[:, 3], 0, imh - 1)], axis=-1)
            # FilterBoxes: min_size at image scale, centers inside
            ms = jnp.maximum(min_size, 1.0)
            ws = prop[:, 2] - prop[:, 0] + 1
            hs = prop[:, 3] - prop[:, 1] + 1
            ws_s = (prop[:, 2] - prop[:, 0]) / inf[2] + 1
            hs_s = (prop[:, 3] - prop[:, 1]) / inf[2] + 1
            cx_in = prop[:, 0] + ws / 2
            cy_in = prop[:, 1] + hs / 2
            ok = ((ws_s >= ms) & (hs_s >= ms)
                  & (cx_in <= imw) & (cy_in <= imh))
            s_kept = jnp.where(ok, top_s, -jnp.inf)
            # NMS over the surviving candidates (already sorted)
            keep = _nms_core(prop, s_kept, nms_thresh, post,
                             -jnp.inf, eta=eta, normalized=False)
            keep = keep & ok
            pos = jnp.where(keep, jnp.cumsum(keep) - 1, post)
            rois = jnp.zeros((post, 4), prop.dtype).at[pos].set(
                prop, mode='drop')
            probs = jnp.zeros((post, 1), prop.dtype).at[pos].set(
                top_s[:, None], mode='drop')
            num = jnp.minimum(jnp.sum(keep), post).astype(jnp.int32)
            return rois, probs, num

        return jax.vmap(one_image)(sc, bd, info)

    return apply(fn, wrap(scores), wrap(bbox_deltas), wrap(im_info),
                 wrap(anchors), wrap(variances),
                 op_name='generate_proposals')


# -- RoI pooling ---------------------------------------------------------


def _roi_batch_ids(boxes_num, R):
    """Map flat roi index -> batch index from per-image counts
    (the RoisNum contract of the reference's v2 ops)."""
    ends = jnp.cumsum(boxes_num)
    return jnp.searchsorted(ends, jnp.arange(R), side='right')


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoI Align (reference roi_align_op.h / vision ops roi_align):
    average of bilinear samples on a regular grid per output bin.

    x: [N, C, H, W]; boxes: [R, 4] in input-image coords;
    boxes_num: [N] int. Returns [R, C, ph, pw].  Differentiable
    through jax.grad (the reference ships a hand-written backward).

    sampling_ratio > 0 uses that fixed grid; <= 0 uses the reference's
    adaptive ceil(roi_size / pooled_size) grid, computed with a static
    upper bound of ceil(feature_dim / pooled_dim) samples and masking
    (rois larger than the feature map clamp to that bound).
    """
    if isinstance(output_size, int):
        ph, pw = output_size, output_size
    else:
        ph, pw = output_size

    def fn(xv, bx, bn):
        _check_boxes4(bx, 'roi_align')
        N, C, H, W = xv.shape
        R = bx.shape[0]
        bids = _roi_batch_ids(bn, R)
        off = 0.5 if aligned else 0.0
        if sampling_ratio > 0:
            gh = gw = int(sampling_ratio)
        else:
            gh = max(1, -(-H // ph))
            gw = max(1, -(-W // pw))

        def one_roi(roi, bid):
            x1 = roi[0] * spatial_scale - off
            y1 = roi[1] * spatial_scale - off
            x2 = roi[2] * spatial_scale - off
            y2 = roi[3] * spatial_scale - off
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            bin_h = rh / ph
            bin_w = rw / pw
            if sampling_ratio > 0:
                nh = jnp.full((), gh)
                nw = jnp.full((), gw)
            else:
                nh = jnp.minimum(jnp.ceil(bin_h), gh).astype(jnp.int32)
                nw = jnp.minimum(jnp.ceil(bin_w), gw).astype(jnp.int32)
                nh = jnp.maximum(nh, 1)
                nw = jnp.maximum(nw, 1)
            iy = jnp.arange(gh)
            ix = jnp.arange(gw)
            # sample centers: (p + (i + .5)/n) * bin  (masked past n)
            yy = (y1 + (jnp.arange(ph)[:, None] * bin_h)
                  + (iy[None, :] + 0.5) * (bin_h / nh))   # [ph, gh]
            xx = (x1 + (jnp.arange(pw)[:, None] * bin_w)
                  + (ix[None, :] + 0.5) * (bin_w / nw))   # [pw, gw]
            my = iy[None, :] < nh                          # [1, gh]
            mx = ix[None, :] < nw

            def bilinear(ys, xs):
                # reference: samples outside [-1, H] x [-1, W] give 0
                oob = ((ys < -1.0) | (ys > H) | (xs < -1.0)
                       | (xs > W))
                y = jnp.clip(ys, 0.0, None)
                xq = jnp.clip(xs, 0.0, None)
                y0 = jnp.minimum(jnp.floor(y), H - 1).astype(jnp.int32)
                x0 = jnp.minimum(jnp.floor(xq),
                                 W - 1).astype(jnp.int32)
                y = jnp.where(y0 >= H - 1, jnp.asarray(
                    H - 1, y.dtype), y)
                xq = jnp.where(x0 >= W - 1, jnp.asarray(
                    W - 1, xq.dtype), xq)
                y1i = jnp.minimum(y0 + 1, H - 1)
                x1i = jnp.minimum(x0 + 1, W - 1)
                ly = y - y0
                lx = xq - x0
                hy, hx = 1.0 - ly, 1.0 - lx
                img = xv[bid]                              # [C, H, W]
                v00 = img[:, y0, x0]
                v01 = img[:, y0, x1i]
                v10 = img[:, y1i, x0]
                v11 = img[:, y1i, x1i]
                val = (hy * hx * v00 + hy * lx * v01
                       + ly * hx * v10 + ly * lx * v11)
                return jnp.where(oob, 0.0, val)

            # all sample points [ph, pw, gh, gw]
            ys = yy[:, None, :, None]
            xs = xx[None, :, None, :]
            ysb = jnp.broadcast_to(ys, (ph, pw, gh, gw))
            xsb = jnp.broadcast_to(xs, (ph, pw, gh, gw))
            vals = bilinear(ysb.reshape(-1), xsb.reshape(-1))
            vals = vals.reshape(C, ph, pw, gh, gw)
            m = (my[0][None, None, None, :, None]
                 & mx[0][None, None, None, None, :])
            count = (nh * nw).astype(vals.dtype)
            return jnp.sum(jnp.where(m, vals, 0.0),
                           axis=(-2, -1)) / count

        return jax.vmap(one_roi)(bx, bids)

    return apply(fn, wrap(x), wrap(boxes), wrap(boxes_num),
                 op_name='roi_align')


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """RoI max pooling (reference roi_pool_op.h): quantized bins, max
    over each bin's pixels; empty bins give 0.

    x: [N, C, H, W]; boxes [R, 4]; boxes_num [N].
    Returns [R, C, ph, pw].  The bin membership is computed as masks
    over the full H/W extents (two masked-max passes) — fixed shapes
    instead of the reference's per-bin scalar loops.
    """
    if isinstance(output_size, int):
        ph, pw = output_size, output_size
    else:
        ph, pw = output_size

    def fn(xv, bx, bn):
        _check_boxes4(bx, 'roi_pool')
        N, C, H, W = xv.shape
        R = bx.shape[0]
        bids = _roi_batch_ids(bn, R)

        def one_roi(roi, bid):
            x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            bin_h = rh.astype(xv.dtype) / ph
            bin_w = rw.astype(xv.dtype) / pw
            pidx = jnp.arange(ph)[:, None]                # [ph, 1]
            hh = jnp.arange(H)[None, :]                   # [1, H]
            hstart = jnp.clip(jnp.floor(pidx * bin_h) + y1, 0, H)
            hend = jnp.clip(jnp.ceil((pidx + 1) * bin_h) + y1, 0, H)
            mask_h = (hh >= hstart) & (hh < hend)          # [ph, H]
            qidx = jnp.arange(pw)[:, None]
            ww = jnp.arange(W)[None, :]
            wstart = jnp.clip(jnp.floor(qidx * bin_w) + x1, 0, W)
            wend = jnp.clip(jnp.ceil((qidx + 1) * bin_w) + x1, 0, W)
            mask_w = (ww >= wstart) & (ww < wend)          # [pw, W]
            img = xv[bid]                                  # [C, H, W]
            neg = jnp.asarray(-jnp.inf, xv.dtype)
            # max over w per (pw) bin, then over h per (ph) bin
            t = jnp.max(jnp.where(mask_w[None, None, :, :],
                                  img[:, :, None, :], neg),
                        axis=-1)                           # [C, H, pw]
            out = jnp.max(jnp.where(mask_h[None, :, :, None],
                                    t[:, None], neg),
                          axis=2)                          # [C, ph, pw]
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(one_roi)(bx, bids)

    return apply(fn, wrap(x), wrap(boxes), wrap(boxes_num),
                 op_name='roi_pool')


# -- SSD training path + FPN routing (batch 2) ---------------------------

__all__ += ['density_prior_box', 'bipartite_match', 'target_assign',
            'detection_output', 'ssd_loss',
            'distribute_fpn_proposals', 'collect_fpn_proposals']


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """SSD density prior boxes (reference detection.py:1925 /
    density_prior_box_op.h): each fixed_size s with density d places a
    d x d grid of shifted centers per cell, one box per fixed_ratio.
    Returns (boxes [H, W, P, 4] or [H*W*P, 4], variances same)."""
    densities = [int(d) for d in (densities or [])]
    fixed_sizes = [float(s) for s in (fixed_sizes or [])]
    fixed_ratios = [float(r) for r in (fixed_ratios or [])]
    if len(densities) != len(fixed_sizes):
        raise ValueError('densities and fixed_sizes must pair up')
    if not fixed_ratios:
        raise ValueError('fixed_ratios must be provided')
    var = [float(v) for v in variance]

    def fn(feat, img):
        H, W = feat.shape[2], feat.shape[3]
        imH, imW = img.shape[2], img.shape[3]
        step_w = float(steps[0]) or imW / W
        step_h = float(steps[1]) or imH / H
        step_avg = int((step_w + step_h) * 0.5)
        dt = jnp.promote_types(feat.dtype, jnp.float32)
        cx = (jnp.arange(W, dtype=dt) + offset) * step_w     # [W]
        cy = (jnp.arange(H, dtype=dt) + offset) * step_h     # [H]
        # per-cell offsets and box extents, in reference emit order
        offs_x, offs_y, half_w, half_h = [], [], [], []
        for s, d in zip(fixed_sizes, densities):
            shift = step_avg // d
            base = -step_avg / 2.0 + shift / 2.0
            for r in fixed_ratios:
                bw = s * math.sqrt(r) / 2.0
                bh = s / math.sqrt(r) / 2.0
                for di in range(d):
                    for dj in range(d):
                        offs_x.append(base + dj * shift)
                        offs_y.append(base + di * shift)
                        half_w.append(bw)
                        half_h.append(bh)
        ox = jnp.asarray(offs_x, dt)                         # [P]
        oy = jnp.asarray(offs_y, dt)
        hw = jnp.asarray(half_w, dt)
        hh = jnp.asarray(half_h, dt)
        P = ox.shape[0]
        ctr_x = cx[None, :, None] + ox                       # [1,W,P]
        ctr_y = cy[:, None, None] + oy                       # [H,1,P]
        # the kernel clamps into [0, 1] at assignment time
        parts = [jnp.maximum((ctr_x - hw) / imW, 0.0),
                 jnp.maximum((ctr_y - hh) / imH, 0.0),
                 jnp.minimum((ctr_x + hw) / imW, 1.0),
                 jnp.minimum((ctr_y + hh) / imH, 1.0)]
        boxes = jnp.stack([jnp.broadcast_to(p, (H, W, P))
                           for p in parts], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        vs = jnp.broadcast_to(jnp.asarray(var, dt), boxes.shape)
        if flatten_to_2d:
            boxes = boxes.reshape(-1, 4)
            vs = vs.reshape(-1, 4)
        return boxes, vs

    return apply(fn, wrap(input), wrap(image),
                 op_name='density_prior_box')


def _bipartite_core(dist, match_type, dist_threshold):
    """dist [R, C] -> (col_to_row [C] int32, col_dist [C]).  Greedy
    global matching exactly like bipartite_match_op.cc: repeatedly
    take the largest remaining (row, col) pair — as a fori_loop of R
    argmax steps over a masked matrix; then the per_prediction pass
    argmaxes each unmatched column over rows with dist >= threshold."""
    R, C = dist.shape
    NEG = jnp.asarray(-1.0, dist.dtype)

    def body(_, st):
        m, row_used, col_used = st
        masked = jnp.where(row_used[:, None] | col_used[None, :],
                           NEG, dist)
        flat = jnp.argmax(masked)
        i, j = flat // C, flat % C
        ok = masked[i, j] > 0
        m = m.at[j].set(jnp.where(ok, i.astype(jnp.int32), m[j]))
        row_used = row_used.at[i].set(row_used[i] | ok)
        col_used = col_used.at[j].set(col_used[j] | ok)
        return m, row_used, col_used

    m0 = jnp.full((C,), -1, jnp.int32)
    m, _, _ = lax.fori_loop(
        0, R, body, (m0, jnp.zeros(R, bool), jnp.zeros(C, bool)))

    if match_type == 'per_prediction':
        thr = 0.5 if dist_threshold is None else float(dist_threshold)
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)   # [C]
        best = jnp.max(dist, axis=0)
        extra = (m == -1) & (best >= thr) & (best >= 1e-6)
        m = jnp.where(extra, best_row, m)
    col_dist = jnp.where(
        m >= 0,
        jnp.take_along_axis(dist, jnp.clip(m, 0, R - 1)[None, :],
                            axis=0)[0],
        0.0)
    return m, col_dist


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Greedy bipartite (+ optional per-prediction argmax) matching
    (reference detection.py bipartite_match / bipartite_match_op.cc).

    dist_matrix: [R, C] or batched [N, R, C] (the reference's LoD
    instances become a leading batch dim).  Returns
    (match_indices [.., C] int32 with -1 for unmatched,
    match_dist [.., C])."""
    def fn(d):
        if d.ndim == 2:
            return _bipartite_core(d, match_type, dist_threshold)
        return jax.vmap(
            lambda x: _bipartite_core(x, match_type, dist_threshold)
        )(d)
    return apply(fn, wrap(dist_matrix), op_name='bipartite_match')


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """Assign per-prior targets from per-instance rows (reference
    detection.py:1407 / target_assign_op.h).

    input: [N, G, K] per-instance rows (the reference's LoD rows,
    dense-padded); matched_indices: [N, P] int32 (-1 = unmatched).
    negative_indices: [N, Q] int32 padded with -1 (the reference's
    LoD negative list).  Returns (out [N, P, K], weight [N, P, 1])."""
    mv = 0.0 if mismatch_value is None else mismatch_value

    def fn(x, m, *neg):
        N, P = m.shape
        K = x.shape[-1]
        idx = jnp.clip(m, 0, x.shape[1] - 1)
        gathered = jnp.take_along_axis(
            x, idx[..., None].astype(jnp.int32).repeat(K, -1), axis=1)
        matched = (m >= 0)
        out = jnp.where(matched[..., None], gathered,
                        jnp.asarray(mv, x.dtype))
        w = matched.astype(jnp.float32)
        if neg:
            ni = neg[0]                                   # [N, Q]
            valid = ni >= 0
            one = jnp.zeros((N, P), jnp.float32)
            rows = jnp.broadcast_to(
                jnp.arange(N)[:, None], ni.shape)
            one = one.at[rows.reshape(-1),
                         jnp.clip(ni, 0, P - 1).reshape(-1)].max(
                             valid.reshape(-1).astype(jnp.float32))
            out = jnp.where((one > 0)[..., None],
                            jnp.asarray(mv, x.dtype), out)
            w = jnp.maximum(w, one)
        return out, w[..., None]

    args = [wrap(input), wrap(matched_indices)]
    if negative_indices is not None:
        args.append(wrap(negative_indices))
    return apply(fn, *args, op_name='target_assign')


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3,
                     nms_top_k=400, keep_top_k=200,
                     score_threshold=0.01, nms_eta=1.0,
                     return_index=False, name=None):
    """SSD postprocess: decode loc deltas against priors, softmax the
    class scores, then multiclass NMS (reference detection.py:621 —
    it applies nn.softmax(scores) before the NMS op, so thresholds
    compare against probabilities, not raw logits).  scores are
    [N, M, C] per-box class logits.  Returns the fixed-shape padded
    (out [N, keep_top_k, 6], nms_rois_num [N][, index])."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type='decode_center_size', axis=0)

    def tr(s):
        return jnp.transpose(jax.nn.softmax(s, axis=-1), (0, 2, 1))
    sc = apply(tr, wrap(scores), op_name='detection_output_softmax')
    return multiclass_nms(decoded, sc,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          normalized=True, nms_eta=nms_eta,
                          background_label=background_label,
                          return_index=return_index, name=name)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0,
             neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type='per_prediction',
             mining_type='max_negative', normalize=True,
             sample_size=None, name=None):
    """SSD multibox loss (reference detection.py:1513): match priors
    to ground truth (bipartite + per-prediction argmax), assign conf/
    loc targets, hard-negative-mine the confidence loss, smooth-L1 the
    matched locations.

    Dense redesign of the LoD contract: gt_box [N, G, 4] and gt_label
    [N, G] are PADDED per image — padding rows have all-zero boxes
    (zero IoU with everything, so they can never match).  location
    [N, P, 4], confidence [N, P, C], prior_box [P, 4].
    Returns the scalar weighted loss (normalize=True divides by the
    total matched count, like the reference)."""
    if mining_type != 'max_negative':
        raise NotImplementedError(
            'only max_negative mining is supported (the reference '
            'deprecated mining_type=hard_example)')
    var_list = None
    if prior_box_var is None:
        var_list = [1.0, 1.0, 1.0, 1.0]
    elif isinstance(prior_box_var, (list, tuple)):
        var_list = [float(v) for v in prior_box_var]

    def fn(locp, conf, gtb, gtl, prior, *maybe_var):
        N, P, C = conf.shape
        G = gtb.shape[1]
        pvar = (maybe_var[0] if maybe_var
                else jnp.asarray(var_list, locp.dtype))

        def one_image(lp, cf, gb, gl):
            iou = _iou_matrix(gb, prior)                  # [G, P]
            m, mdist = _bipartite_core(iou, match_type,
                                       overlap_threshold)
            matched = m >= 0                              # [P]
            gidx = jnp.clip(m, 0, G - 1)
            # conf target: matched -> gt label, else background
            tgt_lab = jnp.where(matched, gl[gidx],
                                background_label).astype(jnp.int32)
            logp = jax.nn.log_softmax(cf.astype(jnp.float32), -1)
            ce = -jnp.take_along_axis(
                logp, tgt_lab[:, None], axis=1)[:, 0]     # [P]
            # hard negative mining: negatives ranked by THEIR loss
            # (conf loss against background), top neg_pos_ratio*npos
            npos = jnp.sum(matched)
            nneg_cap = jnp.minimum(
                (neg_pos_ratio * npos).astype(jnp.int32),
                P - npos.astype(jnp.int32))
            if sample_size is not None:
                nneg_cap = jnp.minimum(nneg_cap, int(sample_size))
            neg_scores = jnp.where(matched, -jnp.inf, ce)
            order = jnp.argsort(-neg_scores)
            rank = jnp.zeros(P, jnp.int32).at[order].set(
                jnp.arange(P, dtype=jnp.int32))
            neg_sel = (~matched) & (rank < nneg_cap)
            conf_loss = jnp.sum(jnp.where(matched | neg_sel, ce, 0.0))
            # loc loss on matched priors: encode gt against priors
            pw = prior[:, 2] - prior[:, 0]
            ph = prior[:, 3] - prior[:, 1]
            pcx = prior[:, 0] + pw / 2
            pcy = prior[:, 1] + ph / 2
            g = gb[gidx]                                  # [P, 4]
            gw = g[:, 2] - g[:, 0]
            gh = g[:, 3] - g[:, 1]
            gcx = (g[:, 0] + g[:, 2]) / 2
            gcy = (g[:, 1] + g[:, 3]) / 2
            vx, vy, vw, vh = (pvar[..., 0], pvar[..., 1],
                              pvar[..., 2], pvar[..., 3])
            tx = (gcx - pcx) / pw / vx
            ty = (gcy - pcy) / ph / vy
            tw = jnp.log(jnp.maximum(gw / pw, 1e-10)) / vw
            th = jnp.log(jnp.maximum(gh / ph, 1e-10)) / vh
            tgt = jnp.stack([tx, ty, tw, th], -1)         # [P, 4]
            diff = jnp.abs(lp.astype(jnp.float32) - tgt)
            sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff,
                            diff - 0.5).sum(-1)
            loc_loss = jnp.sum(jnp.where(matched, sl1, 0.0))
            return conf_loss, loc_loss, npos

        cl, ll, np_ = jax.vmap(one_image)(locp, conf, gtb, gtl)
        total = (conf_loss_weight * jnp.sum(cl)
                 + loc_loss_weight * jnp.sum(ll))
        if normalize:
            total = total / jnp.maximum(
                jnp.sum(np_).astype(jnp.float32), 1.0)
        return total

    args = [wrap(location), wrap(confidence), wrap(gt_box),
            wrap(gt_label), wrap(prior_box)]
    if prior_box_var is not None and var_list is None:
        args.append(wrap(prior_box_var))
    return apply(fn, *args, op_name='ssd_loss')


def distribute_fpn_proposals(fpn_rois, min_level, max_level,
                             refer_level, refer_scale, rois_num=None,
                             pixel_offset=True, name=None):
    """Route RoIs to FPN levels by scale (reference detection.py:3673 /
    distribute_fpn_proposals_op.h):
    level = floor(log2(sqrt(area)/refer_scale + eps) + refer_level).

    fpn_rois: [R, 4].  Returns (multi_rois — one [R, 4] padded array
    per level, restore_ind [R, 1] int32 mapping each input roi to its
    slot in the PADDED concat(multi_rois) (level li's block starts at
    li*R — jit-usable, unlike offsets that depend on traced counts),
    rois_num_per_level — [num_levels] int32 counts).  Fixed [R, 4]
    per level instead of the reference's variable slices.  The
    reference's per-image rois_num split is not implemented — pass
    rois of ONE image at a time (or vmap)."""
    if rois_num is not None:
        raise NotImplementedError(
            'distribute_fpn_proposals: per-image rois_num splitting '
            'is not implemented — route each image separately (the '
            'fixed-shape outputs vmap cleanly)')
    levels = list(range(int(min_level), int(max_level) + 1))
    L = len(levels)

    def fn(rois):
        R = rois.shape[0]
        off = 1.0 if pixel_offset else 0.0
        area = ((rois[:, 2] - rois[:, 0] + off)
                * (rois[:, 3] - rois[:, 1] + off))
        scale = jnp.sqrt(jnp.maximum(area, 0.0))
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)
                        + refer_level)
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        multi, counts, orders = [], [], []
        for li, level in enumerate(levels):
            mine = lvl == level
            pos = jnp.where(mine, jnp.cumsum(mine) - 1, R)
            out = jnp.zeros((R, 4), rois.dtype).at[pos].set(
                rois, mode='drop')
            # original input index of each packed slot
            ordr = jnp.full((R,), -1, jnp.int32).at[pos].set(
                jnp.arange(R, dtype=jnp.int32), mode='drop')
            multi.append(out)
            orders.append(ordr)
            counts.append(jnp.sum(mine).astype(jnp.int32))
        counts = jnp.stack(counts)
        # restore_ind: original roi index -> its slot in the PADDED
        # concatenation (level li's block = [li*R, (li+1)*R)); static
        # offsets keep the mapping valid inside jit
        packed = jnp.concatenate(orders)              # [L*R]
        slot = jnp.arange(L * R, dtype=jnp.int32)
        # padding slots (packed == -1) scatter out of bounds and drop
        # (a clipped index would clobber roi 0's entry)
        idx = jnp.where(packed >= 0, packed, L * R)
        restore = jnp.zeros((R,), jnp.int32).at[idx].set(
            slot, mode='drop')
        return tuple(multi) + (restore[:, None], counts)

    return apply(fn, wrap(fpn_rois),
                 op_name='distribute_fpn_proposals')


def collect_fpn_proposals(multi_rois, multi_scores, min_level,
                          max_level, post_nms_top_n,
                          level_counts=None, rois_nums=None,
                          name=None):
    """Merge per-level RoIs back by score (reference
    collect_fpn_proposals_op.h): concat all levels, keep the top
    post_nms_top_n by score.  multi_rois: list of [Ri, 4];
    multi_scores: list of [Ri] (or [Ri, 1]).

    `level_counts` ([num_levels] int, e.g. distribute_fpn_proposals'
    rois_num_per_level) marks the VALID prefix of each padded level —
    padding rows are excluded from the top-k and from `num`.  Without
    it every row competes (pass exact-length arrays).  Returns
    (rois [K, 4], scores [K], num int32) padded fixed-shape.  The
    reference's per-image rois_nums split is not implemented."""
    if rois_nums is not None:
        raise NotImplementedError(
            'collect_fpn_proposals: per-image rois_nums splitting is '
            'not implemented — collect each image separately')

    def fn(*arrs):
        if level_counts is not None:
            L = (len(arrs) - 1) // 2
            counts = arrs[-1]
            arrs = arrs[:-1]
        else:
            L = len(arrs) // 2
            counts = None
        rois = jnp.concatenate(arrs[:L], axis=0)
        score_list = [a.reshape(-1) for a in arrs[L:]]
        if counts is not None:
            score_list = [
                jnp.where(jnp.arange(s.shape[0]) < counts[i],
                          s, -jnp.inf)
                for i, s in enumerate(score_list)]
        scores = jnp.concatenate(score_list, axis=0)
        K = min(int(post_nms_top_n), scores.shape[0])
        top_s, top_i = lax.top_k(scores, K)
        valid = jnp.isfinite(top_s)
        return (jnp.where(valid[:, None], rois[top_i], 0.0),
                jnp.where(valid, top_s, 0.0),
                jnp.sum(valid).astype(jnp.int32))

    args = [wrap(r) for r in multi_rois] + \
        [wrap(s) for s in multi_scores]
    if level_counts is not None:
        args.append(wrap(level_counts))
    return apply(fn, *args, op_name='collect_fpn_proposals')


# -- focal/matrix NMS + RCNN/RetinaNet target machinery (batch 3) --------

__all__ += ['sigmoid_focal_loss', 'matrix_nms', 'polygon_box_transform',
            'box_decoder_and_assign', 'rpn_target_assign',
            'generate_proposal_labels', 'retinanet_target_assign',
            'retinanet_detection_output']

_POLY_NON_GOALS = {
    'locality_aware_nms': 'polygon IoU merging (gpc.cc)',
    'roi_perspective_transform': 'quadrilateral perspective warps',
    'generate_mask_labels': 'polygon rasterization (mask_util.cc)',
}


def __getattr__(name):
    if name in _POLY_NON_GOALS:
        raise NotImplementedError(
            f'{name} is an explicit non-goal: it needs '
            f'{_POLY_NON_GOALS[name]}, polygon machinery with no '
            'axis-aligned-box equivalent. See SURVEY.md non-goals.')
    raise AttributeError(name)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25,
                       name=None):
    """Focal loss over per-class sigmoid scores (reference
    detection.py:474 / sigmoid_focal_loss_op.h): positives are class
    j == label-1 (labels are 1..C, 0 = background, -1 = ignored), and
    everything is scaled by 1/fg_num.  x: [N, C] logits; label:
    [N, 1] int; fg_num: [1] int.  Returns [N, C] losses
    (differentiable through jax.grad; the reference ships a
    hand-written backward)."""
    def fn(xv, lab, fg):
        N, C = xv.shape
        lab = lab.reshape(-1)
        j = jnp.arange(C)
        c_pos = (lab[:, None] == (j[None, :] + 1)).astype(jnp.float32)
        c_neg = ((lab[:, None] != -1).astype(jnp.float32)
                 * (1.0 - c_pos))
        fgn = jnp.maximum(fg.reshape(()), 1).astype(jnp.float32)
        p = jax.nn.sigmoid(xv.astype(jnp.float32))
        logp = jax.nn.log_sigmoid(xv.astype(jnp.float32))
        log1mp = jax.nn.log_sigmoid(-xv.astype(jnp.float32))
        term_pos = jnp.power(1.0 - p, gamma) * logp
        term_neg = jnp.power(p, gamma) * log1mp
        return (-c_pos * term_pos * (alpha / fgn)
                - c_neg * term_neg * ((1.0 - alpha) / fgn))
    return apply(fn, wrap(x), wrap(label), wrap(fg_num),
                 op_name='sigmoid_focal_loss')


def polygon_box_transform(input, name=None):
    """EAST geometry-map conversion (reference
    polygon_box_transform_op.cc): even channels hold x offsets, odd
    channels y offsets; output is the absolute coordinate
    4*cell - offset.  input: [N, G, H, W]."""
    def fn(v):
        N, G, H, W = v.shape
        xs = 4.0 * jnp.arange(W, dtype=v.dtype)[None, None, None, :]
        ys = 4.0 * jnp.arange(H, dtype=v.dtype)[None, None, :, None]
        even = (jnp.arange(G) % 2 == 0)[None, :, None, None]
        return jnp.where(even, xs - v, ys - v)
    return apply(fn, wrap(input), op_name='polygon_box_transform')


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0,
               normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference detection.py matrix_nms /
    matrix_nms_op.cc, SOLOv2): instead of a sequential suppression
    loop, every candidate's score decays by
    min_j decay(iou_ij, max_iou_j) over all higher-scored j — pure
    matrix math, embarrassingly TPU-parallel (the one NMS variant
    with NO loop at all).

    bboxes [N, M, 4], scores [N, C, M].  Returns (out [N, keep_top_k,
    6] rows (label, decayed_score, box) padded with label -1,
    rois_num [N][, index])."""
    def fn(bb, sc):
        _check_boxes4(bb, 'matrix_nms')
        N, C, M = sc.shape
        K = min(int(nms_top_k), M) if nms_top_k > 0 else M

        def one_class(b, s):
            s = jnp.where(s > score_threshold, s, -jnp.inf)
            top_s, top_i = lax.top_k(s, K)
            bt = b[top_i]
            iou = _iou_matrix(bt, bt, normalized=normalized)
            lower = jnp.tril(jnp.ones((K, K), bool), -1)  # j < i
            iou_l = jnp.where(lower, iou, 0.0)
            # iou_max[j] = max_{l<j} iou[j, l]
            iou_max = jnp.max(iou_l, axis=1)              # [K]
            if use_gaussian:
                decay = jnp.exp((iou_max[None, :] ** 2
                                 - iou_l ** 2) * gaussian_sigma)
            else:
                decay = (1.0 - iou_l) / (1.0 - iou_max[None, :])
            decay = jnp.where(lower, decay, 1.0)
            min_decay = jnp.min(decay, axis=1)            # [K]
            ds = min_decay * top_s
            ds = jnp.where(jnp.isfinite(top_s), ds, -jnp.inf)
            ds = jnp.where(ds > post_threshold, ds, -jnp.inf)
            return ds, top_i

        def one_image(b, s):
            ds, ti = jax.vmap(one_class, in_axes=(None, 0))(b, s)
            cls = jnp.broadcast_to(jnp.arange(C)[:, None], (C, K))
            if background_label >= 0:
                ds = jnp.where(cls == background_label, -jnp.inf, ds)
            flat = ds.reshape(-1)
            kk = min(int(keep_top_k), flat.shape[0]) \
                if keep_top_k > 0 else flat.shape[0]
            sel_s, sel = lax.top_k(flat, kk)
            valid = jnp.isfinite(sel_s)
            lab = jnp.where(valid, cls.reshape(-1)[sel], -1)
            bidx = ti.reshape(-1)[sel]
            out = jnp.concatenate([
                lab[:, None].astype(b.dtype),
                jnp.where(valid, sel_s, 0.0)[:, None],
                jnp.where(valid[:, None], b[bidx], 0.0)], axis=1)
            return (out, jnp.sum(valid).astype(jnp.int32),
                    jnp.where(valid, bidx, -1).astype(jnp.int32))

        out, num, idx = jax.vmap(one_image)(bb, sc)
        if return_index:
            base = (jnp.arange(out.shape[0]) * M)[:, None]
            idx = jnp.where(idx >= 0, idx + base, -1)
            return out, num, idx
        return out, num

    return apply(fn, wrap(bboxes), wrap(scores), op_name='matrix_nms')


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip=None, name=None):
    """Cascade-RCNN per-class decode + best-class assignment
    (reference box_decoder_and_assign_op.h): decode each class's
    deltas against the roi, then pick the box of the highest-scoring
    NON-background class.  prior_box [R, 4], prior_box_var [4],
    target_box [R, C*4], box_score [R, C].
    Returns (decode_box [R, C*4], assign_box [R, 4])."""
    clip = _BBOX_CLIP if box_clip is None else float(box_clip)

    def fn(p, pv, t, s):
        R = p.shape[0]
        C = s.shape[1]
        td = t.reshape(R, C, 4)
        pw = p[:, 2] - p[:, 0] + 1
        ph = p[:, 3] - p[:, 1] + 1
        pcx = p[:, 0] + pw / 2
        pcy = p[:, 1] + ph / 2
        dw = jnp.minimum(pv[2] * td[..., 2], clip)
        dh = jnp.minimum(pv[3] * td[..., 3], clip)
        cx = pv[0] * td[..., 0] * pw[:, None] + pcx[:, None]
        cy = pv[1] * td[..., 1] * ph[:, None] + pcy[:, None]
        w = jnp.exp(dw) * pw[:, None]
        h = jnp.exp(dh) * ph[:, None]
        dec = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1],
                        axis=-1)                         # [R, C, 4]
        # best non-background class (j > 0); rois whose best is the
        # background keep their ORIGINAL prior box (max_j == -1 path)
        s_fg = s.at[:, 0].set(-jnp.inf) if C > 0 else s
        best = jnp.argmax(s_fg, axis=1)
        has_fg = jnp.isfinite(jnp.max(s_fg, axis=1)) & (C > 1)
        assign = jnp.take_along_axis(
            dec, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
        assign = jnp.where(has_fg[:, None], assign, p[:, :4])
        return dec.reshape(R, C * 4), assign

    return apply(fn, wrap(prior_box), wrap(prior_box_var),
                 wrap(target_box), wrap(box_score),
                 op_name='box_decoder_and_assign')


def _anchor_gt_match(anchors, gt, pos_thr, neg_thr):
    """Shared RPN/RetinaNet matching: per-anchor max IoU + the
    per-gt-argmax force-match (reference rpn_target_assign semantics).
    Returns (labels [A] {1 fg, 0 bg, -1 ignore}, matched_gt [A])."""
    iou = _iou_matrix(gt, anchors)                 # [G, A]
    anchor_best = jnp.max(iou, axis=0)             # [A]
    anchor_arg = jnp.argmax(iou, axis=0)
    labels = jnp.full(anchors.shape[0], -1, jnp.int32)
    labels = jnp.where(anchor_best < neg_thr, 0, labels)
    labels = jnp.where(anchor_best >= pos_thr, 1, labels)
    # every gt's best anchor is positive even below the threshold
    gt_best_anchor = jnp.argmax(iou, axis=1)       # [G]
    gt_has_area = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
    labels = labels.at[gt_best_anchor].set(
        jnp.where(gt_has_area, 1, labels[gt_best_anchor]))
    return labels, anchor_arg


def _encode_against(anchors, g, weights=None):
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    gw = g[:, 2] - g[:, 0] + 1
    gh = g[:, 3] - g[:, 1] + 1
    gcx = (g[:, 0] + g[:, 2]) / 2
    gcy = (g[:, 1] + g[:, 3]) / 2
    t = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                   jnp.log(jnp.maximum(gw / aw, 1e-10)),
                   jnp.log(jnp.maximum(gh / ah, 1e-10))], axis=-1)
    if weights is not None:
        t = t / jnp.asarray(weights, t.dtype)
    return t


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True,
                      seed=None, name=None):
    """RPN training targets (reference detection.py:311 /
    rpn_target_assign_op.cc): label anchors fg/bg by IoU (plus the
    per-gt argmax force match), subsample to rpn_batch_size_per_im
    with at most rpn_fg_fraction foreground, and return the sampled
    predictions + targets.

    Dense single-image redesign (vmap for batches): bbox_pred [A, 4],
    cls_logits [A, 1], anchor_box [A, 4], gt_boxes [G, 4] (zero-area
    rows are padding).  Returns fixed-shape
    (pred_loc [S, 4], pred_score [S, 1], target_loc [S, 4],
    target_label [S, 1] int32 {1, 0, -1 padding},
    bbox_inside_weight [S, 4]) with S = rpn_batch_size_per_im; rows
    with label -1 are padding and carry zero weights.  Sampling uses
    jax PRNG from `seed` when use_random; seed=None draws a FRESH
    seed per eager call (the reference's per-step np.random) — inside
    a jit trace pass a distinct seed per step, or one permutation is
    baked in.  `is_crowd` ([G] int) excludes crowd gt from matching;
    with `im_info` ([3] h/w/scale), anchors straddling the image
    beyond rpn_straddle_thresh are ignored (label -1) — the
    reference's straddle filter."""
    S = int(rpn_batch_size_per_im)
    fg_cap = int(S * rpn_fg_fraction)
    if seed is None:
        from ..core import rng as _rng
        _SAMPLER_CALLS[0] += 1
        seed = _rng.get_seed() + 0x5bd1 * _SAMPLER_CALLS[0]

    has_crowd = is_crowd is not None
    has_im = im_info is not None

    def fn(bp, cl, anc, gtb, *extra):
        A = anc.shape[0]
        crowd = extra[0] if has_crowd else None
        im = extra[1 if has_crowd else 0] if has_im else None
        if crowd is not None:
            # crowd gt rows zero out -> zero area -> never match
            gtb = jnp.where((crowd.reshape(-1) != 0)[:, None],
                            0.0, gtb)
        labels, arg = _anchor_gt_match(anc, gtb,
                                       rpn_positive_overlap,
                                       rpn_negative_overlap)
        if im is not None:
            t = rpn_straddle_thresh
            inside = ((anc[:, 0] >= -t) & (anc[:, 1] >= -t)
                      & (anc[:, 2] < im[1] + t)
                      & (anc[:, 3] < im[0] + t))
            labels = jnp.where(inside, labels, -1)
        key = jax.random.PRNGKey(seed)
        kf, kb = jax.random.split(key)
        # random priority within each pool, top-k to sample
        def pick(mask, k, prio_key):
            prio = jax.random.uniform(prio_key, (A,)) if use_random \
                else -jnp.arange(A, dtype=jnp.float32)
            prio = jnp.where(mask, prio, -jnp.inf)
            _, idx = lax.top_k(prio, min(k, A))
            ok = jnp.take(mask, idx)
            return idx, ok

        fg_idx, fg_ok = pick(labels == 1, fg_cap, kf)
        n_fg = jnp.sum(fg_ok)
        bg_idx, bg_ok0 = pick(labels == 0, S, kb)
        # backgrounds fill the remaining S - n_fg slots
        bg_ok = bg_ok0 & (jnp.cumsum(bg_ok0) <= S - n_fg)
        idx = jnp.concatenate([fg_idx, bg_idx])
        ok = jnp.concatenate([fg_ok, bg_ok])
        # compact the selected rows into S slots
        pos = jnp.where(ok, jnp.cumsum(ok) - 1, S)
        slot_src = jnp.full((S,), A, jnp.int32).at[pos].set(
            idx.astype(jnp.int32), mode='drop')
        valid = slot_src < A
        src = jnp.clip(slot_src, 0, A - 1)
        lab = jnp.where(valid, jnp.take(labels, src), -1)
        g = gtb[jnp.take(arg, src)]
        tloc = _encode_against(anc[src], g)
        inside = ((lab == 1).astype(jnp.float32))[:, None] \
            * jnp.ones((1, 4), jnp.float32)
        return (jnp.where(valid[:, None], bp[src], 0.0),
                jnp.where(valid[:, None], cl[src], 0.0),
                jnp.where((lab == 1)[:, None], tloc, 0.0),
                lab[:, None],
                inside)

    args = [wrap(bbox_pred), wrap(cls_logits), wrap(anchor_box),
            wrap(gt_boxes)]
    if is_crowd is not None:
        args.append(wrap(is_crowd))
    if im_info is not None:
        args.append(wrap(im_info))
    return apply(fn, *args, op_name='rpn_target_assign')


_SAMPLER_CALLS = [0]


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, seed=None,
                             name=None):
    """Sample RoIs + build RCNN head targets (reference
    detection.py:2596 / generate_proposal_labels_op.cc): gt boxes
    join the proposal pool, fg = max IoU >= fg_thresh (sampled to
    fg_fraction), bg = IoU in [bg_thresh_lo, bg_thresh_hi), targets
    encoded with bbox_reg_weights into per-class slots.

    Dense single-image redesign (vmap for batches): rpn_rois [R, 4],
    gt_classes [G], gt_boxes [G, 4] (zero-area rows padding).
    Returns fixed-shape (rois [S, 4], labels [S] int32 (0 =
    background, -1 = padding), bbox_targets [S, 4*class_nums],
    inside_weights, outside_weights same shape) with
    S = batch_size_per_im.  seed=None draws a fresh seed per eager
    call; `is_crowd` rows are excluded from matching AND the pool."""
    if class_nums is None:
        raise ValueError('class_nums is required')
    S = int(batch_size_per_im)
    fg_cap = int(S * fg_fraction)
    C = int(class_nums)
    if seed is None:
        from ..core import rng as _rng
        _SAMPLER_CALLS[0] += 1
        seed = _rng.get_seed() + 0x5bd1 * _SAMPLER_CALLS[0]
    has_crowd = is_crowd is not None

    def fn(rois, gcls, gtb, *extra):
        if has_crowd:
            gtb = jnp.where((extra[0].reshape(-1) != 0)[:, None],
                            0.0, gtb)
        pool = jnp.concatenate([rois, gtb], axis=0)   # gt join pool
        P = pool.shape[0]
        # padding / crowd gt rows (zero area) must not enter the
        # sample as degenerate background RoIs
        gt_valid = (gtb[:, 2] > gtb[:, 0]) & (gtb[:, 3] > gtb[:, 1])
        pool_valid = jnp.concatenate(
            [jnp.ones(rois.shape[0], bool), gt_valid])
        iou = _iou_matrix(gtb, pool)                  # [G, P]
        iou = jnp.where(gt_valid[:, None], iou, 0.0)
        best = jnp.max(iou, axis=0)
        arg = jnp.argmax(iou, axis=0)
        fg_mask = (best >= fg_thresh) & pool_valid
        bg_mask = ((best < bg_thresh_hi) & (best >= bg_thresh_lo)
                   & pool_valid)
        key = jax.random.PRNGKey(seed)
        kf, kb = jax.random.split(key)

        def pick(mask, k, prio_key):
            prio = jax.random.uniform(prio_key, (P,)) if use_random \
                else -jnp.arange(P, dtype=jnp.float32)
            prio = jnp.where(mask, prio, -jnp.inf)
            _, idx = lax.top_k(prio, k)
            return idx, jnp.take(mask, idx)

        fg_idx, fg_ok = pick(fg_mask, min(fg_cap, P), kf)
        n_fg = jnp.sum(fg_ok)
        bg_idx, bg_ok0 = pick(bg_mask, min(S, P), kb)
        bg_ok = bg_ok0 & (jnp.cumsum(bg_ok0) <= S - n_fg)
        idx = jnp.concatenate([fg_idx, bg_idx])
        ok = jnp.concatenate([fg_ok, bg_ok])
        pos = jnp.where(ok, jnp.cumsum(ok) - 1, S)
        slot_src = jnp.full((S,), P, jnp.int32).at[pos].set(
            idx.astype(jnp.int32), mode='drop')
        valid = slot_src < P
        src = jnp.clip(slot_src, 0, P - 1)
        out_rois = jnp.where(valid[:, None], pool[src], 0.0)
        is_fg = valid & jnp.take(fg_mask, src)
        lab = jnp.where(is_fg, gcls[jnp.take(arg, src)], 0)
        lab = jnp.where(valid, lab, -1).astype(jnp.int32)
        t = _encode_against(pool[src], gtb[jnp.take(arg, src)],
                            bbox_reg_weights)
        cls_slot = jnp.where(is_cls_agnostic, 1,
                             jnp.clip(lab, 0, C - 1))
        onehot = jax.nn.one_hot(cls_slot, C,
                                dtype=t.dtype) \
            * is_fg[:, None].astype(t.dtype)          # [S, C]
        targets = (onehot[:, :, None] * t[:, None, :]).reshape(S,
                                                               C * 4)
        inside = jnp.repeat(onehot, 4, axis=1)
        return out_rois, lab, targets, inside, inside

    args = [wrap(rpn_rois), wrap(gt_classes), wrap(gt_boxes)]
    if is_crowd is not None:
        args.append(wrap(is_crowd))
    return apply(fn, *args, op_name='generate_proposal_labels')


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box,
                            anchor_var, gt_boxes, gt_labels,
                            is_crowd=None, im_info=None,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4, name=None):
    """RetinaNet training targets (reference detection.py:108):
    like rpn_target_assign but NO subsampling (focal loss handles the
    imbalance) and class targets are the matched gt labels.

    Dense single-image redesign: returns (pred_loc [A, 4],
    pred_cls [A, num_classes], target_loc [A, 4], target_label
    [A, 1] int32 {1..C fg, 0 bg, -1 ignore}, bbox_inside_weight
    [A, 4], fg_num [1] int32)."""
    def fn(bp, cl, anc, gtb, gtl):
        labels01, arg = _anchor_gt_match(anc, gtb, positive_overlap,
                                         negative_overlap)
        fg = labels01 == 1
        lab = jnp.where(fg, gtl[arg].astype(jnp.int32),
                        labels01)
        tloc = _encode_against(anc, gtb[arg])
        inside = fg.astype(jnp.float32)[:, None] * jnp.ones(
            (1, 4), jnp.float32)
        fg_num = (jnp.sum(fg) + 1).astype(jnp.int32)[None]
        return (bp, cl, jnp.where(fg[:, None], tloc, 0.0),
                lab[:, None], inside, fg_num)

    return apply(fn, wrap(bbox_pred), wrap(cls_logits),
                 wrap(anchor_box), wrap(gt_boxes), wrap(gt_labels),
                 op_name='retinanet_target_assign')


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.45,
                               nms_eta=1.0, name=None):
    """RetinaNet postprocess (reference detection.py:191): per FPN
    level, take top nms_top_k anchor predictions by sigmoid score,
    decode against that level's anchors, then one multiclass NMS over
    the union.  bboxes/scores/anchors: lists per level
    ([A_l, 4] deltas, [A_l, C] logits, [A_l, 4] anchors) for ONE
    image (vmap for batches).  Returns (out [keep_top_k, 6],
    num int32)."""
    L = len(bboxes)

    def fn(info, *arrs):
        bs = arrs[:L]
        ss = arrs[L:2 * L]
        ans = arrs[2 * L:]
        dec_all, sc_all = [], []
        for b, s, a in zip(bs, ss, ans):
            p = jax.nn.sigmoid(s.astype(jnp.float32))   # [A, C]
            best = jnp.max(p, axis=1)
            k = min(int(nms_top_k), b.shape[0])
            _, ti = lax.top_k(best, k)
            aw = a[ti, 2] - a[ti, 0] + 1
            ah = a[ti, 3] - a[ti, 1] + 1
            acx = a[ti, 0] + aw / 2
            acy = a[ti, 1] + ah / 2
            d = b[ti]
            cx = d[:, 0] * aw + acx
            cy = d[:, 1] * ah + acy
            w = jnp.exp(jnp.minimum(d[:, 2], _BBOX_CLIP)) * aw
            h = jnp.exp(jnp.minimum(d[:, 3], _BBOX_CLIP)) * ah
            box = jnp.stack([cx - w / 2, cy - h / 2,
                             cx + w / 2 - 1, cy + h / 2 - 1], -1)
            # the reference rescales predictions back to the ORIGINAL
            # image frame (pred / im_scale) and clips against
            # round(resized_dim / im_scale) - 1
            box = box / info[2]
            imh = jnp.round(info[0] / info[2])
            imw = jnp.round(info[1] / info[2])
            box = jnp.stack([
                jnp.clip(box[:, 0], 0, imw - 1),
                jnp.clip(box[:, 1], 0, imh - 1),
                jnp.clip(box[:, 2], 0, imw - 1),
                jnp.clip(box[:, 3], 0, imh - 1)], -1)
            dec_all.append(box)
            sc_all.append(p[ti])
        boxes = jnp.concatenate(dec_all, axis=0)[None]   # [1, M, 4]
        probs = jnp.transpose(
            jnp.concatenate(sc_all, axis=0))[None]       # [1, C, M]
        # un-normalized (+1 pixel) IoU like the reference's
        # JaccardOverlap(..., false) — normalized=True would give
        # 1-pixel boxes zero area and never suppress duplicates
        out, num, _ = _mcnms_core(boxes, probs, score_threshold,
                                  -1, keep_top_k, nms_threshold,
                                  False, nms_eta, -1)
        # reference labels are 1..C (0 is background in the head's
        # label space); our classes are already foreground-only
        return out[0], num[0]

    args = [wrap(im_info)] + [wrap(b) for b in bboxes] \
        + [wrap(s) for s in scores] + [wrap(a) for a in anchors]
    return apply(fn, *args, op_name='retinanet_detection_output')
