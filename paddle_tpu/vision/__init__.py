"""paddle_tpu.vision (reference: python/paddle/vision)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401

from .models import *  # noqa: F401,F403
from .models import __all__ as _models_all

from . import image  # noqa: F401
from .image import set_image_backend, get_image_backend, image_load  # noqa: F401

__all__ = ['datasets', 'models', 'transforms', 'ops', 'image',
           'set_image_backend', 'get_image_backend', 'image_load'] \
    + list(_models_all)
