"""paddle_tpu.vision (reference: python/paddle/vision)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401

from .models import *  # noqa: F401,F403
from .models import __all__ as _models_all

__all__ = ['datasets', 'models', 'transforms', 'ops'] + list(_models_all)


def set_image_backend(backend):
    if backend not in ('pil', 'cv2', 'numpy'):
        raise ValueError('unsupported backend: {}'.format(backend))
    global _image_backend
    _image_backend = backend


def get_image_backend():
    return _image_backend


_image_backend = 'numpy'
