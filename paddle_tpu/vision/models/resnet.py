"""ResNet family (18/34/50/101/152).

Reference analogue: python/paddle/vision/models/resnet.py:151 (class ResNet,
BasicBlock, BottleneckBlock, resnet18..resnet152).  Same public API; the
implementation is TPU-first:

- ``data_format='NHWC'`` runs the whole network channels-last, the layout
  the TPU conv units prefer, with no per-layer transposes (the reference is
  NCHW-only because cuDNN prefers it).
- the forward is pure w.r.t. parameters, so paddle_tpu.jit compiles the
  full model (+loss+grad) into one XLA module; XLA fuses BN+ReLU into the
  conv epilogues.
"""
import numpy as np

from ... import nn
from ...tensor.manipulation import flatten, reshape, transpose

__all__ = ['ResNet', 'resnet18', 'resnet34', 'resnet50', 'resnet101',
           'resnet152', 'space_to_depth_stem_weight']


def _space_to_depth2(x):
    """NHWC block-2 space-to-depth: [B,H,W,C] → [B,H/2,W/2,4C] with
    channel order (u, v, c) — the MLPerf-TPU ResNet input transform.
    The 7x7/s2 stem conv reads each input pixel from HBM under a
    49-tap window at stride 2; on the s2d layout the same math is a
    4x4/s1 conv over 4x fewer, 4x-wider pixels, which the TPU conv
    unit tiles far better (no halo re-reads across the stride)."""
    B, H, W, C = x.shape
    if H % 2 or W % 2:
        raise ValueError(
            f'stem_space_to_depth needs even spatial dims, got {H}x{W}'
            ' — pad or resize the input (the standard stem has no such'
            ' constraint)')
    x = reshape(x, [B, H // 2, 2, W // 2, 2, C])
    x = transpose(x, [0, 1, 3, 2, 4, 5])
    return reshape(x, [B, H // 2, W // 2, 4 * C])


def space_to_depth_stem_weight(w7):
    """EXACT re-lay of a standard [O,3,7,7] OIHW stem-conv weight into
    the [O,12,4,4] weight of the s2d stem (stride 1, padding
    ((2,1),(2,1))): output tap di of the 7x7/s2/pad-3 conv maps to
    (k, u) of the 4x4 conv via di = 2k + u - 1 (the (k=0,u=0) slot
    falls outside the 7-tap window and stays zero).  Used by the
    parity test and for loading pretrained 7x7 stems into s2d
    models."""
    w7 = np.asarray(w7)
    O, C = w7.shape[0], w7.shape[1]
    w2 = np.zeros((O, 4 * C, 4, 4), w7.dtype)
    for k in range(4):
        for u in range(2):
            di = 2 * k + u - 1
            if not 0 <= di < 7:
                continue
            for l in range(4):
                for v in range(2):
                    dj = 2 * l + v - 1
                    if not 0 <= dj < 7:
                        continue
                    for c in range(C):
                        w2[:, (u * 2 + v) * C + c, k, l] = w7[:, c, di, dj]
    return w2


def _conv_bn(in_ch, out_ch, kernel, stride, padding, data_format,
             groups=1, dilation=1):
    return (nn.Conv2D(in_ch, out_ch, kernel, stride=stride, padding=padding,
                      groups=groups, dilation=dilation, bias_attr=False,
                      data_format=data_format),
            nn.BatchNorm2D(out_ch, data_format=data_format))


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format='NCHW'):
        super().__init__()
        if groups != 1 or base_width != 64:
            raise ValueError('BasicBlock only supports groups=1, width=64')
        self.conv1, self.bn1 = _conv_bn(inplanes, planes, 3, stride, 1,
                                        data_format)
        self.conv2, self.bn2 = _conv_bn(planes, planes, 3, 1, 1, data_format)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format='NCHW'):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1, self.bn1 = _conv_bn(inplanes, width, 1, 1, 0, data_format)
        self.conv2, self.bn2 = _conv_bn(width, width, 3, stride, dilation,
                                        data_format, groups=groups,
                                        dilation=dilation)
        self.conv3, self.bn3 = _conv_bn(width, planes * self.expansion,
                                        1, 1, 0, data_format)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ResNet backbone + classifier.

    Args:
        block: BasicBlock or BottleneckBlock.
        depth: one of 18/34/50/101/152.
        num_classes: head size; <= 0 disables the head.
        with_pool: global-average-pool before the head.
        data_format: 'NCHW' (reference-compatible) or 'NHWC' (TPU-native).
    """

    _layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                  101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}

    def __init__(self, block, depth, num_classes=1000, with_pool=True,
                 data_format='NCHW', stem_space_to_depth=False):
        super().__init__()
        layers = self._layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = data_format
        self.stem_space_to_depth = stem_space_to_depth
        self.inplanes = 64

        if stem_space_to_depth:
            # MLPerf-TPU stem: s2d(2) input + 4x4/s1 conv — the same
            # function as 7x7/s2/pad-3 (see space_to_depth_stem_weight)
            if data_format != 'NHWC':
                raise ValueError('stem_space_to_depth is the TPU-layout '
                                 'stem; use data_format="NHWC"')
            self.conv1 = nn.Conv2D(12, 64, 4, stride=1,
                                   padding=[(2, 1), (2, 1)],
                                   bias_attr=False,
                                   data_format=data_format)
        else:
            self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3,
                                   bias_attr=False,
                                   data_format=data_format)
        self.bn1 = nn.BatchNorm2D(64, data_format=data_format)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1,
                                    data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0], 1)
        self.layer2 = self._make_layer(block, 128, layers[1], 2)
        self.layer3 = self._make_layer(block, 256, layers[2], 2)
        self.layer4 = self._make_layer(block, 512, layers[3], 2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1),
                                                data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, n_blocks, stride):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            conv, bn = _conv_bn(self.inplanes, planes * block.expansion,
                                1, stride, 0, self.data_format)
            downsample = nn.Sequential(conv, bn)
        blocks = [block(self.inplanes, planes, stride, downsample,
                        data_format=self.data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, n_blocks):
            blocks.append(block(self.inplanes, planes,
                                data_format=self.data_format))
        return nn.Sequential(*blocks)

    def forward(self, x):
        if self.stem_space_to_depth:
            x = _space_to_depth2(x)
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(block, depth, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError(
            'pretrained weights are unavailable in this zero-egress build; '
            'load a checkpoint with paddle_tpu.load + set_state_dict')
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)
