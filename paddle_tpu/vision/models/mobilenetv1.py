"""MobileNetV1 (depthwise-separable convolutions).

Reference analogue: python/paddle/vision/models/mobilenetv1.py:84
(class MobileNetV1, mobilenet_v1).  Same API.  Depthwise convs lower to
XLA ``conv_general_dilated`` with feature_group_count — TPU handles these
natively, no im2col.
"""
from ... import nn
from ...tensor.manipulation import flatten

__all__ = ['MobileNetV1', 'mobilenet_v1']


class _ConvBNReLU(nn.Layer):
    def __init__(self, in_ch, out_ch, kernel, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, kernel, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_ch, mid_ch, out_ch, stride, scale):
        super().__init__()
        mid = int(mid_ch * scale)
        self.depthwise = _ConvBNReLU(int(in_ch * scale), mid, 3,
                                     stride=stride, padding=1, groups=mid)
        self.pointwise = _ConvBNReLU(mid, int(out_ch * scale), 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


# (in, mid, out, stride) per depthwise-separable stage
_STAGES = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
           (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
           (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
           (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
           (1024, 1024, 1024, 1)]


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        blocks = [_ConvBNReLU(3, int(32 * scale), 3, stride=2, padding=1)]
        for in_ch, mid_ch, out_ch, stride in _STAGES:
            blocks.append(
                _DepthwiseSeparable(in_ch, mid_ch, out_ch, stride, scale))
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            'pretrained weights unavailable in this zero-egress build')
    return MobileNetV1(scale=scale, **kwargs)
