"""Vision model zoo (reference: python/paddle/vision/models/__init__.py)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34, resnet50,
    resnet101, resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19, make_layers  # noqa: F401
from .mobilenetv1 import MobileNetV1, mobilenet_v1  # noqa: F401
from .mobilenetv2 import MobileNetV2, mobilenet_v2, InvertedResidual  # noqa: F401

__all__ = ['LeNet', 'ResNet', 'BasicBlock', 'BottleneckBlock',
           'resnet18', 'resnet34', 'resnet50', 'resnet101', 'resnet152',
           'VGG', 'vgg11', 'vgg13', 'vgg16', 'vgg19', 'make_layers',
           'MobileNetV1', 'mobilenet_v1', 'MobileNetV2', 'mobilenet_v2',
           'InvertedResidual']
