"""MobileNetV2 (inverted residuals, linear bottlenecks).

Reference analogue: python/paddle/vision/models/mobilenetv2.py:104
(class MobileNetV2, mobilenet_v2).  Same API.
"""
from ... import nn
from ...tensor.manipulation import flatten

__all__ = ['MobileNetV2', 'mobilenet_v2']


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU6(nn.Layer):
    def __init__(self, in_ch, out_ch, kernel=3, stride=1, groups=1):
        super().__init__()
        pad = (kernel - 1) // 2
        self.conv = nn.Conv2D(in_ch, out_ch, kernel, stride=stride,
                              padding=pad, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.act = nn.ReLU6()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU6(inp, hidden, kernel=1))
        layers.append(_ConvBNReLU6(hidden, hidden, stride=stride,
                                   groups=hidden))
        layers.append(nn.Conv2D(hidden, oup, 1, bias_attr=False))
        layers.append(nn.BatchNorm2D(oup))
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


# (expand_ratio t, out-channels c, repeats n, stride s)
_CFG = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_ch = _make_divisible(32 * scale)
        last_ch = _make_divisible(1280 * max(1.0, scale))
        blocks = [_ConvBNReLU6(3, in_ch, stride=2)]
        for t, c, n, s in _CFG:
            out_ch = _make_divisible(c * scale)
            for i in range(n):
                blocks.append(InvertedResidual(
                    in_ch, out_ch, s if i == 0 else 1, t))
                in_ch = out_ch
        blocks.append(_ConvBNReLU6(in_ch, last_ch, kernel=1))
        self.features = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            'pretrained weights unavailable in this zero-egress build')
    return MobileNetV2(scale=scale, **kwargs)
