"""LeNet for MNIST-shaped inputs.

Reference analogue: python/paddle/vision/models/lenet.py:21 (class LeNet).
Same constructor/API; implementation is our Layer/functional stack, so the
whole forward traces into one XLA module under paddle_tpu.jit.
"""
from ... import nn
from ...tensor.manipulation import flatten

__all__ = ['LeNet']


class LeNet(nn.Layer):
    """LeNet-5 style conv net.

    Args:
        num_classes: size of the classifier head; <= 0 disables the head
            and the features are returned flat.
    """

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120),
                nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            x = flatten(x, 1)
            x = self.fc(x)
        return x
