"""paddle.vision.ops — detection ops.

Reference analogue: /root/reference/python/paddle/vision/ops.py
(yolo_loss:31, yolo_box:242, deform_conv2d:397, DeformConv2D:731,
read_file:790, decode_jpeg:835) — there each is a C++/CUDA op
(yolov3_loss_op.h, yolo_box_op.h, deformable_conv_op.cu).

TPU-native: every op is a batched jnp computation — the YOLO grid
decode/target assignment vectorizes over [N, S, H, W] with no scalar
loops (the CUDA kernels' per-thread body becomes array ops XLA tiles
onto the VPU/MXU), and deformable conv is 4 static gathers per kernel
tap + one einsum (see static/nn.py analogue).
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..tensor._helpers import wrap
from ..nn.layer.layers import Layer
from ..nn import initializer as I

__all__ = ['yolo_loss', 'yolo_box', 'deform_conv2d', 'DeformConv2D',
           'read_file', 'decode_jpeg']


def _sce(logit, target):
    """Sigmoid cross entropy (the reference op's SCE helper)."""
    return jnp.maximum(logit, 0.) - logit * target \
        + jnp.log1p(jnp.exp(-jnp.abs(logit)))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLOv3 head output into boxes+scores (reference
    vision/ops.py:242 / yolo_box_op.h).

    x: [N, S*(5+C), H, W]; img_size: [N, 2] (h, w).
    Returns (boxes [N, H*W*S, 4] xyxy in image pixels,
             scores [N, H*W*S, C]).
    """
    S = len(anchors) // 2
    C = int(class_num)
    anc = np.asarray(anchors, np.float32).reshape(S, 2)  # (w, h)

    def fn(xv, imgs):
        N, _, H, W = xv.shape
        p = xv.reshape(N, S, 5 + C, H, W)
        tx, ty = p[:, :, 0], p[:, :, 1]
        tw, th = p[:, :, 2], p[:, :, 3]
        conf = jax.nn.sigmoid(p[:, :, 4])                # [N,S,H,W]
        cls = jax.nn.sigmoid(p[:, :, 5:])                # [N,S,C,H,W]

        gx = jnp.arange(W, dtype=xv.dtype)[None, None, None, :]
        gy = jnp.arange(H, dtype=xv.dtype)[None, None, :, None]
        bias = -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(tx) * scale_x_y + bias + gx) / W
        cy = (jax.nn.sigmoid(ty) * scale_x_y + bias + gy) / H
        in_w = downsample_ratio * W
        in_h = downsample_ratio * H
        aw = anc[:, 0][None, :, None, None]
        ah = anc[:, 1][None, :, None, None]
        bw = jnp.exp(tw) * aw / in_w
        bh = jnp.exp(th) * ah / in_h

        img_h = imgs[:, 0].astype(xv.dtype)[:, None, None, None]
        img_w = imgs[:, 1].astype(xv.dtype)[:, None, None, None]
        x0 = (cx - bw / 2.) * img_w
        y0 = (cy - bh / 2.) * img_h
        x1 = (cx + bw / 2.) * img_w
        y1 = (cy + bh / 2.) * img_h
        if clip_bbox:
            x0 = jnp.clip(x0, 0., img_w - 1.)
            y0 = jnp.clip(y0, 0., img_h - 1.)
            x1 = jnp.clip(x1, 0., img_w - 1.)
            y1 = jnp.clip(y1, 0., img_h - 1.)
        keep = (conf >= conf_thresh).astype(xv.dtype)    # [N,S,H,W]
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1) \
            * keep[..., None]                            # [N,S,H,W,4]
        scores = cls.transpose(0, 1, 3, 4, 2) \
            * (conf * keep)[..., None]                   # [N,S,H,W,C]
        # reference layout: rows ordered (s, h, w)
        return (boxes.reshape(N, S * H * W, 4),
                scores.reshape(N, S * H * W, C))

    return apply(fn, wrap(x), wrap(img_size), op_name='yolo_box')


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference vision/ops.py:31 /
    yolov3_loss_op.h), fully vectorized:

      * each gt box matches its best ANCHOR by wh-IoU; if that anchor
        is in this head's anchor_mask, the gt's grid cell becomes a
        positive: SCE on (x, y), L1 on (w, h) — both scaled by
        2 - gw*gh — SCE objectness target 1, smoothed one-hot classes;
      * predictions whose best IoU over the image's gt boxes exceeds
        ignore_thresh are excluded from the negative objectness term.

    x: [N, S*(5+C), H, W]; gt_box: [N, B, 4] (cx, cy, w, h in [0, 1]);
    gt_label: [N, B] int; gt_score: [N, B] mixup weights.
    Returns loss [N].
    """
    full = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    S = len(mask)
    C = int(class_num)
    masked = full[mask]                                  # [S, 2]
    smooth_pos = 1.0 - 1.0 / C if use_label_smooth and C > 1 else 1.0
    smooth_neg = 1.0 / C if use_label_smooth and C > 1 else 0.0

    ins = [wrap(x), wrap(gt_box), wrap(gt_label)]
    if gt_score is not None:
        ins.append(wrap(gt_score))

    def fn(xv, gb, gl, *gs):
        N, _, H, W = xv.shape
        B = gb.shape[1]
        in_w = float(downsample_ratio * W)
        in_h = float(downsample_ratio * H)
        p = xv.reshape(N, S, 5 + C, H, W)
        px, py = p[:, :, 0], p[:, :, 1]
        pw, ph = p[:, :, 2], p[:, :, 3]
        pobj = p[:, :, 4]
        pcls = p[:, :, 5:]                               # [N,S,C,H,W]
        score = gs[0].astype(xv.dtype) if gs \
            else jnp.ones((N, B), xv.dtype)

        valid = (gb[:, :, 2] > 0.) & (gb[:, :, 3] > 0.)  # [N,B]

        # ---- best anchor per gt: IoU of (w, h) at common origin -----
        gw_pix = gb[:, :, 2] * in_w                      # [N,B]
        gh_pix = gb[:, :, 3] * in_h
        aw = full[:, 0][None, None, :]
        ah = full[:, 1][None, None, :]
        inter = jnp.minimum(gw_pix[..., None], aw) \
            * jnp.minimum(gh_pix[..., None], ah)
        union = gw_pix[..., None] * gh_pix[..., None] + aw * ah - inter
        an_iou = inter / jnp.maximum(union, 1e-9)        # [N,B,A]
        best = jnp.argmax(an_iou, axis=-1)               # [N,B]
        mask_arr = jnp.asarray(mask)
        in_head = (best[..., None] == mask_arr[None, None]).any(-1)
        slot = jnp.argmax(
            best[..., None] == mask_arr[None, None], -1)  # [N,B]
        pos = valid & in_head

        gi = jnp.clip((gb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)

        # per-gt regression targets
        tx = gb[:, :, 0] * W - gi                        # [N,B]
        ty = gb[:, :, 1] * H - gj
        best_aw = jnp.asarray(full[:, 0])[best]          # [N,B]
        best_ah = jnp.asarray(full[:, 1])[best]
        tw = jnp.log(jnp.maximum(gw_pix, 1e-9)
                     / jnp.maximum(best_aw, 1e-9))
        th = jnp.log(jnp.maximum(gh_pix, 1e-9)
                     / jnp.maximum(best_ah, 1e-9))
        box_w = 2.0 - gb[:, :, 2] * gb[:, :, 3]          # [N,B]

        # gather this head's predictions at each gt's cell
        bidx = jnp.arange(N)[:, None]
        sel = (bidx, slot, gj, gi)
        px_g = px[sel]                                   # [N,B]
        py_g = py[sel]
        pw_g = pw[sel]
        ph_g = ph[sel]
        pobj_g = pobj[sel]
        pcls_g = pcls[bidx, slot, :, gj, gi]             # [N,B,C]

        wpos = pos.astype(xv.dtype) * score
        loss_xy = (_sce(px_g, tx) + _sce(py_g, ty)) * box_w * wpos
        loss_wh = (jnp.abs(pw_g - tw) + jnp.abs(ph_g - th)) \
            * box_w * wpos
        onehot = jax.nn.one_hot(gl.astype(jnp.int32), C,
                                dtype=xv.dtype)
        target_cls = onehot * smooth_pos + (1 - onehot) * smooth_neg
        loss_cls = _sce(pcls_g, target_cls).sum(-1) * wpos
        loss_obj_pos = _sce(pobj_g, jnp.ones_like(pobj_g)) * wpos

        # ---- negative objectness with ignore region ------------------
        # decoded predictions [N,S,H,W,4] (normalized xywh)
        gx = jnp.arange(W, dtype=xv.dtype)[None, None, None, :]
        gy = jnp.arange(H, dtype=xv.dtype)[None, None, :, None]
        bias = -0.5 * (scale_x_y - 1.0)
        cx = (jax.nn.sigmoid(px) * scale_x_y + bias + gx) / W
        cy = (jax.nn.sigmoid(py) * scale_x_y + bias + gy) / H
        bw = jnp.exp(pw) * masked[:, 0][None, :, None, None] / in_w
        bh = jnp.exp(ph) * masked[:, 1][None, :, None, None] / in_h
        # IoU of each prediction with each gt (xywh, normalized)
        p0x, p0y = cx - bw / 2, cy - bh / 2
        p1x, p1y = cx + bw / 2, cy + bh / 2
        g0x = (gb[:, :, 0] - gb[:, :, 2] / 2)
        g0y = (gb[:, :, 1] - gb[:, :, 3] / 2)
        g1x = (gb[:, :, 0] + gb[:, :, 2] / 2)
        g1y = (gb[:, :, 1] + gb[:, :, 3] / 2)

        def exp_pred(t):  # [N,S,H,W] -> [N,S,H,W,1]
            return t[..., None]

        def exp_gt(t):    # [N,B] -> [N,1,1,1,B]
            return t[:, None, None, None, :]

        ix0 = jnp.maximum(exp_pred(p0x), exp_gt(g0x))
        iy0 = jnp.maximum(exp_pred(p0y), exp_gt(g0y))
        ix1 = jnp.minimum(exp_pred(p1x), exp_gt(g1x))
        iy1 = jnp.minimum(exp_pred(p1y), exp_gt(g1y))
        iw = jnp.maximum(ix1 - ix0, 0.)
        ih = jnp.maximum(iy1 - iy0, 0.)
        inter_p = iw * ih
        area_p = exp_pred(bw * bh)
        area_g = exp_gt(gb[:, :, 2] * gb[:, :, 3])
        iou = inter_p / jnp.maximum(area_p + area_g - inter_p, 1e-9)
        iou = jnp.where(exp_gt(valid.astype(xv.dtype)) > 0, iou, 0.)
        best_iou = iou.max(-1)                            # [N,S,H,W]
        noobj = (best_iou <= ignore_thresh).astype(xv.dtype)
        # positives excluded from the negative term
        pos_map = jnp.zeros((N, S, H, W), xv.dtype)
        pos_map = pos_map.at[sel].max(pos.astype(xv.dtype))
        neg_w = noobj * (1.0 - pos_map)
        loss_obj_neg = (_sce(pobj, jnp.zeros_like(pobj)) * neg_w) \
            .sum((1, 2, 3))

        per_gt = (loss_xy + loss_wh + loss_cls + loss_obj_pos).sum(-1)
        return per_gt + loss_obj_neg

    return apply(fn, *ins, op_name='yolo_loss')


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v2 (v1 when mask is None) — reference
    vision/ops.py:397 (deformable_conv_op.cu).  Bilinear sampling at
    offset taps = 4 static gathers per tap + one einsum (same core as
    static.nn.deform_conv2d, but weight/bias come in as tensors).

    x: [B, Cin, H, W]; offset: [B, 2*kh*kw, Ho, Wo];
    weight: [Cout, Cin, kh, kw]; mask: [B, kh*kw, Ho, Wo].
    """
    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError(
            'deform_conv2d: groups/deformable_groups > 1 not supported')
    wv = wrap(weight)
    Cout, Cin, kh, kw = wv.shape
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else dilation
    ins = [wrap(x), wrap(offset), wv]
    if bias is not None:
        ins.append(wrap(bias))
    has_bias = bias is not None
    if mask is not None:
        ins.append(wrap(mask))
    has_mask = mask is not None

    def fn(v, o, wgt, *rest):
        bv = rest[0] if has_bias else None
        mk = rest[-1] if has_mask else None
        B, C, H, W = v.shape
        Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        o = o.reshape(B, kh * kw, 2, Ho, Wo)
        base_y = (jnp.arange(Ho) * sh - ph)[None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, :]
        taps = []
        for i in range(kh):
            for j in range(kw):
                t = i * kw + j
                py = base_y + i * dh + o[:, t, 0]
                px = base_x + j * dw + o[:, t, 1]
                y0 = jnp.floor(py)
                x0 = jnp.floor(px)
                wy = py - y0
                wx = px - x0

                def gather(yy, xx):
                    yi = jnp.clip(yy.astype(jnp.int32), 0, H - 1)
                    xi = jnp.clip(xx.astype(jnp.int32), 0, W - 1)
                    inb = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
                           & (xx <= W - 1)).astype(v.dtype)
                    g = v[jnp.arange(B)[:, None, None], :, yi, xi]
                    return g * inb[..., None]

                g00 = gather(y0, x0)
                g01 = gather(y0, x0 + 1)
                g10 = gather(y0 + 1, x0)
                g11 = gather(y0 + 1, x0 + 1)
                wy_ = wy[..., None]
                wx_ = wx[..., None]
                tap = (g00 * (1 - wy_) * (1 - wx_)
                       + g01 * (1 - wy_) * wx_
                       + g10 * wy_ * (1 - wx_)
                       + g11 * wy_ * wx_)               # [B,Ho,Wo,C]
                if mk is not None:
                    tap = tap * mk.reshape(
                        B, kh * kw, Ho, Wo)[:, t][..., None]
                taps.append(tap)
        stacked = jnp.stack(taps, axis=3)                # [B,Ho,Wo,k,C]
        out = jnp.einsum('bhwkc,okc->bohw', stacked,
                         wgt.reshape(Cout, Cin, kh * kw)
                         .transpose(0, 2, 1))
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    return apply(fn, *ins, op_name='deform_conv2d')


class DeformConv2D(Layer):
    """Deformable conv layer (reference vision/ops.py:731): owns
    weight/bias; offset (and mask for v2) come from a sibling conv at
    call time."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) \
            if isinstance(kernel_size, int) else kernel_size
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        fan_in = in_channels * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, bias=self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups,
            groups=self._groups, mask=mask)


def read_file(filename, name=None):
    """Read a file's raw bytes as a uint8 tensor (reference
    vision/ops.py:790)."""
    from ..core.tensor import Tensor
    with open(filename, 'rb') as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode='unchanged', name=None):
    """Decode JPEG bytes to a [C, H, W] uint8 tensor (reference
    vision/ops.py:835 uses nvjpeg; PIL on host here)."""
    from ..core.tensor import Tensor
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError('decode_jpeg needs pillow in this build') from e
    import io as _io
    raw = np.asarray(x.value if hasattr(x, 'value') else x,
                     np.uint8).tobytes()
    img = Image.open(_io.BytesIO(raw))
    if mode != 'unchanged':
        img = img.convert(mode.upper())
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


# -- detection suite (vision/detection.py): priors/anchors, box coding,
# NMS, proposals, RoI pooling --------------------------------------------
from .detection import (       # noqa: F401,E402
    iou_similarity, prior_box, anchor_generator, box_coder, box_clip,
    multiclass_nms, generate_proposals, roi_align, roi_pool, nms)

__all__ += ['iou_similarity', 'prior_box', 'anchor_generator',
            'box_coder', 'box_clip', 'multiclass_nms',
            'generate_proposals', 'roi_align', 'roi_pool', 'nms']

from .detection import (       # noqa: F401,E402
    density_prior_box, bipartite_match, target_assign,
    detection_output, ssd_loss, distribute_fpn_proposals,
    collect_fpn_proposals)

__all__ += ['density_prior_box', 'bipartite_match', 'target_assign',
            'detection_output', 'ssd_loss',
            'distribute_fpn_proposals', 'collect_fpn_proposals']

from .detection import (       # noqa: F401,E402
    sigmoid_focal_loss, matrix_nms, polygon_box_transform,
    box_decoder_and_assign, rpn_target_assign,
    generate_proposal_labels, retinanet_target_assign,
    retinanet_detection_output)

__all__ += ['sigmoid_focal_loss', 'matrix_nms',
            'polygon_box_transform', 'box_decoder_and_assign',
            'rpn_target_assign', 'generate_proposal_labels',
            'retinanet_target_assign', 'retinanet_detection_output']
