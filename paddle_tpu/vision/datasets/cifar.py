"""Cifar10 / Cifar100 datasets.

Reference analogue: python/paddle/vision/datasets/cifar.py:99 (Cifar10),
:231 (Cifar100).  Parses the standard python-pickle tar.gz when
`data_file` is given; synthetic fallback otherwise (zero-egress build).
"""
import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset
from ._synthetic import synthetic_images

__all__ = ['Cifar10', 'Cifar100']


class Cifar10(Dataset):
    NUM_CLASSES = 10
    _SYNTH_SEED = 211
    _LABEL_KEYS = (b'labels', 'labels')

    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend=None):
        mode = mode.lower()
        assert mode in ('train', 'test'), \
            "mode should be 'train' or 'test', but got {}".format(mode)
        self.mode = mode
        self.transform = transform
        self.backend = backend or 'numpy'
        if data_file and os.path.exists(data_file):
            self.data = self._load_tar(data_file, mode)
        else:
            n = 8192 if mode == 'train' else 2048
            seed = self._SYNTH_SEED + (0 if mode == 'train' else 1)
            images, labels = synthetic_images(
                n, (32, 32, 3), self.NUM_CLASSES, seed)
            self.data = [(images[i].transpose(2, 0, 1).reshape(-1),
                          int(labels[i])) for i in range(n)]

    def _member_filter(self, name, mode):
        want = 'data_batch' if mode == 'train' else 'test_batch'
        return want in name

    def _load_tar(self, path, mode):
        out = []
        with tarfile.open(path, mode='r') as tf:
            names = [n for n in tf.getnames()
                     if self._member_filter(n, mode)]
            for name in sorted(names):
                batch = pickle.load(tf.extractfile(name), encoding='bytes')
                data = batch[b'data'] if b'data' in batch else batch['data']
                labels = None
                for k in self._LABEL_KEYS:
                    if k in batch:
                        labels = batch[k]
                        break
                for i in range(len(labels)):
                    out.append((data[i], int(labels[i])))
        return out

    def __getitem__(self, idx):
        image, label = self.data[idx]
        image = np.asarray(image, dtype=np.uint8)
        image = image.reshape(3, 32, 32).transpose(1, 2, 0)
        if self.transform is not None:
            image = self.transform(image)
        return image, np.array([label]).astype(np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    NUM_CLASSES = 100
    _SYNTH_SEED = 221
    _LABEL_KEYS = (b'fine_labels', 'fine_labels')

    def _member_filter(self, name, mode):
        return name.endswith(mode)  # files named 'train' / 'test'
