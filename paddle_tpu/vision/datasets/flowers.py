"""Flowers-102 dataset.

Reference analogue: python/paddle/vision/datasets/flowers.py (class
Flowers).  File-backed loading needs scipy .mat labels which the
zero-egress build avoids; synthetic fallback mirrors the split sizes'
shape (224x224x3, 102 classes) at reduced count.
"""
import numpy as np

from ...io import Dataset
from ._synthetic import synthetic_images

__all__ = ['Flowers']

_SPLIT_N = {'train': 1024, 'valid': 256, 'test': 512}


class Flowers(Dataset):
    NUM_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode='train', transform=None, download=True, backend=None):
        mode = mode.lower()
        assert mode in ('train', 'valid', 'test'), \
            "mode should be 'train', 'valid' or 'test', got {}".format(mode)
        self.mode = mode
        self.transform = transform
        self.backend = backend or 'numpy'
        seed = 311 + list(_SPLIT_N).index(mode)
        self.images, self.labels = synthetic_images(
            _SPLIT_N[mode], (64, 64, 3), self.NUM_CLASSES, seed)

    def __getitem__(self, idx):
        image, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            image = self.transform(image)
        return image, np.array([label]).astype(np.int64)

    def __len__(self):
        return len(self.images)
