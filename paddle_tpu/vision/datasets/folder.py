"""DatasetFolder / ImageFolder.

Reference analogue: python/paddle/vision/datasets/folder.py:65
(DatasetFolder), :222 (ImageFolder).  Images load via numpy (`.npy`) or a
minimal PPM/PGM reader; other formats fall back to PIL if present.
"""
import os

import numpy as np

from ...io import Dataset

__all__ = ['DatasetFolder', 'ImageFolder']

IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.ppm', '.pgm', '.bmp', '.npy',
                  '.tif', '.tiff', '.webp')


def has_valid_extension(filename, extensions):
    return filename.lower().endswith(tuple(extensions))


def _read_pnm(path):
    with open(path, 'rb') as f:
        magic = f.readline().strip()
        if magic not in (b'P5', b'P6'):
            raise ValueError('unsupported PNM type: {}'.format(magic))
        vals = []
        while len(vals) < 3:
            line = f.readline()
            if line.startswith(b'#'):
                continue
            vals += line.split()
        w, h, _maxval = (int(v) for v in vals[:3])
        c = 3 if magic == b'P6' else 1
        data = np.frombuffer(f.read(w * h * c), dtype=np.uint8)
    return data.reshape(h, w, c)


def default_loader(path):
    """numpy for .npy, builtin reader for PPM/PGM, PIL for the rest."""
    if path.endswith('.npy'):
        return np.load(path)
    if path.lower().endswith(('.ppm', '.pgm')):
        return _read_pnm(path)
    try:
        from PIL import Image
        with Image.open(path) as img:
            return np.asarray(img.convert('RGB'))
    except ImportError as e:
        raise RuntimeError(
            'loading {} needs PIL, which is unavailable; use .npy or '
            'PPM/PGM images, or pass a custom loader'.format(path)) from e


def make_dataset(directory, class_to_idx, extensions=None,
                 is_valid_file=None):
    samples = []
    for target in sorted(class_to_idx):
        d = os.path.join(directory, target)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                ok = is_valid_file(path) if is_valid_file is not None \
                    else has_valid_extension(path, extensions)
                if ok:
                    samples.append((path, class_to_idx[target]))
    return samples


class DatasetFolder(Dataset):
    """root/class_x/xxx.ext layout -> (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        self.extensions = extensions
        classes, class_to_idx = self._find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions,
                               is_valid_file)
        if not samples:
            raise RuntimeError(
                'found 0 files in subfolders of: {}'.format(root))
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]

    @staticmethod
    def _find_classes(directory):
        classes = sorted(e.name for e in os.scandir(directory)
                         if e.is_dir())
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (or nested) folder of images -> [sample] (no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        samples = []
        for r, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(r, fname)
                ok = is_valid_file(path) if is_valid_file is not None \
                    else has_valid_extension(path, extensions)
                if ok:
                    samples.append(path)
        if not samples:
            raise RuntimeError('found 0 files in: {}'.format(root))
        self.samples = samples

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
