"""Deterministic synthetic data for zero-egress environments.

The reference datasets (python/paddle/vision/datasets/*) download from
dataset.bj.bcebos.com; this build cannot egress, so every dataset falls
back to a deterministic synthetic sample set when no local file is given.
Samples are class-separable (per-class template + bounded noise) so the
e2e convergence tests in SURVEY.md §4 are meaningful.
"""
import numpy as np


def synthetic_images(n, hwc, num_classes, seed):
    """Return (images uint8 [n,H,W,C], labels int64 [n])."""
    rng = np.random.RandomState(seed)
    h, w, c = hwc
    templates = rng.randint(0, 256, size=(num_classes, h, w, c))
    labels = rng.randint(0, num_classes, size=n).astype(np.int64)
    noise = rng.randint(-20, 21, size=(n, h, w, c))
    images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
    return images, labels
