"""VOC2012 segmentation dataset.

Reference analogue: python/paddle/vision/datasets/voc2012.py (class
VOC2012) — (image, segmentation-mask) pairs.  Synthetic fallback emits
blocky class-region masks so segmentation losses have real structure.
"""
import numpy as np

from ...io import Dataset

__all__ = ['VOC2012']

_SPLIT_N = {'train': 512, 'valid': 128, 'test': 128}


class VOC2012(Dataset):
    NUM_CLASSES = 21  # 20 object classes + background

    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend=None):
        mode = mode.lower()
        assert mode in ('train', 'valid', 'test'), \
            "mode should be 'train', 'valid' or 'test', got {}".format(mode)
        self.mode = mode
        self.transform = transform
        self.backend = backend or 'numpy'
        n = _SPLIT_N[mode]
        rng = np.random.RandomState(401 + list(_SPLIT_N).index(mode))
        self.images = rng.randint(0, 256, size=(n, 64, 64, 3),
                                  dtype=np.uint8)
        # blocky masks: each quadrant gets one class id
        self.labels = np.zeros((n, 64, 64), dtype=np.int64)
        quads = rng.randint(0, self.NUM_CLASSES, size=(n, 2, 2))
        for i in range(n):
            for qy in range(2):
                for qx in range(2):
                    self.labels[i, qy * 32:(qy + 1) * 32,
                                qx * 32:(qx + 1) * 32] = quads[i, qy, qx]

    def __getitem__(self, idx):
        image, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.images)
