"""MNIST / FashionMNIST datasets.

Reference analogue: python/paddle/vision/datasets/mnist.py:74 (class MNIST).
Same constructor; parses standard idx-ubyte files when paths are given,
otherwise serves deterministic synthetic digits (zero-egress build).
"""
import gzip
import os
import struct

import numpy as np

from ...io import Dataset
from ._synthetic import synthetic_images

__all__ = ['MNIST', 'FashionMNIST']


def _read_idx(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic = struct.unpack('>I', f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack('>' + 'I' * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    NUM_CLASSES = 10
    _SYNTH_SEED = 101

    def __init__(self, image_path=None, label_path=None, mode='train',
                 transform=None, download=True, backend=None):
        mode = mode.lower()
        assert mode in ('train', 'test'), \
            "mode should be 'train' or 'test', but got {}".format(mode)
        if backend not in (None, 'cv2', 'pil', 'numpy'):
            raise ValueError('unsupported backend: {}'.format(backend))
        self.mode = mode
        self.transform = transform
        self.backend = backend or 'numpy'
        if image_path and label_path and os.path.exists(image_path) \
                and os.path.exists(label_path):
            self.images = _read_idx(image_path)
            if self.images.ndim == 3:
                self.images = self.images[:, :, :, None]
            self.labels = _read_idx(label_path).astype(np.int64)
        else:
            n = 8192 if mode == 'train' else 2048
            seed = self._SYNTH_SEED + (0 if mode == 'train' else 1)
            self.images, self.labels = synthetic_images(
                n, (28, 28, 1), self.NUM_CLASSES, seed)

    def __getitem__(self, idx):
        image, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            image = self.transform(image)
        return image, np.array([label]).astype(np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """Same on-disk format as MNIST; different synthetic seed."""
    _SYNTH_SEED = 131
