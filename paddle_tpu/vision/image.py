"""Image IO backend selection (reference: python/paddle/vision/image.py).

Backends: 'pil' (PIL.Image), 'cv2' (OpenCV BGR ndarray), 'tensor'
(paddle Tensor, HWC uint8) and 'numpy' (host ndarray — the TPU-native
default: datasets stage host-side numpy and batch-transfer to HBM).
"""
import numpy as np

__all__ = ['set_image_backend', 'get_image_backend', 'image_load']

_image_backend = 'numpy'


def set_image_backend(backend):
    global _image_backend
    if backend not in ('pil', 'cv2', 'tensor', 'numpy'):
        raise ValueError(
            "Expected backend is one of ['pil', 'cv2', 'tensor', "
            f"'numpy'], but got {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def _read_array(path):
    # raw .npy dumps are what the synthetic datasets stage in this
    # egress-less environment — they are not PIL-decodable
    if str(path).endswith('.npy'):
        return np.load(path)
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            'image_load needs PIL (or a .npy path) for backend '
            f'{_image_backend!r}; neither is available for {path!r}'
        ) from e
    with Image.open(path) as im:
        return np.asarray(im.convert('RGB'))


def image_load(path, backend=None):
    """Load an image with the selected backend (reference image.py:110)."""
    backend = backend or _image_backend
    if backend not in ('pil', 'cv2', 'tensor', 'numpy'):
        raise ValueError(
            "Expected backend is one of ['pil', 'cv2', 'tensor', "
            f"'numpy'], but got {backend}")
    if backend == 'pil':
        from PIL import Image
        return Image.open(path)
    if backend == 'cv2':
        try:
            import cv2
        except ImportError as e:
            raise ImportError(
                'backend "cv2" needs opencv-python, which is not '
                'installed in this environment; use "pil", "numpy" or '
                '"tensor"') from e
        return cv2.imread(str(path))
    arr = _read_array(path)
    if backend == 'tensor':
        from ..tensor import to_tensor
        return to_tensor(arr)
    return arr
