"""Composable image transforms.

Reference analogue: python/paddle/vision/transforms/transforms.py:38
(same __all__).  Each transform is a callable over numpy HWC images;
randomness uses stdlib `random`, which paddle_tpu.seed reseeds so
augmentation pipelines are reproducible from the framework seed.
"""
import numbers
import random

import numpy as np

from . import functional as F

__all__ = ['BaseTransform', 'Compose', 'Resize', 'RandomResizedCrop',
           'CenterCrop', 'RandomHorizontalFlip', 'RandomVerticalFlip',
           'Transpose', 'Normalize', 'BrightnessTransform',
           'SaturationTransform', 'ContrastTransform', 'HueTransform',
           'ColorJitter', 'RandomCrop', 'Pad', 'RandomRotation',
           'Grayscale', 'ToTensor']


def _pair(x):
    if isinstance(x, numbers.Number):
        return (int(x), int(x))
    return tuple(int(v) for v in x)


class BaseTransform:
    """Apply `_apply_image` to the image (and leave labels alone when the
    input is an (img, label) tuple — keys-based dispatch like the ref)."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        if isinstance(inputs, tuple) and self.keys is not None:
            out = []
            for key, x in zip(self.keys, inputs):
                out.append(self._apply_image(x) if key == 'image' else x)
            return tuple(out)
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data

    def __repr__(self):
        inner = ', '.join(repr(t.__class__.__name__)
                          for t in self.transforms)
        return f'Compose([{inner}])'


class Resize(BaseTransform):
    def __init__(self, size, interpolation='bilinear', keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation='bilinear', keys=None):
        super().__init__(keys)
        self.size = _pair(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _get_param(self, img):
        h, w = np.asarray(img).shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            ar = np.exp(random.uniform(*log_ratio))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return top, left, ch, cw
        # central fallback
        ch, cw = min(h, w), min(h, w)
        return (h - ch) // 2, (w - cw) // 2, ch, cw

    def _apply_image(self, img):
        top, left, ch, cw = self._get_param(img)
        img = F.crop(img, top, left, ch, cw)
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = _pair(size)

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return np.transpose(img, self.order)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format='CHW', to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError('contrast value must be non-negative')
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError('hue value must be in [0, 0.5]')
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode='constant', keys=None):
        super().__init__(keys)
        self.size = _pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and w < tw:
            img = F.pad(img, (tw - w, 0), self.fill, self.padding_mode)
        if self.pad_if_needed and h < th:
            img = F.pad(img, (0, th - h), self.fill, self.padding_mode)
        h, w = np.asarray(img).shape[:2]
        if h == th and w == tw:
            return img
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode='constant', keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation='nearest', expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError('degrees must be non-negative')
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class ToTensor(BaseTransform):
    def __init__(self, data_format='CHW', keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)
