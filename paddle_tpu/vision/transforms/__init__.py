"""Image transforms (reference: python/paddle/vision/transforms)."""
from .transforms import *  # noqa: F401,F403
from .transforms import __all__ as _t_all
from . import functional  # noqa: F401
from .functional import (  # noqa: F401
    to_tensor, resize, crop, center_crop, hflip, vflip, pad, rotate,
    to_grayscale, normalize, adjust_brightness, adjust_contrast,
    adjust_saturation, adjust_hue)

__all__ = list(_t_all) + [
    'to_tensor', 'resize', 'crop', 'center_crop', 'hflip', 'vflip', 'pad',
    'rotate', 'to_grayscale', 'normalize', 'adjust_brightness',
    'adjust_contrast', 'adjust_saturation', 'adjust_hue']
