"""Functional image transforms on numpy HWC arrays.

Reference analogue: python/paddle/vision/transforms/functional.py.  The
reference leans on PIL/cv2; we are numpy-native (host-side preprocessing
feeds the TPU via the DataLoader's prefetch ring, so these must be cheap,
dependency-free and thread-safe).

Images are numpy arrays, shape (H, W, C) or (H, W), dtype uint8 or float.
"""
import numbers

import numpy as np

__all__ = ['to_tensor', 'resize', 'crop', 'center_crop', 'hflip', 'vflip',
           'pad', 'rotate', 'to_grayscale', 'normalize',
           'adjust_brightness', 'adjust_contrast', 'adjust_saturation',
           'adjust_hue']


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(img, data_format='CHW'):
    """uint8 HWC -> float32 scaled to [0,1], CHW or HWC."""
    img = _as_hwc(img)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    else:
        img = img.astype(np.float32)
    if data_format.upper() == 'CHW':
        img = np.transpose(img, (2, 0, 1))
    return img


def resize(img, size, interpolation='bilinear'):
    """Resize to `size` (int: short side; (h, w): exact)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h <= w:
            oh, ow = size, max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), size
    else:
        oh, ow = int(size[0]), int(size[1])
    if (oh, ow) == (h, w):
        return img
    if interpolation == 'nearest':
        ys = np.clip(np.round(np.arange(oh) * h / oh).astype(int), 0, h - 1)
        xs = np.clip(np.round(np.arange(ow) * w / ow).astype(int), 0, w - 1)
        return img[ys][:, xs]
    # bilinear, half-pixel centers
    dt = img.dtype
    fy = (np.arange(oh) + 0.5) * h / oh - 0.5
    fx = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(fy).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(fx).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(fy - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(fx - x0, 0.0, 1.0)[None, :, None]
    im = img.astype(np.float32)
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(dt, np.integer):
        out = np.clip(np.round(out), 0, np.iinfo(dt).max).astype(dt)
    return out


def crop(img, top, left, height, width):
    img = _as_hwc(img)
    return img[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    img = _as_hwc(img)
    h, w = img.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode='constant'):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    pads = [(pt, pb), (pl, pr), (0, 0)]
    if padding_mode == 'constant':
        return np.pad(img, pads, mode='constant', constant_values=fill)
    mode = {'edge': 'edge', 'reflect': 'reflect',
            'symmetric': 'symmetric'}[padding_mode]
    return np.pad(img, pads, mode=mode)


def rotate(img, angle, interpolation='nearest', expand=False,
           center=None, fill=0):
    """Rotate counter-clockwise by `angle` degrees (inverse-map
    sampling, 'nearest' or 'bilinear')."""
    if interpolation not in ('nearest', 'bilinear'):
        raise ValueError(
            f"rotate: unsupported interpolation '{interpolation}' "
            "(use 'nearest' or 'bilinear')")
    img = _as_hwc(img)
    h, w = img.shape[:2]
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        ow = int(np.ceil(abs(w * cos) + abs(h * sin)))
        oh = int(np.ceil(abs(h * cos) + abs(w * sin)))
    else:
        ow, oh = w, h
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing='ij')
    dy, dx = yy - ocy, xx - ocx
    src_x = cos * dx - sin * dy + cx
    src_y = sin * dx + cos * dy + cy
    out = np.full((oh, ow, img.shape[2]), fill, dtype=img.dtype)
    if interpolation == 'nearest':
        sx = np.round(src_x).astype(int)
        sy = np.round(src_y).astype(int)
        valid = (sx >= 0) & (sx < w) & (sy >= 0) & (sy < h)
        out[valid] = img[sy[valid], sx[valid]]
        return out
    # bilinear: blend the 4 neighbours of the (fractional) source point
    x0 = np.floor(src_x).astype(int)
    y0 = np.floor(src_y).astype(int)
    fx = (src_x - x0)[..., None]
    fy = (src_y - y0)[..., None]
    valid = (src_x >= 0) & (src_x <= w - 1) & \
            (src_y >= 0) & (src_y <= h - 1)
    x0c = np.clip(x0, 0, w - 1)
    y0c = np.clip(y0, 0, h - 1)
    x1c = np.clip(x0 + 1, 0, w - 1)
    y1c = np.clip(y0 + 1, 0, h - 1)
    f = img.astype(np.float64)
    top = f[y0c, x0c] * (1 - fx) + f[y0c, x1c] * fx
    bot = f[y1c, x0c] * (1 - fx) + f[y1c, x1c] * fx
    blend = top * (1 - fy) + bot * fy
    if np.issubdtype(img.dtype, np.integer):
        blend = np.round(blend)
    out[valid] = blend[valid].astype(img.dtype)
    return out


def to_grayscale(img, num_output_channels=1):
    img = _as_hwc(img)
    if img.shape[2] == 1:
        gray = img.astype(np.float32)[:, :, 0]
    else:
        gray = (0.299 * img[:, :, 0] + 0.587 * img[:, :, 1]
                + 0.114 * img[:, :, 2]).astype(np.float32)
    if np.issubdtype(img.dtype, np.integer):
        gray = np.clip(np.round(gray), 0, 255).astype(img.dtype)
    else:
        gray = gray.astype(img.dtype)
    return np.repeat(gray[:, :, None], num_output_channels, axis=2)


def normalize(img, mean, std, data_format='CHW', to_rgb=False):
    img = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format.upper() == 'CHW':
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (img - mean) / std


def _blend(img1, img2, ratio):
    dt = img1.dtype
    out = img1.astype(np.float32) * ratio + img2.astype(np.float32) \
        * (1.0 - ratio)
    if np.issubdtype(dt, np.integer):
        return np.clip(out, 0, 255).astype(dt)
    return out.astype(dt)


def adjust_brightness(img, brightness_factor):
    img = _as_hwc(img)
    return _blend(img, np.zeros_like(img), brightness_factor)


def adjust_contrast(img, contrast_factor):
    img = _as_hwc(img)
    mean = to_grayscale(img).astype(np.float32).mean()
    return _blend(img, np.full_like(img, mean.astype(img.dtype)
                  if np.issubdtype(img.dtype, np.integer) else mean),
                  contrast_factor)


def adjust_saturation(img, saturation_factor):
    img = _as_hwc(img)
    gray = to_grayscale(img, num_output_channels=img.shape[2])
    return _blend(img, gray, saturation_factor)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV round-trip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError('hue_factor must be in [-0.5, 0.5]')
    img = _as_hwc(img)
    if img.shape[2] < 3:
        return img  # hue is undefined for grayscale
    dt = img.dtype
    f = img.astype(np.float32)
    if np.issubdtype(dt, np.integer):
        f = f / 255.0
    r, g, b = f[:, :, 0], f[:, :, 1], f[:, :, 2]
    maxc = f.max(axis=2)
    minc = f.min(axis=2)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    hr = np.where((maxc == r), ((g - b) / dz) % 6.0, 0.0)
    hg = np.where((maxc == g) & (maxc != r), (b - r) / dz + 2.0, 0.0)
    hb = np.where((maxc == b) & (maxc != r) & (maxc != g),
                  (r - g) / dz + 4.0, 0.0)
    hcomb = ((hr + hg + hb) / 6.0) % 1.0
    hcomb = (hcomb + hue_factor) % 1.0
    i = np.floor(hcomb * 6.0)
    frac = hcomb * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * frac)
    t = v * (1.0 - s * (1.0 - frac))
    i = i.astype(int) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=2)
    if np.issubdtype(dt, np.integer):
        out = np.clip(np.round(out * 255.0), 0, 255).astype(dt)
    else:
        out = out.astype(dt)
    return out
