"""paddle_tpu.autograd — user-facing autograd namespace.

Reference analogue: /root/reference/python/paddle/autograd/__init__.py
(grad, backward, PyLayer, PyLayerContext — py_layer.py builds a CFunction
node into the dygraph engine).

TPU-native PyLayer: forward runs eagerly (its internal ops are NOT
taped); a single GradNode is recorded whose vjp closure calls the
user's backward().  Inside backward the cotangents arrive as ordinary
Tensors, so any paddle_tpu op works there, and the math still lowers to
XLA when the surrounding step is jitted.
"""
import numpy as np
import jax

from ..core import autograd as _ag
from ..core.autograd import grad  # noqa: F401
from ..core.autograd import GradNode
from ..core.tensor import Tensor

__all__ = ['grad', 'backward', 'PyLayer', 'PyLayerContext']


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Compute grads of several root tensors (reference
    autograd/backward_mode.py::backward); cotangents accumulate into
    `.grad` of every reachable non-stop-gradient tensor."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = grad_tensors if isinstance(grad_tensors, (list, tuple)) \
        else [grad_tensors]
    if len(grad_tensors) != len(tensors):
        raise ValueError('grad_tensors must match tensors in length')
    _ag.backward_multi(tensors, grad_tensors, retain_graph=retain_graph)


class PyLayerContext:
    """Carried from forward to backward (reference py_layer.py)."""

    def __init__(self):
        self._saved = ()
        self.container = None   # legacy alias some reference code pokes

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """User-defined differentiable op:

        class cus_tanh(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = paddle.tanh(x)
                ctx.save_for_backward(y)
                return y

            @staticmethod
            def backward(ctx, dy):
                y, = ctx.saved_tensor()
                return dy * (1 - y * y)

        z = cus_tanh.apply(x)

    backward() must return one grad per Tensor input of forward (None
    for non-differentiable ones), matching the reference's contract.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError(
            'PyLayer subclasses must define a static forward()')

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError(
            'PyLayer subclasses must define a static backward()')

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        kw_tensors = [k for k, v in kwargs.items()
                      if isinstance(v, Tensor)]
        if kw_tensors:
            raise TypeError(
                f'PyLayer.apply: pass differentiable Tensors '
                f'positionally, not as keywords ({kw_tensors}) — keyword '
                'tensors would silently drop their gradients')
        tpos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        requires = (_ag.is_grad_enabled()
                    and any(not args[i].stop_gradient for i in tpos))
        with _ag.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)
        for o in outs:
            if not isinstance(o, Tensor):
                raise TypeError('PyLayer.forward must return Tensor(s), '
                                f'got {type(o).__name__}')
        if not requires:
            # mark only FRESH outputs non-differentiable — forward may
            # return an input unchanged, and mutating the caller's
            # tensor would silently kill its future gradients
            fresh = [o if o.stop_gradient and o.grad_node is None
                     else Tensor._from_value(o.value, stop_gradient=True)
                     for o in outs]
            return fresh[0] if single else type(out)(fresh)

        avals = [(tuple(o.value.shape), o.value.dtype) for o in outs]
        n_out = len(outs)
        in_tensors = [args[i] for i in tpos]

        def vjp_fn(cts):
            cts = (cts,) if n_out == 1 else tuple(cts)
            ct_tensors = [Tensor._from_value(c, stop_gradient=True)
                          for c in cts]
            with _ag.no_grad():
                gs = cls.backward(ctx, *ct_tensors)
            gs = (gs,) if not isinstance(gs, (tuple, list)) else tuple(gs)
            if len(gs) != len(in_tensors):
                raise ValueError(
                    f'{cls.__name__}.backward returned {len(gs)} grads '
                    f'for {len(in_tensors)} tensor inputs')
            return [None if g is None else
                    (g.value if isinstance(g, Tensor) else np.asarray(g))
                    for g in gs]

        node = GradNode(
            vjp_fn,
            [t if not t.stop_gradient else None for t in in_tensors],
            avals, name=cls.__name__, out_is_seq=n_out > 1)
        fresh = []
        for i, o in enumerate(outs):
            t = Tensor._from_value(o.value, stop_gradient=False)
            t.grad_node = node
            t.grad_index = i
            fresh.append(t)
        return fresh[0] if single else type(out)(fresh)
