"""Multi-engine serving router: N ServingEngine replicas behind ONE
door, with proven failure semantics.

Three pieces:

* :class:`ReplicaHandle` — one engine replica: either a spawned
  ``tools/serve_fleet.py worker`` subprocess (the ChaosCluster
  posture: own process, env-configured, port published through a
  file) or an attached already-running frontend URL (in-process
  tests).  Thin HTTP client helpers over the replica's front door.

* :class:`FleetRouter` — the dispatch + supervision brain:

  - **dispatch** is KV-occupancy- and queue-depth-aware, fed by each
    replica's live ``/status.json`` (lowest composite load wins;
    draining/down replicas excluded);
  - **retry**: a replica that dies (or hangs past the read timeout)
    mid-stream gets its in-flight requests replayed on a surviving
    replica as ``prompt + emitted-prefix`` with the SAME rid — the
    per-request position-keyed sampling discipline (ops/sampling)
    makes the continuation bit-exact, and every token carries its
    global stream offset so delivery is at-most-once;
  - **drain + warm-spare promotion**: a replica whose status latches
    ``slo_breach``/``memory_pressure`` is drained (stops being
    dispatched to, finishes in-flight, typed-rejects new) while a
    pre-warmed spare is promoted into the active set — zero dropped
    in-flight requests;
  - **ledger**: every rid the router ever accepted reaches EXACTLY
    one terminal state — ``finished`` | ``evicted(cause)`` |
    ``rejected(type)`` | ``failed(cause)`` — and
    :meth:`FleetRouter.check_invariants` proves it the way the chaos
    harness's I1–I7 are proven, never claims it.

* :class:`FleetFrontend` — the one public door: re-serves
  ``POST /v1/generate`` (SSE re-streaming through the router's retry
  machinery), ``/v1/cancel/<rid>``, ``/healthz``, ``/status.json``
  in the same stdlib posture as the single-engine frontend.

Control-plane actions emit ``fleet_event`` telemetry
(dispatch retries, drains, promotions, replica deaths) — run_report
renders them on the timeline next to the ``serve_reject`` shed trail.
"""
import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .scheduler import RejectReason, RejectedRequest

__all__ = ['ReplicaHandle', 'FleetRouter', 'FleetFrontend']

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


class ReplicaDied(ConnectionError):
    """The replica serving a stream went away (process death, socket
    reset, or a read stalled past the hang timeout)."""


class ReplicaHandle:
    """One serving replica — spawned subprocess or attached URL."""

    def __init__(self, name, host='127.0.0.1', port=None, proc=None,
                 port_file=None):
        self.name = name
        self.host = host
        self.port = port
        self.proc = proc
        self.port_file = port_file
        self.draining = False
        self.down = False
        self.last_status = None

    # -- construction --------------------------------------------------------
    @classmethod
    def attach(cls, name, url):
        """Wrap an already-listening frontend (in-process tests)."""
        host, port = url.split('//', 1)[-1].rsplit(':', 1)
        return cls(name, host=host, port=int(port))

    @classmethod
    def spawn(cls, name, config_path, workdir, host='127.0.0.1',
              warmup=False, extra_env=None):
        """Start one ``tools/serve_fleet.py worker`` subprocess (the
        ChaosCluster env posture: CPU backend, repo on PYTHONPATH,
        port published through a file once the door is open)."""
        os.makedirs(workdir, exist_ok=True)
        port_file = os.path.join(workdir, f'{name}.port')
        log = open(os.path.join(workdir, f'{name}.log'), 'ab')
        cmd = [sys.executable,
               os.path.join(_REPO, 'tools', 'serve_fleet.py'),
               'worker', '--config', config_path,
               '--port-file', port_file, '--host', host]
        if warmup:
            cmd.append('--warmup')
        env = dict(os.environ)
        env.update({
            'JAX_PLATFORMS': 'cpu',
            'PYTHONPATH': _REPO + os.pathsep
            + env.get('PYTHONPATH', ''),
        })
        env.update(extra_env or {})
        proc = subprocess.Popen(cmd, env=env, stdout=log, stderr=log,
                                start_new_session=True)
        log.close()
        return cls(name, host=host, proc=proc, port_file=port_file)

    def wait_ready(self, timeout_s=120.0):
        """Block until the worker published its port and /healthz
        answers; raises on worker death or timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f'replica {self.name} exited rc='
                    f'{self.proc.returncode} before becoming ready')
            if self.port is None and self.port_file \
                    and os.path.exists(self.port_file):
                try:
                    with open(self.port_file) as f:
                        self.port = int(json.load(f)['port'])
                except (ValueError, KeyError, OSError):
                    pass                # partial write; retry
            if self.port is not None:
                try:
                    if self.get_json('/healthz').get('ok'):
                        return self
                except OSError:
                    pass
            time.sleep(0.05)
        raise TimeoutError(f'replica {self.name} not ready after '
                           f'{timeout_s}s')

    # -- liveness ------------------------------------------------------------
    def alive(self):
        if self.down:
            return False
        if self.proc is not None and self.proc.poll() is not None:
            return False
        return True

    def kill(self, sig=signal.SIGKILL):
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.send_signal(sig)
            except ProcessLookupError:
                pass

    def reap(self, timeout_s=10.0):
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.kill()
                self.proc.wait(timeout=timeout_s)

    # -- HTTP client ---------------------------------------------------------
    def _conn(self, timeout_s):
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout_s)

    def get_json(self, path, timeout_s=10.0):
        c = self._conn(timeout_s)
        try:
            c.request('GET', path)
            r = c.getresponse()
            return json.loads(r.read().decode('utf-8'))
        finally:
            c.close()

    def post_json(self, path, doc=None, timeout_s=10.0):
        c = self._conn(timeout_s)
        try:
            c.request('POST', path,
                      body=json.dumps(doc) if doc is not None else '',
                      headers={'Content-Type': 'application/json'})
            r = c.getresponse()
            return r.status, json.loads(r.read().decode('utf-8'))
        finally:
            c.close()

    def status(self, timeout_s=5.0):
        doc = self.get_json('/status.json', timeout_s=timeout_s)
        self.last_status = doc
        return doc

    def drain(self):
        self.draining = True
        try:
            self.post_json('/admin/drain')
        except OSError:
            pass
        return self

    def stream_generate(self, doc, read_timeout_s=30.0):
        """POST /v1/generate and yield parsed SSE events.  Raises
        :class:`ReplicaDied` on any transport failure — including a
        read that stalls past ``read_timeout_s`` (a SIGSTOPped
        replica looks exactly like that)."""
        c = self._conn(read_timeout_s)
        try:
            try:
                c.request('POST', '/v1/generate', body=json.dumps(doc),
                          headers={'Content-Type': 'application/json'})
                r = c.getresponse()
            except OSError as e:
                raise ReplicaDied(f'{self.name}: {e!r}')
            if r.status != 200:
                try:
                    body = json.loads(r.read().decode('utf-8'))
                except (OSError, ValueError) as e:
                    raise ReplicaDied(f'{self.name}: unreadable '
                                      f'rejection body: {e!r}')
                exc = RejectedRequest(
                    body.get('error', RejectReason.QUEUE_FULL),
                    body.get('detail', ''), rid=body.get('rid'))
                exc.retry_after_s = body.get('retry_after_s')
                raise exc
            while True:
                try:
                    line = r.readline()
                except OSError as e:    # timeout / reset mid-stream
                    raise ReplicaDied(f'{self.name}: {e!r}')
                if not line:
                    raise ReplicaDied(
                        f'{self.name}: stream ended without a '
                        'terminal event')
                line = line.strip()
                if not line.startswith(b'data: '):
                    continue
                try:
                    ev = json.loads(line[len(b'data: '):])
                except ValueError:
                    # a replica SIGKILLed mid-write leaves a truncated
                    # line in the socket buffer — that is a death, not
                    # a protocol error to leak to the caller
                    raise ReplicaDied(
                        f'{self.name}: truncated event mid-stream')
                yield ev
                if ev.get('done'):
                    return
        finally:
            c.close()


class FleetRouter:
    """Dispatch + retry + drain/promote over a set of replicas."""

    def __init__(self, replicas, spares=(), max_attempts=3,
                 read_timeout_s=30.0, poll_s=0.25):
        self.replicas = list(replicas)      # active set
        self.spares = list(spares)          # warm, not dispatched to
        self.max_attempts = int(max_attempts)
        self.read_timeout_s = float(read_timeout_s)
        self.poll_s = float(poll_s)
        self.ledger = {}                    # rid -> entry dict
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._health_thread = None
        self.events = []                    # local fleet_event record

    # -- telemetry -----------------------------------------------------------
    def _fleet_event(self, action, **data):
        from .. import telemetry
        ev = dict(action=action, **{k: v for k, v in data.items()
                                    if v is not None})
        self.events.append(ev)
        telemetry.event('fleet_event', **ev)

    # -- replica set ---------------------------------------------------------
    def replica(self, name):
        for r in self.replicas + self.spares:
            if r.name == name:
                return r
        return None

    def mark_down(self, rep, cause='dead'):
        if rep.down:
            return
        rep.down = True
        self._fleet_event('replica_down', replica=rep.name,
                          cause=cause)
        self.promote_spare()

    def promote_spare(self):
        """Move one warm spare into the active set (pre-warmed via
        warmup()/precompile --serve, so promotion costs no compile)."""
        with self._lock:
            while self.spares:
                rep = self.spares.pop(0)
                if not rep.alive():
                    continue
                self.replicas.append(rep)
                self._fleet_event('promote', replica=rep.name)
                return rep
        return None

    def drain_replica(self, rep, cause='manual'):
        """Stop dispatching to `rep`, let in-flight finish, promote a
        spare to cover.  The health loop retires it (kills the
        process) once its in-flight count reaches zero."""
        if rep.draining:
            return rep
        rep.drain()
        self._fleet_event('drain', replica=rep.name, cause=cause)
        self.promote_spare()
        return rep

    def dispatchable(self):
        return [r for r in self.replicas
                if r.alive() and not r.draining]

    def pick(self, exclude=()):
        """Load-aware dispatch: live occupancy + queue depth from
        each candidate's /status.json (a replica that cannot answer
        its own status is not a replica you want to dispatch to)."""
        best, best_score = None, None
        for rep in self.dispatchable():
            if rep.name in exclude:
                continue
            try:
                st = rep.status(timeout_s=2.0)
            except OSError:
                continue
            if st.get('draining'):
                rep.draining = True
                continue
            score = (st.get('kv_occupancy') or 0.0) \
                + st.get('queue_depth', 0) / max(1, st.get('max_queue')
                                                 or 1) \
                + st.get('live', 0) / max(1, st.get('max_slots') or 1)
            if best_score is None or score < best_score:
                best, best_score = rep, score
        return best

    # -- the request path ----------------------------------------------------
    def generate(self, prompt, max_new_tokens, rid, on_token=None,
                 deadline_s=None):
        """Run one request to a TERMINAL state, surviving replica
        death mid-stream.  ``on_token(i, token)`` fires exactly once
        per global stream offset (at-most-once delivery: a retry
        resumes from the last delivered offset via
        prompt+emitted-prefix replay).  Returns the ledger entry."""
        prompt = [int(t) for t in prompt]
        max_new_tokens = int(max_new_tokens)
        with self._lock:
            if rid in self.ledger:
                raise ValueError(f'duplicate rid {rid!r}')
            entry = {'rid': rid, 'state': 'in_flight', 'reason': None,
                     'tokens': [], 'attempts': 0, 'replicas': [],
                     'retried': 0}
            self.ledger[rid] = entry
        tokens = entry['tokens']
        tried_dead = set()
        while True:
            rep = self.pick(exclude=tried_dead)
            if rep is None and tried_dead:
                # every untried replica is gone; one more chance on
                # ANY dispatchable (a promoted spare may have landed)
                rep = self.pick()
            if rep is None:
                return self._terminal(entry, 'failed', 'no_replica')
            entry['attempts'] += 1
            entry['replicas'].append(rep.name)
            prefix = len(tokens)
            if entry['attempts'] > 1:
                entry['retried'] += 1
                self._fleet_event('retry', rid=rid, replica=rep.name,
                                  offset=prefix)
            doc = {'prompt': prompt + tokens,
                   'max_new_tokens': max_new_tokens - prefix,
                   'rid': rid, 'stream': True}
            if deadline_s is not None:
                doc['deadline_s'] = deadline_s
            try:
                for ev in rep.stream_generate(
                        doc, read_timeout_s=self.read_timeout_s):
                    if 'token' in ev:
                        i = prefix + int(ev['i'])
                        if i == len(tokens):    # at-most-once
                            tokens.append(int(ev['token']))
                            if on_token is not None:
                                try:
                                    on_token(i, tokens[i])
                                except BaseException:
                                    # the CLIENT went away — the
                                    # replica is fine: evict there,
                                    # terminalize here (a rid must
                                    # never stick at in_flight), then
                                    # let the caller see the error
                                    try:
                                        rep.post_json(
                                            f'/v1/cancel/{rid}')
                                    except OSError:
                                        pass
                                    self._terminal(entry, 'evicted',
                                                   'client_lost')
                                    raise
                    elif ev.get('done'):
                        state = ('finished' if ev.get('state') == 'done'
                                 else 'evicted')
                        return self._terminal(entry, state,
                                              ev.get('reason'))
            except RejectedRequest as e:
                entry['retry_after_s'] = getattr(
                    e, 'retry_after_s', None)
                if entry['attempts'] < self.max_attempts:
                    tried_dead.add(rep.name)
                    continue            # another replica may admit it
                return self._terminal(entry, 'rejected', e.reason)
            except ReplicaDied as e:
                tried_dead.add(rep.name)
                if not rep.alive() or rep.proc is not None:
                    # a stream that died on a live process means the
                    # process is wedged (hang) — kill it so its KV
                    # blocks and port free up before the retry lands
                    if rep.alive():
                        rep.kill()
                    self.mark_down(rep, cause='stream_lost')
                if entry['attempts'] >= self.max_attempts:
                    return self._terminal(entry, 'failed',
                                          f'replica_lost:{e}')
                if len(tokens) >= max_new_tokens:
                    # the dead replica had already emitted everything
                    return self._terminal(entry, 'finished',
                                          'max_tokens')

    def _terminal(self, entry, state, reason):
        with self._lock:
            assert entry['state'] == 'in_flight', \
                f"rid {entry['rid']} reached two terminal states"
            entry['state'] = state
            entry['reason'] = reason
        return entry

    def cancel(self, rid):
        """Forward a cancel to the replica currently streaming it."""
        entry = self.ledger.get(rid)
        if entry is None or entry['state'] != 'in_flight':
            return False
        for name in reversed(entry['replicas']):
            rep = self.replica(name)
            if rep is not None and rep.alive():
                try:
                    st, _doc = rep.post_json(f'/v1/cancel/{rid}')
                    return st == 200
                except OSError:
                    continue
        return False

    # -- supervision ---------------------------------------------------------
    def start_health_loop(self):
        self._health_thread = threading.Thread(
            target=self._health_loop, name='paddle-tpu-fleet-health',
            daemon=True)
        self._health_thread.start()
        return self

    def _health_loop(self):
        while not self._stop.wait(self.poll_s):
            self.health_tick()

    def health_tick(self):
        """ONE supervision pass: detect deaths, drain on latched
        alerts, retire drained replicas whose in-flight hit zero."""
        for rep in list(self.replicas):
            if rep.down:
                continue
            if not rep.alive():
                self.mark_down(rep, cause='process_exit')
                continue
            try:
                st = rep.status(timeout_s=2.0)
            except OSError:
                # unreachable but process alive: transient (status is
                # best-effort; the stream path has its own detection)
                continue
            alerts = [a for a in st.get('alerts', ())
                      if a in ('slo_breach', 'memory_pressure')]
            if alerts and not rep.draining:
                self.drain_replica(rep, cause=alerts[0])
            if rep.draining and st.get('in_flight', 1) == 0:
                self._fleet_event('retire', replica=rep.name)
                rep.down = True
                rep.kill(signal.SIGTERM)

    def stop(self, kill=True):
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        if kill:
            for rep in self.replicas + self.spares:
                rep.kill(signal.SIGTERM)
            for rep in self.replicas + self.spares:
                rep.reap()

    # -- status + invariants -------------------------------------------------
    def status(self):
        per = {}
        for rep in self.replicas + self.spares:
            role = 'spare' if rep in self.spares else 'active'
            doc = {'role': role, 'alive': rep.alive(),
                   'draining': rep.draining, 'down': rep.down}
            if rep.last_status:
                doc.update({k: rep.last_status.get(k) for k in
                            ('kv_occupancy', 'queue_depth', 'live',
                             'in_flight', 'shed_counts', 'alerts')})
            per[rep.name] = doc
        with self._lock:
            states = {}
            for e in self.ledger.values():
                states[e['state']] = states.get(e['state'], 0) + 1
        return {'ok': bool(self.dispatchable()),
                'replicas': per, 'ledger': states,
                'events': len(self.events)}

    def check_invariants(self):
        """Router invariants, checked like chaos I1–I7; returns the
        violation list (empty = green).

        R1  every accepted rid is terminal: finished | evicted(cause)
            | rejected(type) | failed(cause) — never in_flight once
            the fleet is quiet, never silently lost;
        R2  terminal exactly once (enforced at transition; re-checked
            here);
        R3  a finished entry holds exactly the tokens it delivered —
            contiguous offsets, no gaps or duplicates (at-most-once
            delivery made at-least-once by retry = exactly-once);
        R4  a rejected entry carries a typed RejectReason.
        """
        problems = []
        with self._lock:
            entries = list(self.ledger.values())
        for e in entries:
            if e['state'] == 'in_flight':
                problems.append(f"R1: rid {e['rid']} not terminal")
            elif e['state'] not in ('finished', 'evicted', 'rejected',
                                    'failed'):
                problems.append(
                    f"R2: rid {e['rid']} bad state {e['state']!r}")
            if e['state'] in ('evicted', 'failed') \
                    and not e.get('reason'):
                problems.append(
                    f"R1: rid {e['rid']} {e['state']} without cause")
            if e['state'] == 'rejected' \
                    and e.get('reason') not in RejectReason.ALL:
                problems.append(
                    f"R4: rid {e['rid']} untyped rejection "
                    f"{e.get('reason')!r}")
        return problems


class FleetFrontend:
    """The fleet's ONE public door — same posture/routes as the
    single-engine frontend, but every request runs through the
    router's dispatch/retry machinery."""

    def __init__(self, router, port=0, host='127.0.0.1'):
        self.router = router
        self.requested_port = int(port)
        self.host = host
        self._httpd = None
        self._thread = None
        self.port = None
        self.started_t = time.monotonic()

    def start(self):
        httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                    _FleetHandler)
        httpd.daemon_threads = True
        httpd.fleet = self
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, name='paddle-tpu-fleet-http',
            daemon=True)
        self._thread.start()
        return self

    @property
    def url(self):
        return (None if self.port is None
                else f'http://{self.host}:{self.port}')

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = 'HTTP/1.1'

    def log_message(self, *args):
        pass

    def _send_json(self, code, doc, headers=()):
        data = json.dumps(doc).encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type',
                         'application/json; charset=utf-8')
        self.send_header('Content-Length', str(len(data)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_GET(self):                   # noqa: N802 (http.server API)
        fleet = self.server.fleet
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/healthz':
                self._send_json(200, {
                    'ok': bool(fleet.router.dispatchable()),
                    'uptime_s': round(
                        time.monotonic() - fleet.started_t, 3)})
            elif path == '/status.json':
                self._send_json(200, fleet.router.status())
            else:
                self._send_json(404, {'error': 'not found'})
        except Exception as e:
            try:
                self._send_json(500, {'error': repr(e)[:200]})
            except Exception:
                pass

    def do_POST(self):                  # noqa: N802 (http.server API)
        fleet = self.server.fleet
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/v1/generate':
                self._generate(fleet)
            elif path.startswith('/v1/cancel/'):
                rid = path[len('/v1/cancel/'):]
                hit = fleet.router.cancel(rid)
                self._send_json(200 if hit else 404,
                                {'rid': rid, 'cancelled': bool(hit)})
            else:
                self._send_json(404, {'error': 'not found'})
        except Exception as e:
            try:
                self._send_json(500, {'error': repr(e)[:200]})
            except Exception:
                pass

    def _generate(self, fleet):
        n = int(self.headers.get('Content-Length') or 0)
        doc = json.loads(self.rfile.read(n).decode('utf-8')) if n \
            else {}
        prompt = doc.get('prompt')
        rid = doc.get('rid')
        if not prompt or not rid:
            self._send_json(400, {'error': 'bad_request',
                                  'detail': 'prompt and rid required'})
            return
        router = fleet.router
        if doc.get('stream', True):
            self.send_response(200)
            self.send_header('Content-Type', 'text/event-stream')
            self.send_header('Cache-Control', 'no-store')
            self.send_header('Transfer-Encoding', 'chunked')
            self.send_header('X-Request-Id', str(rid))
            self.end_headers()

            def chunk(data):
                self.wfile.write(b'%X\r\n%s\r\n' % (len(data), data))
                self.wfile.flush()

            def on_token(i, tok):
                chunk(b'data: ' + json.dumps(
                    {'i': i, 'token': tok}).encode('utf-8') + b'\n\n')

            try:
                entry = router.generate(
                    prompt, doc.get('max_new_tokens', 16), rid,
                    on_token=on_token,
                    deadline_s=doc.get('deadline_s'))
                chunk(b'data: ' + json.dumps(
                    {'done': True, 'rid': rid,
                     'n': len(entry['tokens']),
                     'state': entry['state'],
                     'reason': entry['reason'],
                     'retried': entry['retried']}).encode('utf-8')
                    + b'\n\n')
                chunk(b'')
            except (BrokenPipeError, ConnectionResetError, OSError):
                router.cancel(rid)
            except Exception as e:
                # a router bug must not strand the client mid-stream
                # with a silent EOF: terminalize the ledger entry and
                # send the terminal event the protocol promises
                entry = router.ledger.get(rid)
                if entry is not None \
                        and entry['state'] == 'in_flight':
                    router._terminal(entry, 'failed', repr(e)[:120])
                try:
                    chunk(b'data: ' + json.dumps(
                        {'done': True, 'rid': rid,
                         'n': len(entry['tokens']) if entry else 0,
                         'state': entry['state'] if entry
                         else 'failed',
                         'reason': entry['reason'] if entry
                         else repr(e)[:120]}).encode('utf-8')
                        + b'\n\n')
                    chunk(b'')
                except OSError:
                    pass
        else:
            try:
                entry = router.generate(
                    prompt, doc.get('max_new_tokens', 16), rid,
                    deadline_s=doc.get('deadline_s'))
            except ValueError as e:
                self._send_json(400, {'error': 'bad_request',
                                      'detail': str(e)})
                return
            code = 200
            body = {'rid': rid, 'tokens': entry['tokens'],
                    'state': entry['state'],
                    'reason': entry['reason'],
                    'retried': entry['retried']}
            headers = ()
            if entry['state'] == 'rejected':
                # same typed contract as the single-engine door:
                # machine-readable 'error' + Retry-After
                code = RejectReason.HTTP_STATUS.get(
                    entry['reason'], 503)
                body['error'] = entry['reason']
                retry = entry.get('retry_after_s')
                if retry:
                    body['retry_after_s'] = retry
                    headers = (('Retry-After',
                                str(max(1, int(round(retry)))),),)
            elif entry['state'] == 'failed':
                code = 502
            self._send_json(code, body, headers=headers)
