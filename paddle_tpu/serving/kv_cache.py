"""Paged KV cache: fixed-size blocks in one preallocated pool.

The serving engine never allocates per-sequence KV buffers.  Instead
each layer owns ONE device pool ``[num_blocks, num_heads, block_size,
head_dim]`` allocated once at engine construction, and every live
sequence owns an ordered list of pool blocks (its *block table*).
Admission allocates blocks, eviction frees them — memory churn is a
host-side free-list operation, never a device reallocation, so the
compiled decode step's shapes never change (the zero-recompile
property the whole serving surface is built on).

Block 0 is reserved as the **trash block**: inactive batch slots in a
compiled decode step point their tables at it so their (masked,
ignored) writes land somewhere harmless.  The allocator never hands
out block 0, and ``audit()`` proves the invariants the churn tests
lean on: a block is owned by at most one sequence, owned and free
sets never intersect, and nothing leaks.

Sharding: pools carry their heads on the ``tp`` mesh axis
(``ops.paged_attention.POOL_SPEC``) — the same Megatron head split as
the attention weights, applied by the engine's compiled steps via
``maybe_shard`` when a mesh is installed.
"""
import jax
import numpy as np

__all__ = ['PagedKVCache', 'PagedCacheView', 'TRASH_BLOCK',
           'blocks_for']

TRASH_BLOCK = 0


def blocks_for(num_positions, block_size):
    """Blocks needed to hold `num_positions` cache slots."""
    return -(-int(num_positions) // int(block_size))


@jax.tree_util.register_pytree_node_class
class PagedCacheView:
    """One layer's paged cache as seen by a compiled decode step.

    A pytree of (k_pool, v_pool, block_table, slots, lens):

    - ``slots`` [S]: the absolute position this step WRITES (each
      sequence's context length before its new token);
    - ``lens`` [S]: the valid length the attention READS (slots + 1 —
      the just-written token attends itself, exactly like the dense
      cached path's causal row).

    ``models/gpt.py::CausalSelfAttention`` dispatches on the ``paged``
    marker: a view threaded through ``caches=`` routes the block's
    attention to ``ops.paged_attention`` instead of the dense
    preallocated buffer.  Views flow through jit/scan like any other
    pytree; ``updated()`` is the functional write-back.
    """

    paged = True

    def __init__(self, k_pool, v_pool, block_table, slots, lens):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.block_table = block_table
        self.slots = slots
        self.lens = lens

    def updated(self, k_pool, v_pool):
        return PagedCacheView(k_pool, v_pool, self.block_table,
                              self.slots, self.lens)

    def tree_flatten(self):
        return ((self.k_pool, self.v_pool, self.block_table,
                 self.slots, self.lens), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


class PagedKVCache:
    """The pool + its host-side block allocator.

    Device state: ``pools`` — one (k_pool, v_pool) pair per layer,
    updated functionally by the engine after each compiled step
    (``set_pools``).  Host state: a free list and the per-sequence
    owned-block lists.  Allocation never partially succeeds: asking
    for more blocks than are free changes nothing and returns False.
    """

    def __init__(self, num_layers, num_heads, head_dim, *,
                 block_size, num_blocks, dtype=None, device_init=True):
        import jax.numpy as jnp
        if num_blocks < 2:
            raise ValueError('num_blocks must be >= 2 (block 0 is the '
                             'reserved trash block)')
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = dtype or jnp.float32
        if device_init:
            shape = (self.num_blocks, self.num_heads, self.block_size,
                     self.head_dim)
            self.pools = [(jnp.zeros(shape, self.dtype),
                           jnp.zeros(shape, self.dtype))
                          for _ in range(self.num_layers)]
        else:           # allocator-only (churn tests, audits)
            self.pools = None
        # LIFO free list: freshly freed blocks are the warmest
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._owned = {}            # seq_id -> [block ids, in order]
        self._high_water = 0        # max blocks ever simultaneously owned

    # -- allocator ----------------------------------------------------------
    @property
    def free_blocks(self):
        return len(self._free)

    def owned(self, seq_id):
        return list(self._owned.get(seq_id, ()))

    def can_cover(self, seq_id, num_positions):
        need = blocks_for(num_positions, self.block_size) \
            - len(self._owned.get(seq_id, ()))
        return need <= len(self._free)

    def ensure(self, seq_id, num_positions):
        """Grow `seq_id`'s block list to cover `num_positions` cache
        slots.  All-or-nothing: False (and no change) when the free
        list cannot cover the growth."""
        have = self._owned.setdefault(seq_id, [])
        need = blocks_for(num_positions, self.block_size) - len(have)
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for _ in range(need):
            have.append(self._free.pop())
        used = (self.num_blocks - 1) - len(self._free)
        if used > self._high_water:
            self._high_water = used
        return True

    @property
    def high_water_blocks(self):
        """Most blocks ever simultaneously owned (lifetime)."""
        return self._high_water

    def frag_report(self):
        """Pool-shape truth for the memory observatory: how BROKEN UP
        the pool is, not just how full.

        - ``free_runs`` / ``largest_free_run``: maximal runs of
          consecutive block ids in the free list — a pool can hold
          plenty of free blocks yet no contiguous span (irrelevant to
          correctness here, the classic fragmentation signal on
          allocators that ever need spans);
        - ``frag_frac``: 1 - largest_run/free (0 = one solid span);
        - ``seq_spread_max`` / ``seq_spread_mean``: per-sequence block
          spread, (max-min+1)/owned — how scattered each sequence's
          blocks sit in the pool (gather locality);
        - ``high_water_blocks``: lifetime peak of owned blocks (the
          number capacity planning actually wants).
        """
        usable = self.num_blocks - 1
        free = sorted(self._free)
        runs = []
        if free:
            start = prev = free[0]
            for b in free[1:]:
                if b == prev + 1:
                    prev = b
                    continue
                runs.append(prev - start + 1)
                start = prev = b
            runs.append(prev - start + 1)
        largest = max(runs) if runs else 0
        spreads = []
        for blocks in self._owned.values():
            if blocks:
                spreads.append(
                    (max(blocks) - min(blocks) + 1) / len(blocks))
        return {
            'num_blocks': self.num_blocks,
            'usable_blocks': usable,
            'free_blocks': len(free),
            'owned_blocks': usable - len(free),
            'owned_seqs': sum(1 for b in self._owned.values() if b),
            'free_runs': len(runs),
            'largest_free_run': largest,
            'frag_frac': round(1.0 - largest / len(free), 4)
            if free else 0.0,
            'seq_spread_max': round(max(spreads), 4) if spreads else 0.0,
            'seq_spread_mean': round(sum(spreads) / len(spreads), 4)
            if spreads else 0.0,
            'high_water_blocks': self._high_water,
        }

    def free_seq(self, seq_id):
        """Release every block `seq_id` owns; returns how many."""
        blocks = self._owned.pop(seq_id, [])
        self._free.extend(reversed(blocks))
        return len(blocks)

    def table_row(self, seq_id, width):
        """`seq_id`'s block table padded (with the trash block) to a
        fixed `width` — one row of a compiled step's table input."""
        blocks = self._owned.get(seq_id, ())
        if len(blocks) > width:
            raise ValueError(
                f'sequence {seq_id} owns {len(blocks)} blocks > table '
                f'width {width}')
        row = np.full((width,), TRASH_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        return row

    def audit(self):
        """Invariant check; returns a list of violation strings (empty
        = healthy).  The churn property tests call this after every
        mutation."""
        problems = []
        seen = {}
        for sid, blocks in self._owned.items():
            for b in blocks:
                if b == TRASH_BLOCK or not 0 < b < self.num_blocks:
                    problems.append(f'seq {sid} owns illegal block {b}')
                if b in seen:
                    problems.append(
                        f'block {b} aliased by seqs {seen[b]} and {sid}')
                seen[b] = sid
        free = set(self._free)
        if len(free) != len(self._free):
            problems.append('free list holds duplicates')
        both = free & set(seen)
        if both:
            problems.append(f'blocks {sorted(both)} both free and owned')
        if TRASH_BLOCK in free:
            problems.append('trash block on the free list')
        if len(free) + len(seen) != self.num_blocks - 1:
            problems.append(
                f'leak: {self.num_blocks - 1 - len(free) - len(seen)} '
                'block(s) neither free nor owned')
        return problems

    # -- device pools -------------------------------------------------------
    def set_pools(self, pools):
        """Functional write-back after a compiled step."""
        self.pools = list(pools)

    def layer_view(self, layer, block_tables, slots, lens):
        k, v = self.pools[layer]
        return PagedCacheView(k, v, block_tables, slots, lens)
