"""Serving engine: continuous batching over the paged KV cache.

Ties the whole PR-7..11 runway into live decode throughput:

- **paged KV cache** (``kv_cache.py``): fixed-size blocks in one
  preallocated pool, per-sequence block tables, heads sharded on the
  ``tp`` mesh axis;
- **ragged paged attention** (``ops/paged_attention.py``): the whole
  live set — every sequence at its own depth — decodes as ONE batched
  step, bit-exact vs the dense cached path;
- **continuous batching** (``scheduler.py``): admit/evict at every
  intervention, prefill into freed blocks, immediate backfill;
- **fused multi-step decode**: ``decode_span=K`` scans K decode steps
  inside one compiled module between scheduler interventions — the
  ROADMAP item-4 remainder lifted to the decode loop;
- **finite module set**: prompts bucket to the declared pow2 prompt
  set, the live batch pads to the declared pow2 batch set, admission
  bursts chunk to pow2 prefill batches — the whole serving surface is
  ``len(prompt_buckets) x len(prefill chunks) + len(batch_buckets)``
  compiled modules, built deterministically by ``warmup()`` and
  AOT-compiled by ``tools/precompile.py --serve`` (zero cold-start
  compiles), audited by ``check_ckpt --deep`` like any other
  precompile entry;
- **per-request SLOs**: watchdog-derived deadline budgets (PR 10)
  evict starved requests with a ``timeout`` telemetry event; TTFT /
  TPOT land on ``serve_request`` events and PR-8 profile windows
  attribute device time to exact intervention ids;
- **live observability** (``serve_metrics_port=`` /
  ``PADDLE_TPU_METRICS_PORT``, default OFF): a
  ``telemetry.live.LiveAggregator`` subscribed to the recorder
  stream keeps rolling TTFT/TPOT/occupancy windows, SLO/drift
  monitors emit ``slo_breach``/``drift_detected``, and a stdlib HTTP
  server exposes ``/healthz`` ``/status.json`` ``/metrics``
  ``/requests/<rid>`` — scrapes read host-side rolling state only,
  so a live scrape changes no numerics and adds no syncs (pinned by
  test and ``bench.py --obs-smoke``); every request carries a full
  lifecycle trace (``serve_trace`` events).

The decode math runs through the SAME ``GPTForCausalLM.prefill`` /
``decode_step`` functional forwards that ``generate()`` uses, so
greedy engine output is bit-exact with sequential batch-1 generate —
pinned by test and by ``bench.py --serve-smoke``.
"""
import json
import math
import time
import zlib

import numpy as np

from .. import nn
from ..core import compile_cache as _cc
from ..resilience.watchdog import resolve_watchdog
from .kv_cache import PagedKVCache, PagedCacheView, blocks_for
from .scheduler import ContinuousBatchingScheduler, Request, \
    RejectedRequest

__all__ = ['ServeConfig', 'ServingEngine', 'DecodeAuditLayer',
           'request_seed']


def request_seed(rid, engine_seed):
    """The per-request sampling base seed: a pure function of (rid,
    engine seed), so ANY engine sharing the config seed — including a
    surviving replica replaying a dead replica's request — derives the
    identical seed and continues the identical token stream (the
    ops/sampling per-position key discipline does the rest)."""
    return (zlib.crc32(str(rid).encode()) ^ int(engine_seed)) \
        & 0x7FFFFFFF


def _pow2_chain(lo, hi):
    out = []
    b = int(lo)
    while b < int(hi):
        out.append(b)
        b *= 2
    out.append(int(hi))
    return tuple(sorted(set(out)))


class ServeConfig:
    """Declared serving surface — every field below shapes the finite
    compiled-module set, so the config IS the AOT bucket declaration.
    """

    def __init__(self, *, block_size=16, max_slots=8, decode_span=4,
                 prompt_buckets=None, batch_buckets=None,
                 prefill_batch=8, max_model_len=None, temperature=0.0,
                 top_k=None, eos_id=None, num_blocks=None,
                 request_deadline_s=None, watchdog=None, profile=None,
                 seed=0, quantize=None):
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        self.decode_span = max(1, int(decode_span))
        # admission bursts prefill together: chunks of up to
        # `prefill_batch` same-bucket prompts share ONE dispatch
        # (modules per (prompt bucket, pow2 chunk) pair)
        self.prefill_batch = max(1, int(prefill_batch))
        self.prompt_buckets = None if prompt_buckets is None \
            else tuple(sorted(set(int(p) for p in prompt_buckets)))
        self.batch_buckets = batch_buckets
        self.max_model_len = max_model_len
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.num_blocks = num_blocks
        self.request_deadline_s = request_deadline_s
        self.watchdog = watchdog
        self.profile = profile
        self.seed = int(seed)
        # weight-only PTQ of the served model: None (full width),
        # 'int8' (Int8DynamicLinear) or 'int4' (packed nibbles) —
        # decode reads half-/quarter-width weights from HBM.  Part of
        # signature(), so quantized and full-width surfaces can never
        # share a compiled module.
        if quantize not in (None, 'int8', 'int4'):
            raise ValueError(f'ServeConfig quantize={quantize!r}: '
                             "expected None, 'int8' or 'int4'")
        self.quantize = quantize

    @classmethod
    def from_json(cls, path_or_dict):
        """A serving config file: the ServeConfig fields, plus
        ``model``/``model_kwargs`` keys the callers that build models
        from configs (tools/precompile.py --serve) consume."""
        if isinstance(path_or_dict, dict):
            doc = dict(path_or_dict)
        else:
            with open(path_or_dict) as f:
                doc = json.load(f)
        doc.pop('model', None)
        doc.pop('model_kwargs', None)
        return cls(**doc)

    def resolved(self, model_config):
        """Fill derived fields from the model config; returns self."""
        if self.max_model_len is None:
            self.max_model_len = int(model_config.max_seq_len)
        if self.prompt_buckets is None:
            hi = _cc.bucket_pow2(max(1, self.max_model_len // 2))
            self.prompt_buckets = _pow2_chain(min(8, hi), hi)
        if self.batch_buckets is None:
            self.batch_buckets = _pow2_chain(1, self.max_slots)
        else:
            self.batch_buckets = tuple(sorted(set(
                int(b) for b in self.batch_buckets)))
        if self.num_blocks is None:
            per_seq = blocks_for(self.max_model_len, self.block_size)
            self.num_blocks = self.max_slots * per_seq + 1
        if max(self.prompt_buckets) > self.max_model_len:
            raise ValueError(
                f'prompt bucket {max(self.prompt_buckets)} exceeds '
                f'max_model_len {self.max_model_len}')
        return self

    def signature(self):
        """The scalar fields that key compiled serving modules."""
        return tuple(sorted(
            (k, v if not isinstance(v, (list, tuple)) else tuple(v))
            for k, v in vars(self).items()
            if k not in ('watchdog', 'profile', 'request_deadline_s')))

    def to_dict(self):
        return {k: (list(v) if isinstance(v, tuple) else v)
                for k, v in vars(self).items()
                if k not in ('watchdog', 'profile')}


class ServingEngine:
    """Continuous-batching decode over one ``GPTForCausalLM``.

    ::

        eng = ServingEngine(model, ServeConfig(max_slots=64))
        eng.submit(prompt_ids, max_new_tokens=64)
        report = eng.run()          # drain; per-request TTFT/TPOT

    The model must be non-MoE (padded prefill rows would contend for
    expert capacity — same exemption as generate's pow2 bucketing).
    """

    def __init__(self, model, config=None, now_fn=time.monotonic,
                 serve_metrics_port=None, live_window_s=60.0):
        cfg = model.config
        if cfg.moe_num_experts > 0:
            raise ValueError('serving engine requires a non-MoE model '
                             '(see GPTForCausalLM._decode_bucket)')
        model.eval()
        self.model = model
        self.config = (config or ServeConfig()).resolved(cfg)
        applied = getattr(model, '_ptq_mode', None)
        if applied != (self.config.quantize or None):
            if applied is not None:
                # the swap dropped the float weights — an engine whose
                # declared signature disagrees with the model's actual
                # numerics would mis-key its compiled/AOT surface
                raise ValueError(
                    f'model was already PTQ-quantized ({applied!r}) '
                    f'but this config declares '
                    f'quantize={self.config.quantize!r}; build each '
                    'quantization mode from a FRESH model '
                    '(quantize_for_serving swaps weights in place)')
            # weight-only PTQ BEFORE functional_state: the swapped
            # Int8/Int4DynamicLinears' int8 buffers become the params/
            # buffers every prefill/decode module closes over, so the
            # whole compiled serving surface reads narrow weights from
            # HBM (and precompile --serve AOT-compiles the same —
            # quantize is part of the config signature)
            from ..quantization import quantize_for_serving
            quantize_for_serving(model, self.config.quantize)
        self.now_fn = now_fn
        # one engine-relative clock for EVERY timestamp (arrivals,
        # TTFT, deadlines) so offsets and wall reads never mix frames
        self._epoch = now_fn()
        self._clock = lambda: self.now_fn() - self._epoch
        self._params, self._buffers = model.functional_state()
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        self.cache = PagedKVCache(
            cfg.num_layers, nh, hd, block_size=self.config.block_size,
            num_blocks=self.config.num_blocks)
        self.scheduler = ContinuousBatchingScheduler(
            self.cache, max_slots=self.config.max_slots,
            batch_buckets=self.config.batch_buckets,
            bucket_fn=self.prompt_bucket,
            max_model_len=self.config.max_model_len,
            decode_span=self.config.decode_span,
            eos_id=self.config.eos_id, now_fn=self._clock)
        self.budget = resolve_watchdog(self.config.watchdog)
        self._modules = {}
        self.compile_count = 0
        self.interventions = 0
        self.decoded_tokens = 0
        self._rid = 0
        self._prefills = 0
        # first-token / rollback counts carried to the NEXT serve_step
        # event so the live plane's token accounting matches
        # decoded_tokens exactly (prefill-only interventions emit no
        # serve_step of their own)
        self._pending_prefilled = 0
        self._pending_discarded = 0
        from ..telemetry.profile import step_profiler
        self._prof = step_profiler(profile=self.config.profile,
                                   name='serve')
        # -- live observability plane (default OFF; see telemetry.live) --
        # the aggregator consumes the recorder's boundary-rate stream,
        # the monitors turn its windows into slo_breach/drift_detected
        # events, and the HTTP server exposes /metrics + /status.json.
        # Nothing here adds device syncs: scrapes read host-side
        # rolling state only.
        self.live = None
        self.monitors = []
        self.metrics_server = None
        from ..telemetry.httpd import resolve_metrics_port
        port = resolve_metrics_port(serve_metrics_port)
        if port is not None:
            from ..telemetry.live import LiveAggregator
            from ..telemetry.monitors import DriftMonitor, SLOMonitor
            from ..telemetry.httpd import MetricsServer
            self.live = LiveAggregator(
                window_s=live_window_s).install()
            self.live.live_trace_fn = self._live_trace
            # watchdog budgets feed the SLO thresholds: the same
            # Budget that derives per-request deadlines defines the
            # aggregate TTFT envelope
            self.monitors = [
                self.live.attach_monitor(SLOMonitor(budget=self.budget)),
                self.live.attach_monitor(DriftMonitor()),
            ]
            # memory-pressure sensing rides the same plane when the
            # PADDLE_TPU_MEMSTATS grammar declares a budget_gb
            from ..telemetry import memory as _mem
            mcfg = _mem.resolve_memstats()
            if mcfg is not None and mcfg.budget_bytes is not None:
                from ..telemetry.monitors import MemoryMonitor
                self.monitors.append(self.live.attach_monitor(
                    MemoryMonitor(config=mcfg)))
        # live memory sampler: default OFF, armed by the same env
        # (idempotent no-op when unset; daemon thread, boundary rate)
        from ..telemetry import memory as _mem_sampler
        _mem_sampler.ensure_sampler()
        if port is not None:
            try:
                self.metrics_server = MetricsServer(self.live,
                                                    port=port).start()
            except Exception:
                # a dead port (EADDRINUSE, ...) must not leak the
                # recorder subscription: the engine never constructs,
                # so close() could never run
                self.live.uninstall()
                self.live = None
                self.monitors = []
                raise

    # -- live plane ----------------------------------------------------------
    def _live_trace(self, rid):
        """telemetry.live hook: the in-flight trace for `rid` (the
        finished ones live in the aggregator's serve_trace store).
        Runs on a scrape thread while the engine thread mutates the
        scheduler structures — copying a deque mid-mutation raises
        RuntimeError, so retry a few times and give up with None (the
        next scrape sees a settled state)."""
        sched = self.scheduler
        for _ in range(4):
            try:
                reqs = list(sched.running) + list(sched.queue) \
                    + list(sched.finished)
                for req in reqs:
                    if req.rid == rid:
                        return [dict(row) for row in req.trace]
                return None
            except RuntimeError:    # mutated during iteration
                continue
        return None

    def close(self):
        """Tear down the live plane (HTTP server + stream
        subscription).  Idempotent; the engine itself stays usable."""
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        if self.live is not None:
            self.live.uninstall()

    # -- buckets -------------------------------------------------------------
    def prompt_bucket(self, t0):
        for b in self.config.prompt_buckets:
            if b >= t0:
                return b
        raise ValueError(
            f'prompt length {t0} exceeds the declared bucket set '
            f'{self.config.prompt_buckets}')

    def request_deadline_s(self, max_new_tokens):
        """Per-request completion budget: explicit config wins; an
        armed watchdog Budget (PR 10) derives prefill + per-span
        allowances; None = no deadline."""
        if self.config.request_deadline_s is not None:
            return float(self.config.request_deadline_s)
        if self.budget is None:
            return None
        return self.budget.request_budget_s(
            max_new_tokens, span=self.config.decode_span)

    # -- sampling (the shared ops/sampling discipline) -----------------------
    def _sample_fn(self):
        """``sample(logits[B, V], seeds[B], pos[B]) -> [B]``: each row
        draws with ``row_key(PRNGKey(seed), pos, 0)`` — the SAME key a
        batch-1 ``generate(seed=seed)`` would use at that absolute
        position, which is what makes sampled engine-vs-generate
        parity and mid-stream retry replay bit-exact (greedy ignores
        seeds/pos entirely)."""
        import jax
        from ..ops.sampling import make_row_sampler
        row_sample = make_row_sampler(self.config.temperature,
                                      self.config.top_k)

        def sample(logits, seeds, pos):
            bases = jax.vmap(jax.random.PRNGKey)(seeds)
            return row_sample(logits, bases, pos)

        return sample

    # -- compiled modules ----------------------------------------------------
    def _fingerprint(self, kind, **extra):
        pspec = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                             for n, v in self._params.items()))
        import jax.numpy as jnp
        return _cc.fingerprint(
            kind, config=tuple(sorted(vars(self.model.config).items())),
            serve=self.config.signature(), params=pspec,
            ids_dtype=str(jnp.asarray(0, jnp.int64).dtype), **extra)

    def _get_module(self, sig, build_fn, fp, example, name,
                    donate=()):
        mod = self._modules.get(sig)
        if mod is not None:
            return mod
        import jax
        # through_cache, not export-primary: the COLD path must keep
        # its donate_argnums — the pools are the whole KV cache and a
        # non-donating step memcpys them every call (a warm-start's
        # deserialized module forgoes donation, the documented PR-7
        # trade)
        jitted = _cc.through_cache(
            jax.jit(build_fn, donate_argnums=donate), example,
            fp=fp, name=name)
        self._modules[sig] = jitted
        self.compile_count += 1
        # memory observatory, armed-only (an extra lower+compile per
        # module): every serving module's XLA memory_analysis vs the
        # liveness prediction — through a FRESH jit, because a
        # warm-started exported call cannot re-lower
        from ..telemetry import memory as _mem
        if _mem.armed():
            _mem.maybe_note_compiled(name, jax.jit(build_fn), example,
                                     source='serving')
        return jitted

    def _prefill_build(self, P, B):
        """The prefill module body for one (prompt bucket, chunk)
        pair: ONE cached forward over B padded prompts, per-row first
        tokens sampled at each row's true length, every row's
        block-rounded KV scattered through its own block-table row."""
        import jax.numpy as jnp
        from ..parallel.api import maybe_shard
        from ..ops.paged_attention import POOL_SPEC
        model = self.model
        bs = self.config.block_size
        nblk = blocks_for(P, bs)
        Pc = nblk * bs
        sample = self._sample_fn()
        nh = model.config.num_heads
        hd = model.config.hidden_size // nh

        def prefill_fn(params, buffers, ids, t0, ks, vs, block_ids,
                       seeds):
            caches = model.init_decode_caches(B, Pc)
            logits, caches = model.prefill(
                params, buffers, ids, jnp.zeros((), jnp.int32), caches)
            lg = logits.value if hasattr(logits, 'value') else logits
            rows = jnp.take_along_axis(
                lg, (t0 - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                      # [B, V]
            # the first token's absolute position is t0-1 — the same
            # position generate's prefill samples at
            tok = sample(rows, seeds,
                         (t0 - 1).astype(jnp.int64))  # [B]
            new_ks, new_vs = [], []
            for (kbuf, vbuf), kp, vp in zip(caches, ks, vs):
                kbuf = kbuf.value if hasattr(kbuf, 'value') else kbuf
                vbuf = vbuf.value if hasattr(vbuf, 'value') else vbuf
                # [B, nh, Pc, hd] -> [B, nblk, nh, bs, hd] block rows
                kb = jnp.transpose(
                    kbuf.reshape(B, nh, nblk, bs, hd), (0, 2, 1, 3, 4))
                vb = jnp.transpose(
                    vbuf.reshape(B, nh, nblk, bs, hd), (0, 2, 1, 3, 4))
                kp = maybe_shard(kp, POOL_SPEC)
                vp = maybe_shard(vp, POOL_SPEC)
                new_ks.append(kp.at[block_ids].set(
                    kb.astype(kp.dtype)))
                new_vs.append(vp.at[block_ids].set(
                    vb.astype(vp.dtype)))
            return tok, tuple(new_ks), tuple(new_vs)

        return prefill_fn, nblk

    def _prefill_spec(self, P, B):
        """ONE source of truth for a prefill module's (fn, fp,
        example args, name, donate) — _prefill_module compiles it,
        precompile() AOT-exports it; they can never drift apart."""
        import jax.numpy as jnp
        fn, nblk = self._prefill_build(P, B)
        # keys= marks the per-request-position sampling discipline:
        # the module signature changed from one batch PRNGKey to
        # per-row seeds, and _fingerprint does not hash example avals
        # — without the marker a pre-discipline AOT artifact would
        # deserialize against the new call signature
        fp = self._fingerprint('serve-prefill', bucket=P, nblk=nblk,
                               chunk=B, keys='per-request-pos')
        ks, vs = (tuple(x) for x in zip(*self.cache.pools))
        example = (self._params, self._buffers,
                   jnp.zeros((B, P), jnp.int64),
                   jnp.full((B,), P, jnp.int32), ks, vs,
                   jnp.zeros((B, nblk), jnp.int32),
                   jnp.zeros((B,), jnp.int64))
        return fn, fp, example, f'serve.prefill[{P}x{B}]', (4, 5)

    def _prefill_module(self, P, B):
        sig = ('prefill', P, B)
        if sig in self._modules:
            return self._modules[sig]
        return self._get_module(sig, *self._prefill_spec(P, B))

    def _decode_build(self, S, K):
        """The fused decode module body for one (batch bucket, span):
        ``lax.scan`` over K single-token steps of the WHOLE live set —
        scheduler interventions only happen between these modules."""
        import jax
        import jax.numpy as jnp
        from ..parallel.api import maybe_shard
        from ..ops.paged_attention import POOL_SPEC
        model = self.model
        L = model.config.num_layers
        sample = self._sample_fn()
        eos = self.config.eos_id

        def decode_fn(params, buffers, ks, vs, tables, ctx, tok,
                      active, limit, seeds):
            ks = tuple(maybe_shard(k, POOL_SPEC) for k in ks)
            vs = tuple(maybe_shard(v, POOL_SPEC) for v in vs)

            def body(carry, _):
                tok, ctx, active, ks, vs = carry
                views = [PagedCacheView(ks[l], vs[l], tables, ctx,
                                        ctx + 1) for l in range(L)]
                logits, views = model.decode_step(
                    params, buffers, tok[:, None], ctx, views)
                lg = logits.value if hasattr(logits, 'value') else logits
                # each row samples at its OWN absolute position (the
                # input token's slot, = generate's scan carry p) with
                # its OWN request seed — scheduling history and batch
                # composition cannot perturb the stream
                ntok = sample(lg[:, -1], seeds, ctx)
                emitted_valid = active
                ntok = jnp.where(active, ntok, tok)
                nctx = ctx + active.astype(ctx.dtype)
                nactive = active & (nctx < limit)
                if eos is not None:
                    nactive = nactive & (ntok != eos)
                ks = tuple(v.k_pool for v in views)
                vs = tuple(v.v_pool for v in views)
                return (ntok, nctx, nactive, ks, vs), \
                    (ntok, emitted_valid)

            (tok, ctx, active, ks, vs), (toks, valid) = \
                jax.lax.scan(body, (tok, ctx, active, ks, vs),
                             None, length=K)
            return toks, valid, ks, vs

        return decode_fn

    def _decode_spec(self, S, K):
        """Same single-source contract as _prefill_spec, for the
        fused decode modules."""
        import jax.numpy as jnp
        fn = self._decode_build(S, K)
        fp = self._fingerprint('serve-decode', batch=S, span=K,
                               keys='per-request-pos')
        ks, vs = (tuple(x) for x in zip(*self.cache.pools))
        W = self.scheduler.table_width
        example = (self._params, self._buffers, ks, vs,
                   jnp.zeros((S, W), jnp.int32),
                   jnp.zeros((S,), jnp.int64),
                   jnp.zeros((S,), jnp.int64),
                   jnp.zeros((S,), bool),
                   jnp.zeros((S,), jnp.int64),
                   jnp.zeros((S,), jnp.int64))
        return fn, fp, example, f'serve.decode[{S}x{K}]', (2, 3)

    def _decode_module(self, S, K):
        sig = ('decode', S, K)
        if sig in self._modules:
            return self._modules[sig]
        return self._get_module(sig, *self._decode_spec(S, K))

    # -- request lifecycle ---------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, rid=None,
               arrival_t=None, deadline_s=None):
        from .. import telemetry
        if isinstance(prompt, Request):
            req = prompt
            if req.deadline_s is None:
                req.deadline_s = self.request_deadline_s(
                    req.max_new_tokens)
        else:
            self._rid += 1
            req = Request(
                rid if rid is not None else f'r{self._rid:05d}',
                prompt, max_new_tokens,
                arrival_t=(arrival_t if arrival_t is not None
                           else self._clock()),
                deadline_s=(deadline_s if deadline_s is not None
                            else self.request_deadline_s(
                                max_new_tokens)))
        if req.seed is None:
            # rid-derived, so the SAME request replayed on any replica
            # sharing the config seed samples the identical stream
            req.seed = request_seed(req.rid, self.config.seed)
        try:
            return self.scheduler.submit(req)
        except RejectedRequest as e:
            telemetry.event('serve_reject', rid=req.rid,
                            reason=e.reason, detail=e.detail)
            raise

    def cancel(self, rid, cause='cancelled'):
        """Evict one in-flight request (client cancel / disconnect):
        frees its blocks, rolls its decoded-token accounting back (the
        preemption path's discipline — a token nobody received must
        not count as delivered throughput), and emits the usual
        finished-request telemetry with the typed cause.  Returns True
        if the rid was live (queued or running), False otherwise."""
        sched = self.scheduler
        for req in list(sched.queue):
            if req.rid == rid:
                sched.queue.remove(req)
                sched.finish(req, cause)
                self._note_finished([req], self._clock())
                return True
        for req in list(sched.running):
            if req.rid == rid:
                rolled = len(req.tokens)
                self.decoded_tokens -= rolled
                self._pending_discarded += rolled
                sched.finish(req, cause)
                self._note_finished([req], self._clock())
                return True
        return False

    def _chunk_bucket(self, n):
        return _cc.bucket_pow2(n, cap=self.config.prefill_batch)

    def _prefill_dispatch(self, reqs):
        """Dispatch ONE batched prefill over a chunk of same-bucket
        admissions (async); the pools chain through donation so
        back-to-back chunks pipeline on the device.  Returns the
        un-synced first-token device array [chunk bucket]."""
        import jax.numpy as jnp
        P = reqs[0].prompt_bucket
        nblk = blocks_for(P, self.config.block_size)
        B = self._chunk_bucket(len(reqs))
        mod = self._prefill_module(P, B)
        ids = np.zeros((B, P), np.int64)
        t0s = np.ones((B,), np.int32)      # padding rows sample row 0
        blocks = np.zeros((B, nblk), np.int32)   # padding -> trash
        seeds = np.zeros((B,), np.int64)
        for i, req in enumerate(reqs):
            ids[i, :req.prompt.size] = req.prompt
            t0s[i] = req.prompt.size
            blocks[i] = self.cache.owned(req.rid)[:nblk]
            seeds[i] = req.seed or 0
        ks, vs = (tuple(x) for x in zip(*self.cache.pools))
        self._prefills += 1
        tok, ks, vs = mod(self._params, self._buffers,
                          jnp.asarray(ids), jnp.asarray(t0s),
                          ks, vs, jnp.asarray(blocks),
                          jnp.asarray(seeds))
        self.cache.set_pools(list(zip(ks, vs)))
        now = self._clock()
        for req in reqs:
            req.trace_note('prefill', now, bucket=P, chunk=B,
                           dispatch=self._prefills)
        return tok

    def _prefill_finish(self, req, tok):
        """Record one synced first token (TTFT anchor) and finish the
        request if it is already complete."""
        req.tokens.append(int(tok))
        req.first_token_t = self._clock()
        req.trace_note('first_token', req.first_token_t)
        self.decoded_tokens += 1
        if self.config.eos_id is not None \
                and req.tokens[-1] == self.config.eos_id:
            self.scheduler.finish(req, 'eos')
        elif len(req.tokens) >= req.max_new_tokens:
            self.scheduler.finish(req, 'max_tokens')
        return req

    def _decode(self, plan):
        import jax.numpy as jnp
        mod = self._decode_module(plan.batch, plan.span)
        ks, vs = (tuple(x) for x in zip(*self.cache.pools))
        toks, valid, ks, vs = mod(
            self._params, self._buffers, ks, vs,
            jnp.asarray(plan.tables), jnp.asarray(plan.ctx),
            jnp.asarray(plan.tok), jnp.asarray(plan.active),
            jnp.asarray(plan.limit), jnp.asarray(plan.seed))
        self.cache.set_pools(list(zip(ks, vs)))
        return toks, valid

    def _flush_pending_tokens(self, admitted, t_start):
        """A prefill-only intervention (nothing left running) emits a
        decode-less ``serve_step`` carrying the pending first-token /
        rollback counts, so no delivered token is ever lost to the
        early-return paths."""
        if not self._pending_prefilled and not self._pending_discarded:
            return
        from .. import telemetry
        sched = self.scheduler
        frag = self.cache.frag_report()
        telemetry.event('serve_step', intervention=self.interventions,
                        live=0, batch=0, span=0, decoded=0,
                        admitted=admitted, finished=0, preempted=0,
                        queued=len(sched.queue),
                        free_blocks=self.cache.free_blocks,
                        total_blocks=self.cache.num_blocks,
                        kv_frag_frac=frag['frag_frac'],
                        kv_largest_free_run=frag['largest_free_run'],
                        kv_high_water=frag['high_water_blocks'],
                        prefilled=self._pending_prefilled,
                        discarded=self._pending_discarded,
                        dur_s=round(self._clock() - t_start, 6))
        self._pending_prefilled = 0
        self._pending_discarded = 0

    def _note_finished(self, finished, now):
        from .. import telemetry
        for req in finished:
            rec = req.record(now)
            telemetry.event('serve_request', **rec)
            # the full lifecycle trail, ONE event per finished request
            # (bounded by request count, never by decode steps);
            # joinable with serve_request by rid, served live at
            # /requests/<rid>
            telemetry.event('serve_trace', rid=req.rid,
                            state=req.state, reason=req.reason,
                            prompt_bucket=req.prompt_bucket,
                            trace=[dict(r) for r in req.trace])
            if req.reason == 'deadline':
                telemetry.event(
                    'timeout', op='serve_request', rid=req.rid,
                    budget_s=req.deadline_s, age_s=rec['age_s'])

    # -- the intervention loop -----------------------------------------------
    def step(self, now=None):
        """ONE scheduler intervention: release/admit/prefill, decode
        the live set for one span, absorb, evict, backfill.  Returns
        the intervention's progress count (admissions + evictions +
        decoded tokens); 0 means nothing could move at all."""
        from .. import telemetry
        sched = self.scheduler
        now = self._clock() if now is None else now
        t_start = self._clock()
        breached = sched.check_deadlines(now)
        self._note_finished(breached, now)
        # two-phase admission: chunk same-bucket admissions into
        # batched prefill dispatches (device work pipelines through
        # the donated pool chain), then sync first tokens in order
        dispatched, chunk = [], []
        admitted = 0

        def flush():
            if chunk:
                dispatched.append((list(chunk),
                                   self._prefill_dispatch(chunk)))
                chunk.clear()

        while True:
            req = sched.admit_next()
            if req is None:
                break
            admitted += 1
            if chunk and (req.prompt_bucket != chunk[0].prompt_bucket
                          or len(chunk) >= self.config.prefill_batch):
                flush()
            chunk.append(req)
        flush()
        for reqs, toks_dev in dispatched:
            toks = np.asarray(toks_dev)
            for i, req in enumerate(reqs):
                self._prefill_finish(req, toks[i])
            self._pending_prefilled += len(reqs)
        self._note_finished(
            [r for reqs, _ in dispatched for r in reqs if r.done], now)
        progress = admitted + len(breached)
        if not sched.running:
            # everything finished at prefill (or evicted): flush the
            # carried first-token counts NOW — no later serve_step
            # will fire to carry them, and the live plane / run_report
            # token accounting must still match decoded_tokens
            self._flush_pending_tokens(admitted, t_start)
            return progress
        preempted = sched.reserve_span(sched.decode_span)
        # a preempted request's emitted tokens are discarded and will
        # be recomputed — un-count them so tokens_per_s only ever
        # counts DELIVERED tokens once
        discarded = sum(getattr(r, 'discarded_tokens', 0)
                        for r in preempted)
        self.decoded_tokens -= discarded
        self._pending_discarded += discarded
        plan = sched.plan()
        if plan is None:
            self._flush_pending_tokens(admitted, t_start)
            return progress
        toks_dev, valid_dev = self._decode(plan)
        if self._prof is not None:
            self._prof.observe(self.interventions * plan.span,
                               sync=toks_dev, span=plan.span)
        toks = np.asarray(toks_dev)
        valid = np.asarray(valid_dev)
        finished = sched.absorb(plan, toks, valid)
        self._note_finished(finished, self._clock())
        n = int(valid.sum())
        self.decoded_tokens += n
        self.interventions += 1
        frag = self.cache.frag_report()
        telemetry.event('serve_step', intervention=self.interventions,
                        live=len(plan.requests), batch=plan.batch,
                        span=plan.span, decoded=n, admitted=admitted,
                        finished=len(finished),
                        preempted=len(preempted),
                        queued=len(sched.queue),
                        free_blocks=self.cache.free_blocks,
                        total_blocks=self.cache.num_blocks,
                        kv_frag_frac=frag['frag_frac'],
                        kv_largest_free_run=frag['largest_free_run'],
                        kv_high_water=frag['high_water_blocks'],
                        prefilled=self._pending_prefilled,
                        discarded=self._pending_discarded,
                        dur_s=round(self._clock() - t_start, 6))
        self._pending_prefilled = 0
        self._pending_discarded = 0
        telemetry.add('serve.decoded_tokens', n)
        return progress + n

    def run(self, requests=(), timeout_s=None):
        """Drive to drain: submit `requests` honoring their
        ``arrival_t`` offsets (the Poisson load path), loop
        interventions until every request completes or evicts.
        Returns the report dict."""
        pending = sorted(requests, key=lambda r: r.arrival_t)
        sched = self.scheduler
        t0 = self.now_fn()
        start = self._clock()
        fin0 = len(sched.finished)
        tok0 = self.decoded_tokens
        # arrival offsets land on the engine clock at release time
        for r in pending:
            r.arrival_t = start + max(0.0, r.arrival_t)
        try:
            while pending or sched.queue or sched.running:
                now = self._clock()
                if timeout_s is not None and now - start > timeout_s:
                    timed_out = []
                    for req in list(sched.running) + list(sched.queue):
                        if req in sched.queue:
                            sched.queue.remove(req)
                        sched.finish(req, 'engine_timeout')
                        timed_out.append(req)
                    # same telemetry as any other eviction: these
                    # requests must not vanish from the live plane /
                    # run_report during exactly the overload that
                    # timed the run out
                    self._note_finished(timed_out, self._clock())
                    pending = []
                    break
                while pending and pending[0].arrival_t <= now:
                    self.submit(pending.pop(0))
                if not sched.queue and not sched.running:
                    if pending:
                        time.sleep(min(
                            0.05, max(0.0, pending[0].arrival_t - now)))
                    continue
                if self.step(now=now) == 0 and not sched.running \
                        and sched.queue:
                    # nothing live and the head of the queue cannot be
                    # admitted even into an empty pool: it can never
                    # run — evict instead of spinning forever
                    req = sched.queue.popleft()
                    sched.finish(req, 'oom')
                    self._note_finished([req], self._clock())
        finally:
            if self._prof is not None:
                self._prof.close()
        return self.report(wall_s=self.now_fn() - t0,
                           finished_from=fin0, tokens_from=tok0)

    # -- reporting / stats ---------------------------------------------------
    def report(self, wall_s=None, finished_from=0, tokens_from=0):
        """Aggregate latency/throughput report — over the whole engine
        life by default, or over one run()'s window (its requests and
        its decoded tokens) when the slicing args are given."""
        now = self._clock()
        sched = self.scheduler
        recs = [r.record(now) for r in sched.finished[finished_from:]]
        ttfts = sorted(r['ttft_s'] for r in recs
                       if r['ttft_s'] is not None)
        tpots = [r['tpot_s'] for r in recs if r['tpot_s'] is not None]

        def pct(sorted_vals, q):
            if not sorted_vals:
                return None
            i = min(len(sorted_vals) - 1,
                    int(math.ceil(q * len(sorted_vals))) - 1)
            return sorted_vals[max(0, i)]

        decoded = self.decoded_tokens - tokens_from
        return {
            'requests': recs,
            'counters': dict(sched.counters),
            'decoded_tokens': decoded,
            'interventions': self.interventions,
            'wall_s': wall_s,
            'tokens_per_s': decoded / wall_s if wall_s else None,
            'ttft_p50_s': pct(ttfts, 0.50),
            'ttft_p99_s': pct(ttfts, 0.99),
            'tpot_mean_s': (sum(tpots) / len(tpots)) if tpots else None,
            'compile_count': self.compile_count,
            'modules': sorted(str(s) for s in self._modules),
            'audit': sched.audit(),
        }

    def stats(self):
        return {'compile_count': self.compile_count,
                'modules': sorted(str(s) for s in self._modules),
                'interventions': self.interventions,
                'decoded_tokens': self.decoded_tokens,
                'free_blocks': self.cache.free_blocks,
                'kv_frag': self.cache.frag_report()}

    # -- AOT / declared bucket set -------------------------------------------
    def bucket_set(self):
        """The declared compiled-module signatures — what
        ``tools/precompile.py --serve`` AOT-compiles and what the lint
        gate sweeps."""
        c = self.config
        return {'prompt_buckets': list(c.prompt_buckets),
                'batch_buckets': list(c.batch_buckets),
                'prefill_chunks': list(_pow2_chain(1, c.prefill_batch)),
                'decode_span': c.decode_span,
                'block_size': c.block_size,
                'max_slots': c.max_slots,
                'max_model_len': c.max_model_len}

    def warmup(self):
        """Build AND execute every declared module once, on inert
        inputs (all rows point at the trash block, decode lanes
        inactive), so the call-path XLA compile happens NOW — the
        deterministic cold-start a serving deploy pays once, after
        which run() never compiles or first-call-stalls regardless of
        which buckets the live traffic hits.  Returns stats()."""
        import jax.numpy as jnp
        params, buffers = self._params, self._buffers
        for P in self.config.prompt_buckets:
            nblk = blocks_for(P, self.config.block_size)
            for B in _pow2_chain(1, self.config.prefill_batch):
                mod = self._prefill_module(P, B)
                ks, vs = (tuple(x) for x in zip(*self.cache.pools))
                tok, ks, vs = mod(
                    params, buffers, jnp.zeros((B, P), jnp.int64),
                    jnp.full((B,), P, jnp.int32), ks, vs,
                    jnp.zeros((B, nblk), jnp.int32),
                    jnp.zeros((B,), jnp.int64))
                self.cache.set_pools(list(zip(ks, vs)))
                np.asarray(tok)
        W = self.scheduler.table_width
        for S in self.config.batch_buckets:
            mod = self._decode_module(S, self.config.decode_span)
            ks, vs = (tuple(x) for x in zip(*self.cache.pools))
            toks, _valid, ks, vs = mod(
                params, buffers, ks, vs,
                jnp.zeros((S, W), jnp.int32),
                jnp.zeros((S,), jnp.int64), jnp.zeros((S,), jnp.int64),
                jnp.zeros((S,), bool), jnp.zeros((S,), jnp.int64),
                jnp.zeros((S,), jnp.int64))
            self.cache.set_pools(list(zip(ks, vs)))
            np.asarray(toks)
        if self.live is not None:
            # every declared module just built+ran: compiles from here
            # on are anomalies the drift monitor flags
            self.live.mark_steady()
        return self.stats()

    def precompile(self):
        """Export + AOT-compile every declared serving module into the
        persistent compile cache (PR 7); returns sidecar entries for
        ``compile_cache.write_precompile_manifest``.  A later engine in
        a fresh process deserializes instead of tracing."""
        import jax
        entries, errors = [], {}
        if not _cc.enabled():
            return entries, {'cache': 'compile cache disabled'}
        specs = [(f'serve-prefill bucket {P} chunk {B}',
                  lambda P=P, B=B: self._prefill_spec(P, B))
                 for P in self.config.prompt_buckets
                 for B in _pow2_chain(1, self.config.prefill_batch)]
        specs += [(f'serve-decode batch {S} span '
                   f'{self.config.decode_span}',
                   lambda S=S: self._decode_spec(
                       S, self.config.decode_span))
                  for S in self.config.batch_buckets]
        for desc, make in specs:
            try:
                # the EXACT spec the runtime modules compile from —
                # one source, so the AOT artifact can never drift
                fn, fp, example, name, _donate = make()
                if fp is None:
                    errors[desc] = 'no fingerprint'
                elif _cc.get('exec', fp) is None and \
                        not _cc.store_executable(
                            fp, jax.jit(fn), example, name=name,
                            aot_compile=True):
                    errors[desc] = 'export failed'
                else:
                    entries.append({'tier': 'exec', 'fingerprint': fp,
                                    'description': desc})
            except Exception as e:
                errors[desc] = repr(e)
        return entries, errors


class DecodeAuditLayer(nn.Layer):
    """One paged decode step as an ``analysis.targets`` audit surface:
    a Layer whose forward runs the serving engine's per-step math
    (paged views + ragged attention over the pool) so ``tpu_lint
    --hlo``/``--plan`` can lower and audit the serving path with the
    same machinery as the train steps."""

    def __init__(self, model):
        super().__init__()
        self.model = model

    def forward(self, tok, k_pools, v_pools, tables, ctx):
        import jax.numpy as jnp

        def raw(t):
            return t.value if hasattr(t, 'value') else t

        kp, vp = raw(k_pools), raw(v_pools)
        tbl, cx = raw(tables), raw(ctx)
        L = self.model.config.num_layers
        views = [PagedCacheView(kp[l], vp[l], tbl, cx, cx + 1)
                 for l in range(L)]
        logits, views = self.model(tok, caches=views, pos=cx)
        nk = jnp.stack([raw(v.k_pool) for v in views])
        nv = jnp.stack([raw(v.v_pool) for v in views])
        return logits, nk, nv
