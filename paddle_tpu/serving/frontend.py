"""Serving front door: the streaming HTTP request plane over ONE
:class:`ServingEngine`.

The PR-13 HTTP plane is metrics-only; this is the plane clients talk
to — same stdlib posture as ``telemetry/httpd.py`` (ThreadingHTTPServer,
daemon threads, 127.0.0.1 default bind, default OFF: nothing binds
unless a caller constructs one):

* ``POST /v1/generate``      — submit; token-at-a-time SSE stream
  (``stream: true``, chunked transfer) or one JSON document
* ``POST /v1/cancel/<rid>``  — evict an in-flight request
* ``POST /admin/drain``      — stop admitting (typed 503s), finish
  in-flight; the router's replica-swap lever
* ``GET  /healthz``          — liveness + draining flag
* ``GET  /status.json``      — live occupancy/queue-depth snapshot
  (what the router's dispatch reads)

**Admission control** degrades overload predictably instead of OOMing
or starving: a bounded admission queue and the scheduler's own
worst-case-block preflight shed excess load with TYPED rejections —
the :class:`~.scheduler.RejectReason` taxonomy (429 ``queue_full``,
503 ``draining``, 413 ``exceeds_pool``), each carrying a
``Retry-After`` derived from live TPOT, each emitting a
``serve_reject`` event.  A client that disconnects mid-stream (or
cancels) has its request EVICTED and its delivered-token accounting
rolled back through the preemption path (``ServingEngine.cancel``),
so an abandoned stream frees KV blocks at the next intervention
instead of decoding to its limit.

**Threading contract**: the scheduler/engine structures are not
thread-safe, so ONE daemon engine thread owns every engine mutation
(an intervention loop around ``engine.step()``); HTTP handler threads
talk to it through a control queue (submit/cancel ops, each acked via
an Event) and read request progress through ``Request.tokens`` —
CPython list appends are atomic, and the reader only indexes below
``len``, so streaming never takes the engine's locks and a slow
client never stalls decode (tokens buffer host-side; TCP backpressure
stays in the handler thread).
"""
import json
import queue
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .scheduler import RejectReason, RejectedRequest

__all__ = ['ServingFrontend', 'FRONTEND_HOST_ENV']

FRONTEND_HOST_ENV = 'PADDLE_TPU_FRONTEND_HOST'


class _Op:
    """One control-queue operation (HTTP thread -> engine thread)."""

    def __init__(self, kind, **kw):
        self.kind = kind
        self.kw = kw
        self.done = threading.Event()
        self.result = None
        self.error = None

    def finish(self, result=None, error=None):
        self.result, self.error = result, error
        self.done.set()

    def wait(self, timeout_s):
        if not self.done.wait(timeout_s):
            raise TimeoutError(f'engine loop did not ack {self.kind}')
        if self.error is not None:
            raise self.error
        return self.result


class ServingFrontend:
    """One engine, one door.

    ::

        fe = ServingFrontend(engine, port=0).start()
        ... POST http://127.0.0.1:{fe.port}/v1/generate ...
        fe.drain(); fe.stop()

    ``max_queue`` bounds ADMISSION (scheduler queue + in-flight
    control ops); past it new work sheds with 429 ``queue_full``.
    ``port=0`` binds an ephemeral port (tests/fleet workers).
    """

    def __init__(self, engine, port=0, host=None, max_queue=None,
                 poll_s=0.002):
        import os
        self.engine = engine
        self.requested_port = int(port)
        self.host = host or os.environ.get(FRONTEND_HOST_ENV,
                                           '127.0.0.1')
        self.max_queue = (2 * engine.config.max_slots
                          if max_queue is None else int(max_queue))
        self.poll_s = float(poll_s)
        self.draining = False
        self.shed_counts = {r: 0 for r in RejectReason.ALL}
        # alerts forced through POST /admin/alert/<kind> — the chaos
        # drill's deterministic stand-in for a latched monitor (the
        # real SLOMonitor/MemoryMonitor latches ride the same status
        # field when the live plane is armed)
        self.forced_alerts = set()
        self._requests = {}          # rid -> Request (every admitted)
        self._ops = queue.Queue()
        self._pending_submits = 0    # ops in flight toward the queue
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._httpd = None
        self._http_thread = None
        self.port = None
        self.started_t = time.monotonic()

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._httpd is not None:
            return self
        self._thread = threading.Thread(
            target=self._engine_loop, name='paddle-tpu-frontdoor-engine',
            daemon=True)
        self._thread.start()
        httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        httpd.frontend = self
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=httpd.serve_forever, name='paddle-tpu-frontdoor-http',
            daemon=True)
        self._http_thread.start()
        return self

    @property
    def url(self):
        return (None if self.port is None
                else f'http://{self.host}:{self.port}')

    def drain(self):
        """Stop admitting (new submissions shed 503 ``draining``);
        in-flight requests run to completion.  Idempotent."""
        self.draining = True
        return self

    def stop(self, timeout_s=10.0):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=timeout_s)
            self._http_thread = None
        self.engine.close()

    # -- the engine thread ---------------------------------------------------
    def _engine_loop(self):
        """The ONLY thread that mutates the engine: drain control
        ops, run one intervention, repeat.  Mirrors ``engine.run()``'s
        drain loop but never exits on an empty schedule — the door
        stays open until stop()."""
        eng = self.engine
        sched = eng.scheduler
        while not self._stop.is_set():
            ran_op = False
            while True:
                try:
                    op = self._ops.get_nowait()
                except queue.Empty:
                    break
                ran_op = True
                try:
                    if op.kind == 'submit':
                        try:
                            op.finish(eng.submit(**op.kw))
                        finally:
                            with self._lock:
                                self._pending_submits -= 1
                    elif op.kind == 'cancel':
                        op.finish(eng.cancel(**op.kw))
                    else:
                        op.finish(error=ValueError(op.kind))
                except Exception as e:      # pragma: no cover - relay
                    op.finish(error=e)
            if not sched.queue and not sched.running:
                if not ran_op:
                    time.sleep(self.poll_s)
                continue
            if eng.step() == 0 and not sched.running and sched.queue:
                # the head of the queue can never be admitted even
                # into an empty pool (engine.run()'s livelock guard —
                # preflight makes this near-unreachable, but a guard
                # that spins forever is worse than one that evicts)
                req = sched.queue.popleft()
                sched.finish(req, 'oom')
                eng._note_finished([req], eng._clock())

    # -- admission (HTTP threads) --------------------------------------------
    def submit(self, prompt, max_new_tokens, rid=None,
               deadline_s=None):
        """Typed admission: sheds BEFORE touching the engine thread
        when draining or the admission queue is full; the engine's own
        preflight sheds ``exceeds_pool``.  Returns the live Request;
        raises RejectedRequest."""
        from .. import telemetry
        if self.draining:
            self._shed(RejectReason.DRAINING, rid,
                       'front door is draining')
        with self._lock:
            depth = (len(self.engine.scheduler.queue)
                     + self._pending_submits)
            if depth >= self.max_queue:
                pass                    # shed outside the lock
            else:
                self._pending_submits += 1
                depth = None
        if depth is not None:
            self._shed(RejectReason.QUEUE_FULL, rid,
                       f'admission queue at capacity ({depth} >= '
                       f'{self.max_queue})')
        op = _Op('submit', prompt=np.asarray(prompt, np.int64),
                 max_new_tokens=int(max_new_tokens), rid=rid,
                 deadline_s=deadline_s)
        self._ops.put(op)
        try:
            req = op.wait(timeout_s=30.0)
        except RejectedRequest as e:
            # engine.submit already emitted serve_reject; count it
            self.shed_counts[e.reason] += 1
            raise
        self._requests[req.rid] = req
        telemetry.add('frontdoor.admitted', 1)
        return req

    def _shed(self, reason, rid, detail):
        from .. import telemetry
        self.shed_counts[reason] += 1
        retry = self.retry_after_s()
        telemetry.event('serve_reject', rid=rid, reason=reason,
                        detail=detail, retry_after_s=retry)
        raise RejectedRequest(reason, detail, rid=rid)

    def cancel(self, rid, cause='cancelled'):
        """Evict an in-flight request from any thread (handler path
        for /v1/cancel and for detected client disconnects)."""
        op = _Op('cancel', rid=rid, cause=cause)
        self._ops.put(op)
        try:
            return bool(op.wait(timeout_s=30.0))
        except TimeoutError:
            return False

    def get_request(self, rid):
        return self._requests.get(rid)

    # -- load-shedding arithmetic --------------------------------------------
    def _recent_tpot_s(self, tail=16):
        """Live TPOT estimate from the most recent finished requests
        (host-side fields only — no device sync, no aggregator
        dependency)."""
        vals = []
        for req in self.engine.scheduler.finished[-tail:]:
            if (req.finish_t is not None
                    and req.first_token_t is not None
                    and len(req.tokens) > 1):
                vals.append((req.finish_t - req.first_token_t)
                            / (len(req.tokens) - 1))
        return (sum(vals) / len(vals)) if vals else None

    def retry_after_s(self):
        """``Retry-After`` for a typed rejection: the backlog's
        decode work at the live TPOT, spread over the slots — i.e.
        roughly when a queue position frees up.  Falls back to the
        watchdog step allowance, then a constant, when no TPOT has
        been observed yet."""
        eng = self.engine
        tpot = self._recent_tpot_s()
        if tpot is None:
            if eng.budget is not None:
                tpot = eng.budget.effective_step_s() \
                    / max(1, eng.config.decode_span)
            else:
                tpot = 0.05
        backlog = sum(r.max_new_tokens for r in
                      list(eng.scheduler.queue))
        backlog += sum(max(0, r.max_new_tokens - len(r.tokens))
                       for r in list(eng.scheduler.running))
        est = tpot * backlog / max(1, eng.config.max_slots)
        return round(min(30.0, max(0.05, est)), 3)

    # -- status (HTTP threads; best-effort reads) ----------------------------
    def alerts(self):
        """Latched alert kinds the router's supervision acts on
        (drain + warm-spare promotion): the live plane's monitor
        latches — SLOMonitor -> ``slo_breach``, MemoryMonitor ->
        ``memory_pressure`` — plus any drill-forced kinds."""
        out = set(self.forced_alerts)
        for mon in self.engine.monitors:
            if not getattr(mon, '_latched', None):
                continue
            name = type(mon).__name__
            if name == 'SLOMonitor':
                out.add('slo_breach')
            elif name == 'MemoryMonitor':
                out.add('memory_pressure')
            elif name == 'DriftMonitor':
                out.add('drift_detected')
        return sorted(out)

    def status(self):
        eng = self.engine
        sched = eng.scheduler
        total = eng.cache.num_blocks
        free = eng.cache.free_blocks
        return {
            'ok': True,
            'draining': bool(self.draining),
            'uptime_s': round(time.monotonic() - self.started_t, 3),
            'queue_depth': len(sched.queue),
            'live': len(sched.running),
            'in_flight': len(sched.queue) + len(sched.running),
            'max_queue': self.max_queue,
            'max_slots': eng.config.max_slots,
            'free_blocks': free,
            'total_blocks': total,
            'kv_occupancy': round(1.0 - free / total, 4) if total
            else None,
            'shed_counts': dict(self.shed_counts),
            'alerts': self.alerts(),
            'counters': dict(sched.counters),
            'decoded_tokens': eng.decoded_tokens,
            'interventions': eng.interventions,
            'tpot_s': self._recent_tpot_s(),
            'retry_after_s': self.retry_after_s(),
        }


class _Handler(BaseHTTPRequestHandler):
    # the server instance carries .frontend (set by ServingFrontend)
    protocol_version = 'HTTP/1.1'

    def log_message(self, *args):       # no stderr chatter per request
        pass

    # -- plumbing ------------------------------------------------------------
    def _send_json(self, code, doc, headers=()):
        data = json.dumps(doc).encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type',
                         'application/json; charset=utf-8')
        self.send_header('Content-Length', str(len(data)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _read_body(self):
        n = int(self.headers.get('Content-Length') or 0)
        raw = self.rfile.read(n) if n else b''
        if not raw:
            return {}
        return json.loads(raw.decode('utf-8'))

    def _reject(self, exc, retry_after_s):
        self._send_json(
            exc.http_status,
            {'error': exc.reason, 'detail': exc.detail,
             'rid': exc.rid, 'retry_after_s': retry_after_s},
            headers=(('Retry-After',
                      str(max(1, int(round(retry_after_s)))),),))

    # -- routes --------------------------------------------------------------
    def do_GET(self):                   # noqa: N802 (http.server API)
        fe = self.server.frontend
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/healthz':
                self._send_json(200, {
                    'ok': True, 'draining': bool(fe.draining),
                    'uptime_s': round(
                        time.monotonic() - fe.started_t, 3)})
            elif path == '/status.json':
                self._send_json(200, fe.status())
            else:
                self._send_json(404, {'error': 'not found'})
        except Exception as e:          # a probe must never crash it
            try:
                self._send_json(500, {'error': repr(e)[:200]})
            except Exception:
                pass

    def do_POST(self):                  # noqa: N802 (http.server API)
        fe = self.server.frontend
        path = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if path == '/v1/generate':
                self._generate(fe)
            elif path.startswith('/v1/cancel/'):
                rid = path[len('/v1/cancel/'):]
                hit = fe.cancel(rid, cause='cancelled')
                self._send_json(200 if hit else 404,
                                {'rid': rid, 'cancelled': bool(hit)})
            elif path == '/admin/drain':
                fe.drain()
                self._send_json(200, {'draining': True,
                                      'in_flight': fe.status()
                                      ['in_flight']})
            elif path.startswith('/admin/alert/'):
                kind = path[len('/admin/alert/'):]
                fe.forced_alerts.add(kind)
                self._send_json(200, {'alerts': fe.alerts()})
            else:
                self._send_json(404, {'error': 'not found'})
        except RejectedRequest as e:
            self._reject(e, fe.retry_after_s())
        except Exception as e:
            try:
                self._send_json(500, {'error': repr(e)[:200]})
            except Exception:
                pass

    # -- generate ------------------------------------------------------------
    def _generate(self, fe):
        doc = self._read_body()
        prompt = doc.get('prompt')
        if not prompt:
            self._send_json(400, {'error': 'bad_request',
                                  'detail': 'prompt required'})
            return
        req = fe.submit(prompt, int(doc.get('max_new_tokens', 16)),
                        rid=doc.get('rid'),
                        deadline_s=doc.get('deadline_s'))
        if doc.get('stream', True):
            self._stream(fe, req)
        else:
            while not req.done:
                time.sleep(fe.poll_s)
            self._send_json(200, {
                'rid': req.rid, 'tokens': list(req.tokens),
                'state': req.state, 'reason': req.reason})

    def _stream(self, fe, req):
        """Token-at-a-time SSE over chunked transfer.  At-most-once
        delivery: every event carries the token's stream offset ``i``,
        so a router that lost this replica mid-stream knows exactly
        which prefix its client already holds.  A failed write means
        the client is gone — evict the request and roll its tokens
        back."""
        self.send_response(200)
        self.send_header('Content-Type', 'text/event-stream')
        self.send_header('Cache-Control', 'no-store')
        self.send_header('Transfer-Encoding', 'chunked')
        self.send_header('X-Request-Id', str(req.rid))
        self.end_headers()

        def chunk(data):
            self.wfile.write(b'%X\r\n%s\r\n' % (len(data), data))
            self.wfile.flush()

        def event(doc):
            chunk(b'data: ' + json.dumps(doc).encode('utf-8')
                  + b'\n\n')

        def client_gone():
            # a failed write only surfaces once kernel buffers fill —
            # a short stream fits entirely in them and the dead
            # client would never be noticed.  An SSE client sends
            # nothing after the request, so readable == EOF (or
            # pipelined garbage; either way this stream is over).
            import select
            r, _w, _x = select.select([self.connection], [], [], 0)
            if not r:
                return False
            try:
                return self.connection.recv(
                    1, socket.MSG_PEEK) == b''
            except OSError:
                return True

        sent = 0
        try:
            while True:
                n = len(req.tokens)
                while sent < n:
                    event({'i': sent, 'token': int(req.tokens[sent])})
                    sent += 1
                if req.done and sent >= len(req.tokens):
                    break
                if client_gone():
                    raise ConnectionResetError('client closed stream')
                time.sleep(fe.poll_s)
            event({'done': True, 'rid': req.rid, 'n': sent,
                   'state': req.state, 'reason': req.reason})
            chunk(b'')                  # terminal chunk
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client went away mid-stream: evict + roll back —
            # an abandoned request must not decode to its limit
            if not req.done:
                fe.cancel(req.rid, cause='client_disconnect')
