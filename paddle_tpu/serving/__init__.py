"""paddle_tpu.serving — the production inference runtime.

Continuous batching + paged KV-cache decode over the sharded engine
(ROADMAP item 1): a request scheduler that admits/evicts sequences at
every decode intervention, a paged KV cache (fixed-size blocks, one
preallocated pool, per-sequence block tables), a ragged paged
attention op (``ops/paged_attention.py``, RPA-style per PAPERS.md
arxiv 2604.15464) and a serving engine with fused multi-step decode —
all over a declared pow2 bucket set so ``tools/precompile.py --serve``
AOT-compiles the whole surface at deploy time.

    from paddle_tpu.serving import ServingEngine, ServeConfig
    eng = ServingEngine(model, ServeConfig(max_slots=64))
    eng.submit(prompt_ids, max_new_tokens=64)
    report = eng.run()

Additive: ``GPTForCausalLM.generate`` is unchanged (and bit-exact
with the engine's greedy decode by test).
"""
from .kv_cache import PagedKVCache, PagedCacheView   # noqa: F401
from .scheduler import (                             # noqa: F401
    ContinuousBatchingScheduler, DecodePlan, Request, RejectReason,
    RejectedRequest)
from .loadgen import poisson_requests                # noqa: F401
from .engine import (                                # noqa: F401
    DecodeAuditLayer, ServeConfig, ServingEngine, request_seed)

__all__ = ['PagedKVCache', 'PagedCacheView', 'Request', 'DecodePlan',
           'ContinuousBatchingScheduler', 'poisson_requests',
           'ServeConfig', 'ServingEngine', 'DecodeAuditLayer',
           'RejectReason', 'RejectedRequest', 'request_seed']
