"""Continuous-batching request scheduler.

The unit of scheduling is the *decode intervention*: between two
interventions the engine runs one compiled multi-step decode over the
live set; at each intervention the scheduler

- releases newly-arrived requests from the load/clock into the queue,
- **admits** queued requests while a batch slot AND enough free KV
  blocks exist (prefill happens immediately on admission),
- **evicts** finished requests (EOS, max tokens, deadline breach) and
  frees their blocks — the freed capacity backfills from the queue at
  the SAME intervention, so the batch never idles half-empty while
  work queues,
- **reserves** blocks so every live sequence can absorb the next
  fused decode span without any allocation inside the compiled step.

When reservation cannot cover the live set (pool pressure), the
youngest running request is *preempted* back to the queue — its
blocks free immediately and it re-prefills later (recompute-style
preemption, the simple/robust vLLM policy).

All host-side bookkeeping: the scheduler never touches a device
array.  The engine asks for a :class:`DecodePlan` (padded numpy
tables/lengths bucketed to the declared pow2 batch set) and reports
back the decoded tokens.
"""
import collections
import time

import numpy as np

from .kv_cache import TRASH_BLOCK, blocks_for

__all__ = ['Request', 'DecodePlan', 'ContinuousBatchingScheduler',
           'RejectReason', 'RejectedRequest']


class RejectReason:
    """The typed load-shedding taxonomy — ONE source of truth shared
    by ``ServingEngine.submit`` (EXCEEDS_POOL) and the serving front
    door (QUEUE_FULL/DRAINING), so the engine, the HTTP plane, the
    router and run_report can never disagree on what a rejection is.
    Each reason maps to the HTTP status the frontend returns."""

    EXCEEDS_POOL = 'exceeds_pool'   # can NEVER run on this engine
    QUEUE_FULL = 'queue_full'       # admission queue at capacity now
    DRAINING = 'draining'           # engine draining; retry elsewhere

    ALL = (EXCEEDS_POOL, QUEUE_FULL, DRAINING)
    HTTP_STATUS = {EXCEEDS_POOL: 413, QUEUE_FULL: 429, DRAINING: 503}


class RejectedRequest(ValueError):
    """A typed admission refusal.  Subclasses ValueError so callers
    that predate the taxonomy (tests, scripts catching ValueError
    from ``submit``) keep working unchanged."""

    def __init__(self, reason, detail, rid=None):
        assert reason in RejectReason.ALL, reason
        super().__init__(detail)
        self.reason = reason
        self.detail = detail
        self.rid = rid

    @property
    def http_status(self):
        return RejectReason.HTTP_STATUS[self.reason]


class Request:
    """One generation request moving through the serving engine."""

    QUEUED, RUNNING, DONE, EVICTED = 'queued', 'running', 'done', \
        'evicted'

    def __init__(self, rid, prompt, max_new_tokens, *, arrival_t=0.0,
                 deadline_s=None, seed=None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int64).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError('empty prompt')
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError('max_new_tokens must be >= 1')
        self.arrival_t = float(arrival_t)
        self.deadline_s = deadline_s
        # per-request sampling base seed (ops/sampling discipline):
        # every token this request samples derives its key from
        # (seed, absolute position), NOT from batch composition or
        # scheduling history — None means the engine derives one from
        # the rid at submit, so a replayed retry on another replica
        # continues the identical stream
        self.seed = None if seed is None else int(seed)
        self.state = Request.QUEUED
        self.reason = None          # eos | max_tokens | deadline | ...
        self.tokens = []            # decoded token ids (ints)
        self.ctx = 0                # cache positions written so far
        self.prompt_bucket = None   # padded prefill length (pow2)
        self.first_token_t = None   # wall time of the first token
        self.finish_t = None
        self.preemptions = 0
        self.discarded_tokens = 0   # last preemption's recompute debt
        self.trace = []             # lifecycle rows (see trace_note)

    def trace_note(self, stage, t, **tags):
        """Append one lifecycle row: the queued→admitted→prefill→
        first_token→decode_span*→finished/evicted/preempted trail,
        each row timestamped on the engine clock and tagged with its
        cause/bucket.  Host-side list append — the engine emits the
        whole trail as ONE ``serve_trace`` event at finish, and
        ``telemetry.live`` serves it at ``/requests/<rid>``."""
        row = {'stage': stage, 't': round(float(t), 6)}
        row.update({k: v for k, v in tags.items() if v is not None})
        self.trace.append(row)
        return row

    @property
    def done(self):
        return self.state in (Request.DONE, Request.EVICTED)

    # emitted-token accounting: after prefill ctx == t0 and ONE token
    # exists; each decode step advances ctx and emits one more.  The
    # last useful decode step is the one reaching ctx == limit - 1.
    @property
    def limit(self):
        return self.prompt.size + self.max_new_tokens - 1

    def record(self, now, ttft_anchor=None):
        """Latency summary for reports/telemetry."""
        anchor = self.arrival_t if ttft_anchor is None else ttft_anchor
        ttft = None if self.first_token_t is None \
            else self.first_token_t - anchor
        tpot = None
        if self.finish_t is not None and self.first_token_t is not None \
                and len(self.tokens) > 1:
            tpot = (self.finish_t - self.first_token_t) \
                / (len(self.tokens) - 1)
        return {'rid': self.rid, 'state': self.state,
                'reason': self.reason, 'prompt_len': int(self.prompt.size),
                'tokens': len(self.tokens), 'ttft_s': ttft,
                'tpot_s': tpot, 'preemptions': self.preemptions,
                'age_s': (now - self.arrival_t)}


class DecodePlan:
    """One intervention's padded decode inputs (host numpy)."""

    def __init__(self, requests, batch_bucket, table_width, span):
        self.requests = list(requests)        # live order, <= bucket
        self.batch = int(batch_bucket)
        self.span = int(span)
        self.tables = np.full((self.batch, table_width), TRASH_BLOCK,
                              np.int32)
        self.ctx = np.zeros((self.batch,), np.int64)
        self.tok = np.zeros((self.batch,), np.int64)
        self.active = np.zeros((self.batch,), bool)
        self.limit = np.zeros((self.batch,), np.int64)
        self.seed = np.zeros((self.batch,), np.int64)


class ContinuousBatchingScheduler:
    """Admission/eviction policy over a :class:`PagedKVCache`.

    ``bucket_fn(prompt_len) -> padded prefill length`` comes from the
    engine (its declared pow2 prompt-bucket set); ``batch_buckets`` is
    the declared pow2 set of decode batch sizes (must contain
    ``max_slots``).
    """

    def __init__(self, cache, *, max_slots, batch_buckets, bucket_fn,
                 max_model_len, decode_span=1, eos_id=None,
                 now_fn=time.monotonic):
        self.cache = cache
        self.max_slots = int(max_slots)
        self.batch_buckets = tuple(sorted(set(
            int(b) for b in batch_buckets)))
        if self.max_slots not in self.batch_buckets:
            raise ValueError(
                f'batch_buckets {self.batch_buckets} must contain '
                f'max_slots {self.max_slots}')
        self.bucket_fn = bucket_fn
        self.max_model_len = int(max_model_len)
        self.decode_span = max(1, int(decode_span))
        self.eos_id = eos_id
        self.now_fn = now_fn
        self.table_width = blocks_for(self.max_model_len,
                                      cache.block_size)
        self.queue = collections.deque()
        self.running = []            # admission order (oldest first)
        self.finished = []
        self.counters = collections.Counter()

    # -- submission ---------------------------------------------------------
    def submit(self, req):
        total = req.prompt.size + req.max_new_tokens
        if total > self.max_model_len:
            self.counters['rejected'] += 1
            raise RejectedRequest(
                RejectReason.EXCEEDS_POOL,
                f'request {req.rid}: prompt+new {total} exceeds '
                f'max_model_len {self.max_model_len}', rid=req.rid)
        # feasibility: the request's WORST-CASE block need (prefill
        # bucket or its full trajectory, whichever is larger) must fit
        # an empty pool — otherwise reservation would preempt it
        # against itself forever (admit -> decode -> self-preempt ->
        # re-admit livelock)
        worst = blocks_for(max(int(self.bucket_fn(req.prompt.size)),
                               req.limit), self.cache.block_size)
        if worst > self.cache.num_blocks - 1:
            self.counters['rejected'] += 1
            raise RejectedRequest(
                RejectReason.EXCEEDS_POOL,
                f'request {req.rid}: needs {worst} KV blocks at its '
                f'longest, pool only has {self.cache.num_blocks - 1}',
                rid=req.rid)
        self.queue.append(req)
        self.counters['submitted'] += 1
        req.trace_note('queued', self.now_fn(),
                       prompt_len=int(req.prompt.size),
                       max_new_tokens=req.max_new_tokens,
                       deadline_s=req.deadline_s)
        return req

    # -- admission ----------------------------------------------------------
    def admit_next(self):
        """Admit the head of the queue if a slot and blocks exist;
        returns the Request (caller prefills it) or None."""
        if not self.queue or len(self.running) >= self.max_slots:
            return None
        req = self.queue[0]
        bucket = int(self.bucket_fn(req.prompt.size))
        # the prefill scatter writes the whole (block-rounded) bucket;
        # reserving one decode span up front keeps admission from
        # thrashing against the very next reservation pass
        need = max(bucket,
                   min(req.prompt.size + self.decode_span, req.limit))
        if not self.cache.ensure(req.rid, need):
            return None
        self.queue.popleft()
        req.state = Request.RUNNING
        req.prompt_bucket = bucket
        req.ctx = req.prompt.size
        self.running.append(req)
        self.counters['admitted'] += 1
        req.trace_note('admitted', self.now_fn(), bucket=bucket,
                       blocks=len(self.cache.owned(req.rid)))
        return req

    # -- eviction / completion ----------------------------------------------
    def finish(self, req, reason):
        req.state = Request.DONE if reason in ('eos', 'max_tokens') \
            else Request.EVICTED
        req.reason = reason
        req.finish_t = self.now_fn()
        self.cache.free_seq(req.rid)
        if req in self.running:
            self.running.remove(req)
        self.finished.append(req)
        self.counters['evicted' if req.state == Request.EVICTED
                      else 'completed'] += 1
        req.trace_note('finished' if req.state == Request.DONE
                       else 'evicted', req.finish_t, cause=reason,
                       tokens=len(req.tokens))

    def preempt_youngest(self):
        """Pool pressure: push the newest running request back to the
        queue head (recompute-style — its blocks free now, it
        re-prefills from scratch later)."""
        if not self.running:
            return None
        req = self.running.pop()
        self.cache.free_seq(req.rid)
        req.state = Request.QUEUED
        # the discarded work is recomputed after re-admission — the
        # engine rolls its decoded-token accounting back by this much
        # so throughput never counts a token twice
        req.discarded_tokens = len(req.tokens)
        self.counters['discarded_tokens'] += req.discarded_tokens
        req.tokens = []
        req.ctx = 0
        req.first_token_t = None
        req.preemptions += 1
        self.queue.appendleft(req)
        self.counters['preempted'] += 1
        req.trace_note('preempted', self.now_fn(), cause='pool',
                       discarded_tokens=req.discarded_tokens)
        return req

    def check_deadlines(self, now):
        """Evict running AND queued requests past their deadline —
        the watchdog-budget starvation guard."""
        breached = [r for r in list(self.running) + list(self.queue)
                    if r.deadline_s is not None
                    and now - r.arrival_t > r.deadline_s]
        for req in breached:
            if req in self.queue:
                self.queue.remove(req)
            self.finish(req, 'deadline')
        return breached

    # -- decode planning -----------------------------------------------------
    def reserve_span(self, span):
        """Reserve blocks so every live sequence can write `span` more
        positions (capped at its own limit).  Preempts the youngest
        request(s) on pool pressure; returns the preempted list."""
        preempted = []
        i = 0
        while i < len(self.running):
            req = self.running[i]
            need = min(req.ctx + span, req.limit)
            if self.cache.ensure(req.rid, need):
                i += 1
                continue
            victim = self.preempt_youngest()
            preempted.append(victim)
            if victim is req:
                continue            # re-check from the same index
            # a younger victim freed blocks; retry this request
        return preempted

    def plan(self, span=None):
        """Build the DecodePlan for the current live set (None when
        nothing is running).  Batch is padded to the smallest declared
        pow2 bucket >= live count; padding rows point at the trash
        block and stay inactive."""
        if not self.running:
            return None
        span = self.decode_span if span is None else int(span)
        live = len(self.running)
        batch = next(b for b in self.batch_buckets if b >= live)
        plan = DecodePlan(self.running, batch, self.table_width, span)
        for i, req in enumerate(self.running):
            plan.tables[i] = self.cache.table_row(req.rid,
                                                  self.table_width)
            plan.ctx[i] = req.ctx
            plan.tok[i] = req.tokens[-1]
            plan.active[i] = len(req.tokens) < req.max_new_tokens
            plan.limit[i] = req.limit
            plan.seed[i] = req.seed or 0
        return plan

    def absorb(self, plan, toks, valid):
        """Fold one decode span's outputs back into the requests:
        append valid tokens, finish on EOS / max tokens.  ``toks`` and
        ``valid`` are ``[span, batch]`` host arrays."""
        finished = []
        now = self.now_fn()
        for i, req in enumerate(plan.requests):
            emitted = 0
            finish_reason = None
            for k in range(plan.span):
                if not valid[k, i] or req.done:
                    break
                tok = int(toks[k, i])
                req.tokens.append(tok)
                emitted += 1
                if self.eos_id is not None and tok == self.eos_id:
                    finish_reason = 'eos'
                    break
                if len(req.tokens) >= req.max_new_tokens:
                    finish_reason = 'max_tokens'
                    break
            req.ctx = min(req.ctx + emitted, req.limit)
            if emitted:
                # ONE trace row per intervention per live request,
                # noted BEFORE any finish row so the trail stays in
                # lifecycle order
                req.trace_note('decode_span', now, span=plan.span,
                               emitted=emitted,
                               tokens=len(req.tokens))
            if finish_reason is not None:
                self.finish(req, finish_reason)
            if req.done:
                finished.append(req)
        self.counters['decode_steps'] += plan.span
        return finished

    # -- invariants ----------------------------------------------------------
    def audit(self):
        """Scheduler+allocator invariants; list of violations."""
        problems = list(self.cache.audit())
        states = collections.Counter(r.state for r in self.running)
        if set(states) - {Request.RUNNING}:
            problems.append(f'non-running request in live set: {states}')
        for req in self.running:
            covered = len(self.cache.owned(req.rid)) \
                * self.cache.block_size
            if covered < req.ctx:
                problems.append(
                    f'request {req.rid}: ctx {req.ctx} exceeds its '
                    f'{covered} covered cache positions')
        for req in self.finished:
            if self.cache.owned(req.rid):
                problems.append(
                    f'finished request {req.rid} still owns blocks')
        return problems
