"""Seeded synthetic load for the serving engine.

Poisson arrivals (exponential inter-arrival gaps at ``rate_rps``) with
mixed prompt/output lengths drawn from declared choice sets — the
ROADMAP item-1 contract that makes scheduler policies *benchmarkable*:
the same seed always produces the same request set with the same
arrival times, so two engine configurations (or an engine vs a
sequential baseline) see identical offered load.
"""
import numpy as np

from .scheduler import Request

__all__ = ['poisson_requests']


def poisson_requests(num_requests, *, rate_rps, prompt_lens,
                     new_tokens, vocab_size, seed=0, deadline_s=None,
                     start_t=0.0):
    """A deterministic request list sorted by arrival time.

    prompt_lens / new_tokens: sequences of lengths sampled uniformly
    per request (mixed-length traffic); ``rate_rps`` the Poisson
    arrival rate; ``deadline_s`` an optional per-request completion
    budget (the watchdog-deadline seed).
    """
    rs = np.random.RandomState(int(seed))
    prompt_lens = list(prompt_lens)
    new_tokens = list(new_tokens)
    t = float(start_t)
    out = []
    for i in range(int(num_requests)):
        t += rs.exponential(1.0 / float(rate_rps))
        t0 = int(prompt_lens[rs.randint(len(prompt_lens))])
        new = int(new_tokens[rs.randint(len(new_tokens))])
        prompt = rs.randint(0, int(vocab_size), size=(t0,)) \
            .astype(np.int64)
        out.append(Request(f'req-{seed}-{i:04d}', prompt, new,
                           arrival_t=t, deadline_s=deadline_s))
    return out
