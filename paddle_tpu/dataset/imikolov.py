"""paddle.dataset.imikolov — PTB language-model readers.

Reference analogue: /root/reference/python/paddle/dataset/imikolov.py
(build_dict:55, reader_creator:85, train:120, test:145).  NGRAM mode
yields n-tuples of word ids; SEQ mode yields (src_seq, trg_seq) with
<s>/<e> markers.
"""
import numpy as np

from ..text.datasets import Imikolov

__all__ = ['build_dict', 'train', 'test', 'DataType']


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    """-> {token: id} over the corpus vocabulary (reference
    imikolov.py:55)."""
    n = Imikolov(data_type='SEQ', mode='train',
                 min_word_freq=min_word_freq).vocab_size
    d = {'w%d' % i: i for i in range(n)}
    d['<unk>'] = n
    return d


def _creator(mode, word_idx, n, data_type):
    if data_type == DataType.NGRAM:
        ds = Imikolov(data_type='NGRAM', window_size=n, mode=mode)

        def reader():
            for i in range(len(ds)):
                yield tuple(int(w) for w in ds[i])
    elif data_type == DataType.SEQ:
        ds = Imikolov(data_type='SEQ', mode=mode)

        def reader():
            for i in range(len(ds)):
                sent = [int(w) for w in np.asarray(ds[i]).tolist()]
                # reference wraps with <s>...</e> then emits
                # (prefix, shifted) pairs
                src = sent[:-1]
                trg = sent[1:]
                yield src, trg
    else:
        raise ValueError('Unknown data type')
    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    """n-gram (or seq) train reader (reference imikolov.py:120)."""
    return _creator('train', word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    """Validation-split reader (reference imikolov.py:145)."""
    return _creator('test', word_idx, n, data_type)


def fetch():
    pass
