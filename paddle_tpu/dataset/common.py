"""paddle.dataset.common — shared helpers of the fluid-era dataset stack.

Reference analogue: /root/reference/python/paddle/dataset/common.py
(download:62, md5file:53, split:130, cluster_files_reader:167).

Zero-egress build: download() never fetches; it returns the cache path
when the file is already there and raises with a pointer otherwise —
the per-dataset modules fall back to the synthetic corpora in
vision/text datasets instead of calling it.
"""
import glob
import hashlib
import os
import pickle

import numpy as np

__all__ = ['DATA_HOME', 'download', 'md5file', 'split',
           'cluster_files_reader']

DATA_HOME = os.path.expanduser('~/.cache/paddle/dataset')


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b''):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve the local cache path for a dataset file.  This build has
    no egress: if the file exists (pre-seeded) return it, else raise —
    callers in this package catch and serve synthetic data."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split('/')[-1])
    if os.path.exists(filename) and (
            not md5sum or md5file(filename) == md5sum):
        return filename
    raise RuntimeError(
        f'dataset file {filename} not present and this build cannot '
        f'download ({url}); place the file there or use the synthetic '
        'fallback readers')


def split(reader, line_count, suffix='%05d.pickle', dumper=None):
    """Spill a reader into numbered pickle chunks of line_count samples
    (reference common.py:130)."""
    if not callable(reader):
        raise TypeError('reader should be callable')
    if '%' not in suffix:
        raise ValueError('suffix must contain a printf format like %05d')
    dumper = dumper or pickle.dump
    lines = []
    indx_file = 0
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % indx_file, 'wb') as f:
                dumper(lines, f)
            lines = []
            indx_file += 1
    if lines:
        with open(suffix % indx_file, 'wb') as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Round-robin a glob of spilled files across trainers (reference
    common.py:167)."""
    loader = loader or pickle.load

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_files = [f for i, f in enumerate(file_list)
                    if i % trainer_count == trainer_id]
        for fn in my_files:
            with open(fn, 'rb') as f:
                for item in loader(f):
                    yield item

    return reader


def _dataset_reader(ds, mapper=None):
    """Adapt a map-style io.Dataset into a fluid-era reader callable."""

    def reader():
        for i in range(len(ds)):
            sample = ds[i]
            yield mapper(sample) if mapper else sample

    return reader
