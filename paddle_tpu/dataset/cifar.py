"""paddle.dataset.cifar — fluid-era CIFAR reader creators.

Reference analogue: /root/reference/python/paddle/dataset/cifar.py
(reader_creator:49, train10/test10/train100/test100).  Samples are
(3072-float32 in [0, 1] CHW-flat, int label) — the reference's
`sample/255` convention.
"""
import numpy as np

from ..vision.datasets import Cifar10, Cifar100

__all__ = ['train10', 'test10', 'train100', 'test100']


def _creator(cls, mode, cycle=False):
    ds = cls(mode=mode)

    def reader():
        while True:
            for i in range(len(ds)):
                img, label = ds[i]
                arr = np.asarray(img)
                # scaling decided by DTYPE, not per-image content: the
                # loader serves raw uint8 pixels; a float transform
                # output is assumed already scaled
                flat = arr.astype(np.float32).reshape(-1)
                if np.issubdtype(arr.dtype, np.integer):
                    flat = flat / 255.0
                yield flat, int(np.asarray(label).reshape(()))
            if not cycle:
                break

    return reader


def train10(cycle=False):
    """CIFAR-10 train reader (reference cifar.py:76)."""
    return _creator(Cifar10, 'train', cycle)


def test10(cycle=False):
    """CIFAR-10 test reader (reference cifar.py:95)."""
    return _creator(Cifar10, 'test', cycle)


def train100():
    """CIFAR-100 train reader (reference cifar.py:114)."""
    return _creator(Cifar100, 'train')


def test100():
    """CIFAR-100 test reader (reference cifar.py:132)."""
    return _creator(Cifar100, 'test')


def fetch():
    pass
