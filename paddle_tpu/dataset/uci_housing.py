"""paddle.dataset.uci_housing — fluid-era Boston-housing readers.

Reference analogue: /root/reference/python/paddle/dataset/uci_housing.py
(load_data:69, train:92, test:117).  Samples are
(13 normalized float features, [price]).
"""
import numpy as np

from ..text.datasets import UCIHousing

__all__ = ['train', 'test']


def _creator(mode):
    ds = UCIHousing(mode=mode)

    def reader():
        for i in range(len(ds)):
            feats, price = ds[i]
            yield np.asarray(feats, np.float32), \
                np.asarray(price, np.float32)

    return reader


def train():
    """404-sample train split (reference uci_housing.py:92)."""
    return _creator('train')


def test():
    """102-sample test split (reference uci_housing.py:117)."""
    return _creator('test')


def fetch():
    pass
