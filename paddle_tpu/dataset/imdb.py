"""paddle.dataset.imdb — fluid-era IMDB sentiment readers.

Reference analogue: /root/reference/python/paddle/dataset/imdb.py
(build_dict:60, reader_creator:85, train:108, test:130, word_dict:152).
Samples are (word-id list, 0/1 label).
"""
import numpy as np

from ..text.datasets import Imdb

__all__ = ['build_dict', 'train', 'test', 'word_dict']

_CACHE = {}


def _ds(mode):
    if mode not in _CACHE:
        _CACHE[mode] = Imdb(mode=mode)
    return _CACHE[mode]


def word_dict():
    """-> {word-or-id: index} (reference imdb.py:152)."""
    return dict(_ds('train').word_idx)


def build_dict(pattern=None, cutoff=150):
    """Reference imdb.py:60 walks the tarball; here the loader already
    built (or synthesized) the vocabulary."""
    return word_dict()


def _creator(mode, word_idx):
    ds = _ds(mode)

    def reader():
        for i in range(len(ds)):
            doc, label = ds[i]
            yield [int(w) for w in np.asarray(doc).tolist()], \
                int(np.asarray(label).reshape(()))

    return reader


def train(word_idx):
    """(ids, 0/1) train reader (reference imdb.py:108)."""
    return _creator('train', word_idx)


def test(word_idx):
    """(ids, 0/1) test reader (reference imdb.py:130)."""
    return _creator('test', word_idx)


def fetch():
    pass
