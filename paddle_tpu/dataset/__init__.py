"""paddle.dataset — the fluid-era reader-creator data stack.

Reference analogue: /root/reference/python/paddle/dataset/__init__.py.
Each module exposes train()/test() reader creators yielding plain
numpy/python samples; compose them with paddle.reader decorators and
paddle.batch, then feed DataLoader/executor — the classic 1.x input
pipeline that the fluid compat namespace's users expect.  The modern
map-style equivalents live in paddle.vision.datasets / paddle.text.
"""
from . import common      # noqa: F401
from . import mnist       # noqa: F401
from . import cifar       # noqa: F401
from . import uci_housing # noqa: F401
from . import imdb        # noqa: F401
from . import imikolov    # noqa: F401
from . import movielens   # noqa: F401
from . import conll05     # noqa: F401
from . import wmt14       # noqa: F401
from . import wmt16       # noqa: F401
from . import flowers     # noqa: F401
from . import voc2012     # noqa: F401
from . import image       # noqa: F401

__all__ = ['common', 'mnist', 'cifar', 'uci_housing', 'imdb', 'imikolov',
           'movielens', 'conll05', 'wmt14', 'wmt16', 'flowers',
           'voc2012', 'image']
