"""paddle.dataset.mnist — fluid-era MNIST reader creators.

Reference analogue: /root/reference/python/paddle/dataset/mnist.py
(reader_creator:43, train:98, test:120).  Samples are
(784-float32 in [-1, 1], int label) — the reference's
`img/255*2-1` normalization — served from the vision.datasets.MNIST
loader (idx files when present, deterministic synthetic otherwise).
"""
import numpy as np

from ..vision.datasets import MNIST

__all__ = ['train', 'test']


def _creator(mode):
    ds = MNIST(mode=mode)

    def reader():
        for i in range(len(ds)):
            img, label = ds[i]
            flat = np.asarray(img, np.float32).reshape(-1)
            # vision.MNIST serves raw 0..255 uint8 pixels
            flat = flat / 255.0 * 2.0 - 1.0
            yield flat, int(np.asarray(label).reshape(()))

    return reader


def train():
    """-> reader of (784-float32 in [-1,1], int label), 60k samples
    (reference mnist.py:98)."""
    return _creator('train')


def test():
    """-> reader over the 10k-sample test split (reference
    mnist.py:120)."""
    return _creator('test')


def fetch():
    """Reference mnist.py:141 pre-downloads; no-op here (synthetic or
    pre-seeded files)."""
