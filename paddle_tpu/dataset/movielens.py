"""paddle.dataset.movielens — ML-1M rating readers.

Reference analogue: /root/reference/python/paddle/dataset/movielens.py
(train:188, test:199, get_movie_title_dict:210, max_movie_id:224,
max_user_id:231, max_job_id:238, movie_categories:245, user_info:252,
movie_info:260).  Samples are (user_id, gender, age, job, movie_id,
categories, title, [rating]).
"""
from ..text.datasets import Movielens

__all__ = ['train', 'test', 'get_movie_title_dict', 'max_movie_id',
           'max_user_id', 'max_job_id', 'movie_categories', 'user_info',
           'movie_info']

_CACHE = {}


def _ds(mode):
    if mode not in _CACHE:
        _CACHE[mode] = Movielens(mode=mode)
    return _CACHE[mode]


def _creator(mode):
    ds = _ds(mode)

    def reader():
        for i in range(len(ds)):
            yield ds[i]

    return reader


def train():
    return _creator('train')


def test():
    return _creator('test')


def get_movie_title_dict():
    return {'t%d' % i: i for i in range(Movielens.TITLE_VOCAB)}


def max_movie_id():
    return Movielens.NUM_MOVIES


def max_user_id():
    return Movielens.NUM_USERS


def max_job_id():
    return Movielens.NUM_JOBS - 1


def movie_categories():
    return {'c%d' % i: i for i in range(Movielens.NUM_CATEGORIES)}


def user_info():
    raise NotImplementedError(
        'per-entity metadata requires the real ML-1M corpus; this '
        'zero-egress build serves synthetic rating tuples only')


movie_info = user_info


def fetch():
    pass
