"""paddle.dataset.flowers — 102-category flowers readers.

Reference analogue: /root/reference/python/paddle/dataset/flowers.py
(reader_creator:74, train:120, test:151, valid:180).  Samples are
(CHW float32 image, int label).
"""
import numpy as np

from ..vision.datasets import Flowers

__all__ = ['train', 'test', 'valid']


def _creator(mode, use_xmap=True, cycle=False):
    ds = Flowers(mode=mode)

    def reader():
        while True:
            for i in range(len(ds)):
                img, label = ds[i]
                arr = np.asarray(img, np.float32)
                if arr.ndim == 3 and arr.shape[-1] in (1, 3):
                    arr = arr.transpose(2, 0, 1)     # HWC -> CHW
                yield arr, int(np.asarray(label).reshape(()))
            if not cycle:
                break

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _creator('train', use_xmap, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _creator('test', use_xmap, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _creator('valid', use_xmap)


def fetch():
    pass
