"""paddle.dataset.wmt16 — BPE translation triples.

Reference analogue: /root/reference/python/paddle/dataset/wmt16.py
(reader_creator:114, train:153, test:204, validation:255, get_dict:306).
"""
from ..text.datasets import WMT16

__all__ = ['train', 'test', 'validation', 'get_dict']


def _creator(mode, src_dict_size, trg_dict_size, src_lang):
    ds = WMT16(mode=mode, src_dict_size=src_dict_size,
               trg_dict_size=trg_dict_size, lang=src_lang)

    def reader():
        for i in range(len(ds)):
            src, trg, trg_next = ds[i]
            yield src.tolist(), trg.tolist(), trg_next.tolist()

    return reader


def train(src_dict_size, trg_dict_size, src_lang='en'):
    return _creator('train', src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang='en'):
    return _creator('test', src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang='en'):
    return _creator('val', src_dict_size, trg_dict_size, src_lang)


def get_dict(lang, dict_size, reverse=False):
    """word→id ({id→word} when reverse) (reference wmt16.py:306)."""
    d = {'w%d' % i: i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d


def fetch():
    pass
