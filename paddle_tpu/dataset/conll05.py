"""paddle.dataset.conll05 — SRL sequence readers.

Reference analogue: /root/reference/python/paddle/dataset/conll05.py
(test:348, get_dict:311, get_embedding:340).  Samples are the 9-field
SRL tuples (word_ids, 5 ctx windows, predicate, mark, label_ids).
"""
import numpy as np

from ..text.datasets import Conll05st

__all__ = ['test', 'get_dict', 'get_embedding']


def get_dict():
    """-> (word_dict, verb_dict, label_dict) (reference conll05.py:311)."""
    word_dict = {'w%d' % i: i for i in range(Conll05st.WORD_VOCAB)}
    verb_dict = {'v%d' % i: i for i in range(Conll05st.PRED_VOCAB)}
    label_dict = {'l%d' % i: i for i in range(Conll05st.LABEL_NUM)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Reference conll05.py:340 downloads pretrained emb; deterministic
    synthetic matrix here."""
    rng = np.random.RandomState(77)
    return rng.randn(Conll05st.WORD_VOCAB, 32).astype(np.float32)


def test():
    """The reference ships only a test split publicly (conll05.py:348)."""
    ds = Conll05st(mode='test')

    def reader():
        for i in range(len(ds)):
            yield ds[i]

    return reader


def fetch():
    pass
