"""paddle.dataset.image — numpy image helpers of the fluid data stack.

Reference analogue: /root/reference/python/paddle/dataset/image.py
(resize_short:173, to_chw:203, center_crop:229, random_crop:255,
left_right_flip:283, simple_transform:304, load_image:128,
batch_images_from_tar:87).  The reference shells out to cv2; these are
pure-numpy equivalents (bilinear resize) — the TPU input pipeline does
augmentation on host anyway, and vision.transforms carries the
full-featured versions.
"""
import numpy as np

__all__ = ['resize_short', 'to_chw', 'center_crop', 'random_crop',
           'left_right_flip', 'simple_transform', 'load_image',
           'load_and_transform']


def _bilinear_resize(im, h, w):
    """HWC (or HW) uint8/float -> bilinear resampled float32."""
    im = np.asarray(im)
    squeeze = im.ndim == 2
    if squeeze:
        im = im[:, :, None]
    H, W, C = im.shape
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    im = im.astype(np.float32)
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out[:, :, 0] if squeeze else out


def resize_short(im, size):
    """Scale so the SHORT side equals `size` (reference image.py:173)."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    return _bilinear_resize(im, nh, nw)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference image.py:203)."""
    return np.asarray(im).transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the central size x size window (reference image.py:229)."""
    h, w = im.shape[:2]
    hs, ws = (h - size) // 2, (w - size) // 2
    return im[hs:hs + size, ws:ws + size]


def random_crop(im, size, is_color=True):
    """Crop a uniformly random size x size window (reference
    image.py:255)."""
    h, w = im.shape[:2]
    hs = np.random.randint(0, h - size + 1)
    ws = np.random.randint(0, w - size + 1)
    return im[hs:hs + size, ws:ws + size]


def left_right_flip(im, is_color=True):
    """Mirror horizontally (reference image.py:283)."""
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short → (random|center) crop → maybe flip → CHW → -mean
    (reference image.py:304)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im = im - mean
    return im


def load_image(file_path, is_color=True):
    """Decode an image file.  PNG/BMP via pure numpy is out of scope —
    uses vision's loader when pillow is available, else raises
    (reference image.py:128 uses cv2)."""
    try:
        from PIL import Image
        with Image.open(file_path) as img:
            img = img.convert('RGB' if is_color else 'L')
            return np.asarray(img)
    except ImportError as e:
        raise RuntimeError(
            'load_image needs pillow in this build; feed arrays '
            'directly or use paddle.vision.datasets') from e


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
