"""paddle.dataset.voc2012 — segmentation readers.

Reference analogue: /root/reference/python/paddle/dataset/voc2012.py
(reader_creator:43, train:62, test:73, val:84).  Samples are
(CHW float32 image, HW int32 label mask).
"""
import numpy as np

from ..vision.datasets import VOC2012

__all__ = ['train', 'test', 'val']


def _creator(mode):
    ds = VOC2012(mode=mode)

    def reader():
        for i in range(len(ds)):
            img, mask = ds[i]
            arr = np.asarray(img, np.float32)
            if arr.ndim == 3 and arr.shape[-1] in (1, 3):
                arr = arr.transpose(2, 0, 1)
            yield arr, np.asarray(mask, np.int32)

    return reader


def train():
    return _creator('train')


def test():
    return _creator('test')


def val():
    return _creator('valid')


def fetch():
    pass
