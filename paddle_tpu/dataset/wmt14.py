"""paddle.dataset.wmt14 — translation triples.

Reference analogue: /root/reference/python/paddle/dataset/wmt14.py
(reader_creator:88, train:122, test:139, get_dict:178).
"""
from ..text.datasets import WMT14

__all__ = ['train', 'test', 'get_dict']


def _creator(mode, dict_size):
    ds = WMT14(mode=mode, dict_size=dict_size)

    def reader():
        for i in range(len(ds)):
            src, trg, trg_next = ds[i]
            yield src.tolist(), trg.tolist(), trg_next.tolist()

    return reader


def train(dict_size):
    """(src_ids, trg_ids, trg_ids_next) train reader (wmt14.py:122)."""
    return _creator('train', dict_size)


def test(dict_size):
    return _creator('test', dict_size)


def gen(dict_size):
    return _creator('gen', dict_size)


def get_dict(dict_size, reverse=True):
    """-> (src_dict, trg_dict) id→word (or word→id when reverse=False)
    (reference wmt14.py:178; note the reference's `reverse` default
    returns id→word).  Synthetic corpus: vocab is w0..w<n>."""
    d = {i: 'w%d' % i for i in range(dict_size)}
    if not reverse:
        d = {v: k for k, v in d.items()}
    return d, dict(d)


def fetch():
    pass
