#!/usr/bin/env python
"""precompile — AOT warm start: compile the declared bucket set at
export time so a restarted (or freshly served) worker deserializes
instead of recompiling.

    python tools/precompile.py RUN_DIR                      # defaults
    python tools/precompile.py RUN_DIR --targets lenet,gpt --mesh dp=4
    python tools/precompile.py RUN_DIR \\
        --gpt-decode 8x128x128,8x64x128 --gpt-model small
    python tools/precompile.py RUN_DIR --json

What gets compiled (all without ever executing a step):

* **train-step lowerings** — the built-in audit targets
  (analysis.targets: gpt / widedeep / lenet) lowered through the SPMD
  partitioner for every requested mesh, landing in the persistent
  compile cache's TEXT tier (the exact keys ``tpu_lint --plan``/
  ``--hlo`` and the planner read) and seeding jax's persistent XLA
  cache with the compiled executables;
* **gptgen decode buckets** — ``--gpt-decode BxT0xNEW`` signatures
  exported through ``GPTForCausalLM.precompile_decode`` into the EXEC
  tier (serialized ``jax.export`` artifacts, prompt lengths bucketed
  to the next power of two) plus an AOT XLA compile, so a serving
  cold-start's ``generate`` deserializes and skips the optimizer
  passes too;
* **elastic-reshape target meshes** — when RUN_DIR holds committed
  sharded checkpoints, the newest step's commit manifest records the
  saving mesh (PR 5's reshape metadata); its dp axis halved (dp/2,
  dp/4, ...) is added to the mesh set, so the reshape-restore path a
  preempted pool takes onto fewer hosts finds its lowerings warm.

Every produced entry is recorded in a sidecar
``_PADDLE_PRECOMPILE.json`` committed into RUN_DIR:
``check_ckpt --deep`` audits it (a restore target's AOT set is
provable), and ``warm_start`` (called by auto_checkpoint /
CheckpointManager.restore) pre-loads it on the next restart.

Exit codes: 0 = every requested artifact compiled, 1 = some failed
(the manifest still records the ones that succeeded), 2 = usage error.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _parse_mesh(spec):
    axes = {}
    for part in spec.split(','):
        name, _, size = part.strip().partition('=')
        if not size:
            raise ValueError(f'--mesh wants axis=size, got {part!r}')
        axes[name] = int(size)
    return axes


def _parse_decode(spec):
    """'8x128x128,2x16x8' -> [(B, T0, NEW), ...]."""
    out = []
    for part in spec.split(','):
        dims = part.strip().lower().split('x')
        if len(dims) != 3:
            raise ValueError(
                f'--gpt-decode wants BxT0xNEW, got {part!r}')
        out.append(tuple(int(d) for d in dims))
    return out


def _reshape_meshes(run_dir):
    """Elastic-reshape targets from the newest committed step's
    manifest: the saved mesh itself plus its dp axis halved down to 1
    — the meshes a preempted pool restores onto."""
    from paddle_tpu.resilience import manifest as M
    steps = []
    try:
        for f in os.listdir(run_dir):
            tag = f.rpartition('_')[2]
            if tag.isdigit() and os.path.isdir(os.path.join(run_dir, f)):
                steps.append((int(tag), os.path.join(run_dir, f)))
    except OSError:
        return []
    for _s, p in sorted(steps, reverse=True):
        doc = M.read_manifest(p)
        if doc is None or not doc.get('mesh'):
            continue
        mesh = {a: int(s) for a, s in doc['mesh'].items()}
        out = [dict(mesh)]
        dp = mesh.get('dp', 1)
        while dp > 1:
            dp //= 2
            # dp=1 included: a pool shrinking to a single host is the
            # most-shrunk elastic target and still wants a warm lower
            out.append(dict(mesh, dp=dp))
        return out
    return []


def _build_mesh(axes):
    import math
    import numpy as np
    import jax
    from jax.sharding import Mesh
    n = math.prod(axes.values())
    devs = jax.devices()
    if n > len(devs):
        raise RuntimeError(
            f'mesh {axes} wants {n} devices but only {len(devs)} exist')
    return Mesh(np.array(devs[:n]).reshape(tuple(axes.values())),
                tuple(axes.keys()))


def _precompile_target(name, mesh_axes, entries, errors,
                       fused_steps=0):
    """Lower one audit target's surrogate step for one mesh into the
    persistent text tier (exact tpu_lint/planner keys) — the
    lower+compile also seeds jax's XLA disk cache.  ``fused_steps=K``
    instead lowers the K-step FUSED module (core.scan_loop: one
    lax.scan over a K-stacked batch) under a distinct cache key, so a
    deploy that trains with ``fused_steps=K`` finds its whole-loop
    module warm."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.analysis import hlo as _hlo
    from paddle_tpu.analysis import targets as _targets
    from paddle_tpu.core import compile_cache as _cc
    from paddle_tpu.core import scan_loop as _scan
    from paddle_tpu.distributed import env as _env
    k = max(0, int(fused_steps))
    desc = f'target-step {name} @ {mesh_axes or "1-device"}' + \
        (f' fused x{k}' if k else '')
    try:
        mesh = _build_mesh(mesh_axes) if mesh_axes else \
            _build_mesh({'dp': 1})
        prev = _env.get_mesh()
        _env.set_mesh(mesh)
        try:
            model, batch = _targets.TARGETS[name](mesh)
            params, buffers, p_sh, b_sh = _targets.target_state(
                model, mesh)
            repl = NamedSharding(mesh, P())
            batch_sh = _targets.batch_shardings(mesh, batch)
            key = jax.random.PRNGKey(0)
            step = _targets.surrogate_step(model)
            ck_name = name if not k else f'{name}+fused{k}'
            if k:
                # stack the batch with a leading K dim and shift the
                # dp sharding one dim right — the fused scan's axes
                step = _scan.fused_surrogate(step, k)
                batch = tuple(jax.ShapeDtypeStruct((k,) + tuple(b.shape),
                                                   b.dtype)
                              for b in batch)
                batch_sh = tuple(
                    NamedSharding(mesh, P(None, *sh.spec))
                    for sh in batch_sh)
            ck = _targets.cache_key(ck_name, mesh.shape, p_sh, batch_sh,
                                    batch=batch)
            _hlo.lower_text(
                step, params, buffers, key, *batch,
                jit_kwargs={'in_shardings': (p_sh, b_sh, repl)
                            + batch_sh},
                lower_cache={}, cache_key=ck)
        finally:
            _env.set_mesh(prev)
        fp = _cc.fingerprint('lower-text', key=ck)
        if fp is not None and _cc.get('hlo', fp) is not None:
            entries.append({'tier': 'hlo', 'fingerprint': fp,
                            'description': desc})
        else:
            errors[desc] = 'entry not committed (cache disabled?)'
    except Exception as e:
        errors[desc] = repr(e)


def _precompile_serve(config_path, entries, errors):
    """--serve CONFIG: AOT-compile the WHOLE serving surface a config
    declares — every prompt-bucket prefill module and every
    (batch bucket x decode span) fused decode module
    (paddle_tpu/serving) — into the exec tier, so a serving cold
    start deserializes instead of tracing (zero cold-start compiles).
    Returns the engine's declared bucket set for the sidecar meta."""
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt as _gpt
    from paddle_tpu.serving import ServeConfig, ServingEngine
    try:
        with open(config_path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        errors[f'serve config {config_path}'] = repr(e)
        return None
    model_name = doc.get('model', 'small')
    builders = {'tiny': _gpt.gpt_tiny, 'small': _gpt.gpt_small}
    if model_name not in builders:
        errors[f'serve config {config_path}'] = \
            f'unknown model {model_name!r} (have {list(builders)})'
        return None
    paddle.seed(0)
    kw = dict(doc.get('model_kwargs') or {})
    kw.setdefault('dropout', 0.0)
    try:
        model = builders[model_name](**kw)
        engine = ServingEngine(model, ServeConfig.from_json(doc))
        serve_entries, serve_errors = engine.precompile()
    except Exception as e:
        errors[f'serve config {config_path}'] = repr(e)
        return None
    entries.extend(serve_entries)
    errors.update({f'serve: {k}': v for k, v in serve_errors.items()})
    return dict(engine.bucket_set(), model=model_name)


def _precompile_decode(model_name, shape, kwargs, entries, errors):
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt as _gpt
    B, T0, new = shape
    desc = f'gpt-decode {model_name} b{B} p{T0} n{new}'
    try:
        paddle.seed(0)
        builders = {'tiny': _gpt.gpt_tiny, 'small': _gpt.gpt_small}
        default_len = 128 if model_name == 'tiny' else 1024
        model = builders[model_name](
            max_seq_len=max(default_len, T0 + new), dropout=0.0)
        model.eval()
        fp, P = model.precompile_decode(B, T0, new, **kwargs)
        if fp is None:
            errors[desc] = 'no fingerprint (cache disabled?)'
            return
        from paddle_tpu.core import compile_cache as _cc
        if _cc.get('exec', fp) is None:
            # the export itself failed (non-exportable trace, torn
            # write, disk full) — recording the entry anyway would
            # make check_ckpt --deep fail LATER with no error at the
            # moment the operator could act
            errors[desc] = 'entry not committed (export failed?)'
            return
        entries.append({'tier': 'exec', 'fingerprint': fp,
                        'description': f'{desc} (bucket {P})'})
    except Exception as e:
        errors[desc] = repr(e)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='precompile',
        description='AOT-compile the declared bucket set into the '
                    'persistent compile cache and commit a sidecar '
                    'manifest next to a checkpoint run dir.')
    ap.add_argument('run_dir',
                    help='checkpoint run directory the sidecar '
                         'manifest is committed into (created if '
                         'absent)')
    ap.add_argument('--targets', default='gpt,widedeep,lenet',
                    help='comma-separated built-in train-step targets '
                         '(gpt,widedeep,lenet); "none" to skip')
    ap.add_argument('--mesh', metavar='SPEC', default=None,
                    help='mesh axes to lower the targets for, e.g. '
                         '"dp=4" or "dp=2,tp=2" (default: single '
                         'device, plus any reshape meshes recorded in '
                         'the run dir\'s newest commit manifest)')
    ap.add_argument('--fused-steps', metavar='K[,K2,...]', default=None,
                    help='additionally AOT-lower each target\'s '
                         'K-step FUSED train module (core.scan_loop '
                         'whole-loop compilation) for these chunk '
                         'lengths, e.g. "8,32" — a deploy training '
                         'with fused_steps=K then warm-starts its '
                         'fused module too')
    ap.add_argument('--gpt-decode', metavar='BxT0xNEW[,...]',
                    default=None,
                    help='gptgen decode bucket signatures to export, '
                         'e.g. "8x128x128,8x64x128" (prompt lengths '
                         'are bucketed to the next power of two)')
    ap.add_argument('--serve', metavar='CONFIG', default=None,
                    help='serving config JSON (paddle_tpu/serving '
                         'ServeConfig fields + "model"/"model_kwargs")'
                         ': AOT-compile its WHOLE declared bucket set '
                         '— every prompt-bucket prefill and every '
                         'batch-bucket fused decode module — so a '
                         'serving cold start deserializes instead of '
                         'tracing')
    ap.add_argument('--gpt-model', choices=('tiny', 'small'),
                    default='small',
                    help='GPT config the decode buckets compile for')
    ap.add_argument('--temperature', type=float, default=0.0,
                    help='decode sampling temperature baked into the '
                         'exported modules (default 0 = greedy)')
    ap.add_argument('--top-k', type=int, default=None,
                    help='decode top-k baked into the exported modules')
    ap.add_argument('--cache', metavar='DIR', default=None,
                    help='compile-cache directory (sets '
                         'PADDLE_TPU_COMPILE_CACHE for this run)')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable summary on stdout')
    args = ap.parse_args(argv)

    if args.cache:
        os.environ['PADDLE_TPU_COMPILE_CACHE'] = args.cache
    try:
        mesh_axes = _parse_mesh(args.mesh) if args.mesh else None
        decode = _parse_decode(args.gpt_decode) if args.gpt_decode \
            else []
    except ValueError as e:
        print(f'precompile: {e}', file=sys.stderr)
        return 2

    from paddle_tpu.core import compile_cache as _cc
    if not _cc.enabled():
        print('precompile: the persistent compile cache is disabled '
              f'({_cc.ENV_VAR}); nothing to do', file=sys.stderr)
        return 2

    target_names = [] if args.targets.strip().lower() == 'none' else \
        [t.strip() for t in args.targets.split(',') if t.strip()]
    meshes = [mesh_axes] if mesh_axes else [None]
    reshape = _reshape_meshes(args.run_dir)
    for m in reshape:
        if m not in meshes:
            meshes.append(m)

    try:
        fused = [int(x) for x in args.fused_steps.split(',')
                 if x.strip()] if args.fused_steps else []
        if any(x < 1 for x in fused):
            raise ValueError('--fused-steps wants K >= 1')
    except ValueError as e:
        print(f'precompile: {e}', file=sys.stderr)
        return 2

    entries, errors = [], {}
    for m in meshes:
        for name in target_names:
            _precompile_target(name, m, entries, errors)
            for k in fused:
                _precompile_target(name, m, entries, errors,
                                   fused_steps=k)
    kwargs = {'temperature': args.temperature, 'top_k': args.top_k}
    for shape in decode:
        _precompile_decode(args.gpt_model, shape, kwargs, entries,
                           errors)
    serve_buckets = None
    if args.serve:
        serve_buckets = _precompile_serve(args.serve, entries, errors)

    doc = _cc.write_precompile_manifest(
        args.run_dir, entries,
        meta={'meshes': [m or {} for m in meshes],
              'reshape_meshes': reshape,
              'fused_steps': fused,
              'serve_buckets': serve_buckets})
    summary = {'run_dir': os.path.abspath(args.run_dir),
               'cache_dir': _cc.cache_dir(),
               'entries': len(entries),
               'errors': errors,
               'meshes': doc['meshes']}
    if args.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        print(f'precompiled {len(entries)} artifact(s) into '
              f'{_cc.cache_dir()}')
        for e in entries:
            print(f'  {e["tier"]:<5} {e["fingerprint"][:16]}  '
                  f'{e["description"]}')
        for desc, err in errors.items():
            print(f'  FAILED {desc}: {err}')
        print(f'sidecar manifest: '
              f'{os.path.join(os.path.abspath(args.run_dir), _cc.PRECOMPILE_MANIFEST)}')
    return 1 if errors else 0


if __name__ == '__main__':
    sys.exit(main())
