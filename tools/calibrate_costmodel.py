#!/usr/bin/env python
"""calibrate_costmodel — fit measured alpha/beta for the collective
cost model from archived run telemetry.

The analytic cost model (paddle_tpu.analysis.costmodel) predicts a
collective's time as ``alpha * phases + beta * wire_bytes`` with
data-sheet constants.  A chip session that profiles its collectives
emits ``collective_observed`` telemetry events (op, wire_bytes,
phases, us); this harness replays those JSONL streams (and/or
run_report --json documents), fits alpha/beta per collective kind by
least squares, and writes the calibration table the planner consumes:

    python tools/calibrate_costmodel.py /ckpt/run7/telemetry \\
        -o calibration.json
    python tools/tpu_lint.py --plan --chips 256 \\
        --calibration calibration.json

No chip (and no jax install) required: stdlib-only over archived
JSONL, like run_report.  Sample sources, in priority order:

* ``collective_observed`` events in telemetry-*.jsonl / flightrec
  dumps — one (phases, wire_bytes, us) sample each;
* run_report ``--json`` documents (recognized by schema_version +
  collectives_cmp): each op row's aggregate observed_us /
  observed_wire_bytes / observed_phases becomes one sample.

Fit per op kind: ordinary least squares on
``us ~ alpha * phases + beta * wire_bytes`` via the 2x2 normal
equations, coefficients clamped at >= 0.  With fewer than
--min-samples samples (or a singular system — all samples the same
size), alpha is pinned to the analytic default and only beta is
fitted; kinds with no samples at all are left out of the table (the
cost model keeps its analytic estimate for them).

Output schema (costmodel.Calibration version 1):

    {"version": 1,
     "per_op": {"all-reduce": {"alpha_us": ..,
                               "beta_us_per_byte": ..,
                               "samples": N, "residual_us": ..}},
     "meta": {"sources": [...], "fitted_at": null}}
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import run_report  # noqa: E402  (stdlib-only sibling)

CALIBRATION_VERSION = 1
DEFAULT_ALPHA_US = 1.0      # costmodel.DEFAULT_LINK_LATENCY_US


def harvest(paths):
    """(samples, mem_samples, sources):
    samples = {op: [(phases, wire_bytes, us)]};
    mem_samples = [(predicted_peak_bytes, compiled_peak_bytes)] from
    ``memory_compiled`` events / run_report ``memory`` sections."""
    samples, mem_samples, sources = {}, [], []
    jsonls, flights = run_report.discover(paths)
    report_docs = []
    kept_flights = []
    for f in flights:
        # a run_report --json doc is also a .json file — sniff it
        try:
            with open(f) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and 'collectives_cmp' in doc:
            report_docs.append((f, doc))
        else:
            kept_flights.append(f)
    if jsonls or kept_flights:
        events, srcs, _skew = run_report.load_events(jsonls,
                                                     kept_flights)
        n = m = 0
        for e in events:
            kind = e.get('kind')
            if kind == 'memory_compiled':
                pred = e.get('predicted_peak_bytes')
                comp = e.get('compiled_peak_bytes')
                if pred and comp:
                    mem_samples.append((float(pred), float(comp)))
                    m += 1
                continue
            if kind != 'collective_observed':
                continue
            op = e.get('op')
            us = e.get('us')
            wire = e.get('wire_bytes')
            if op is None or us is None or wire is None:
                continue
            phases = e.get('phases') or 0
            samples.setdefault(op, []).append(
                (float(phases), float(wire), float(us)))
            n += 1
        sources.append({'type': 'events', 'files': len(srcs),
                        'samples': n, 'mem_samples': m})
    for f, doc in report_docs:
        n = m = 0
        for op, row in (doc.get('collectives_cmp') or {}).items():
            us = row.get('observed_us')
            wire = row.get('observed_wire_bytes') \
                or row.get('predicted_wire_bytes')
            phases = row.get('observed_phases') \
                or row.get('predicted_phases') or 0
            if us is None or wire is None:
                continue
            samples.setdefault(op, []).append(
                (float(phases), float(wire), float(us)))
            n += 1
        mem = doc.get('memory') or {}
        for name, row in (mem.get('modules') or {}).items():
            pred = row.get('predicted_peak_bytes')
            comp = row.get('compiled_peak_bytes')
            if pred and comp:
                mem_samples.append((float(pred), float(comp)))
                m += 1
        sources.append({'type': 'run_report', 'file': f,
                        'samples': n, 'mem_samples': m})
    return samples, mem_samples, sources


def fit_op(rows, *, min_samples=2, default_alpha=DEFAULT_ALPHA_US):
    """Least-squares ``us ~ alpha*phases + beta*wire`` over one op's
    samples.  Returns {'alpha_us', 'beta_us_per_byte', 'samples',
    'residual_us', 'mode'}."""
    n = len(rows)
    spp = sum(p * p for p, _, _ in rows)
    sww = sum(w * w for _, w, _ in rows)
    spw = sum(p * w for p, w, _ in rows)
    spu = sum(p * u for p, _, u in rows)
    swu = sum(w * u for _, w, u in rows)
    det = spp * sww - spw * spw
    alpha = beta = None
    mode = 'lstsq'
    # the system is singular when every sample has proportional
    # (phases, wire) — one buffer size profiled over and over
    if n >= min_samples and det > 1e-9 * max(spp, sww, 1.0):
        alpha = (spu * sww - swu * spw) / det
        beta = (swu * spp - spu * spw) / det
    if alpha is None or alpha < 0 or beta is None or beta < 0:
        # beta-only fallback: pin alpha to the analytic default and
        # attribute the rest to bandwidth (clamped at zero)
        mode = 'beta-only'
        alpha = float(default_alpha)
        num = sum(w * (u - alpha * p) for p, w, u in rows)
        beta = max(0.0, num / sww) if sww > 0 else 0.0
    resid = (sum((u - (alpha * p + beta * w)) ** 2
                 for p, w, u in rows) / n) ** 0.5
    return {'alpha_us': round(alpha, 6),
            'beta_us_per_byte': round(beta, 12),
            'samples': n, 'residual_us': round(resid, 3),
            'mode': mode}


def fit_peak_memory(rows):
    """Fit the liveness estimator's bias from (predicted, compiled)
    peak-byte pairs: least squares through the origin on
    ``compiled ~ bias * predicted``.  The planner multiplies its
    liveness peak by this bias before the HBM gate, so a bias > 1
    (estimator runs light vs what XLA actually reserves) makes the
    gate conservative.  Returns a per_op-style row under the
    ``peak_memory`` pseudo-kind, or None without usable samples."""
    rows = [(p, c) for p, c in rows if p > 0 and c > 0]
    if not rows:
        return None
    spp = sum(p * p for p, _ in rows)
    spc = sum(p * c for p, c in rows)
    bias = spc / spp if spp > 0 else 1.0
    n = len(rows)
    resid = (sum((c - bias * p) ** 2 for p, c in rows) / n) ** 0.5
    return {'bias': round(bias, 6), 'samples': n,
            'residual_bytes': round(resid, 1)}


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='calibrate_costmodel',
        description='Fit measured alpha/beta per collective kind from '
                    'archived telemetry; write the calibration table '
                    'the auto-sharding planner consumes.')
    ap.add_argument('paths', nargs='+',
                    help='telemetry dirs, telemetry-*.jsonl files, '
                         'flightrec-*.json dumps and/or run_report '
                         '--json documents')
    ap.add_argument('-o', '--output', default='calibration.json',
                    help='calibration table path (default: '
                         'calibration.json)')
    ap.add_argument('--min-samples', type=int, default=2,
                    help='fewest samples for a full alpha+beta fit '
                         '(below it: beta-only; default 2)')
    ap.add_argument('--json', action='store_true',
                    help='also print the table to stdout')
    args = ap.parse_args(argv)

    samples, mem_samples, sources = harvest(args.paths)
    if not samples and not mem_samples:
        print('calibrate_costmodel: no collective_observed or '
              f'memory_compiled samples under {args.paths} (a chip '
              'session that profiles its collectives emits the '
              'former, any compile choke point the latter; '
              'run_report --json docs also work)', file=sys.stderr)
        return 2
    per_op = {op: fit_op(rows, min_samples=args.min_samples)
              for op, rows in sorted(samples.items())}
    mem_row = fit_peak_memory(mem_samples)
    if mem_row is not None:
        per_op['peak_memory'] = mem_row
    doc = {'version': CALIBRATION_VERSION, 'per_op': per_op,
           'meta': {'sources': sources,
                    'total_samples': sum(len(r)
                                         for r in samples.values())
                    + len(mem_samples)}}
    with open(args.output, 'w') as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        for op, row in per_op.items():
            if op == 'peak_memory':
                print(f'{op}: bias={row["bias"]} '
                      f'(compiled/predicted; {row["samples"]} '
                      f'samples, rms {row["residual_bytes"]} B)')
                continue
            print(f'{op}: alpha={row["alpha_us"]} us/hop  '
                  f'beta={row["beta_us_per_byte"]:.3e} us/B  '
                  f'({row["samples"]} samples, {row["mode"]}, '
                  f'rms {row["residual_us"]} us)')
        print(f'wrote {args.output}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
