#!/usr/bin/env python
"""Thread-vs-process DataLoader crossover (VERDICT r4 task 6).

Two synthetic pipelines over the same 96-sample dataset:
  numpy-heavy — big vectorized augment (releases the GIL inside numpy);
  PIL-heavy   — PIL decode/resize/rotate per sample (holds the GIL for
                most of its runtime).
Each runs sync (num_workers=0), threaded, and process
(use_process_workers=True) and prints one JSON line per cell.

Expectation (multi-core host): threads win numpy-heavy (no pickle/IPC
cost), processes win PIL-heavy (threads serialize on the GIL).  On a
single-core host neither can beat sync — the run still validates
overheads and correctness.  Results land in the io module docstring.
"""
import io as _io
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class NumpyHeavy:
    """Vectorized augment: GIL-releasing numpy on a 256x256x3 image."""

    def __init__(self, n=96, seed=0):
        self.n = n
        rs = np.random.RandomState(seed)
        self.base = rs.randint(0, 255, size=(256, 256, 3)).astype('uint8')

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        x = self.base.astype('float32')
        for _ in range(6):                 # ~50 MFLOP of elementwise
            x = np.sqrt(x * 1.01 + i % 7) * 0.99 + 0.5
        return x.mean(axis=2), np.array([i % 2], dtype='int64')


class PILHeavy:
    """Per-sample JPEG decode + resize + rotate: Python/PIL-bound."""

    def __init__(self, n=96, seed=0):
        from PIL import Image
        self.n = n
        rs = np.random.RandomState(seed)
        img = Image.fromarray(
            rs.randint(0, 255, size=(512, 512, 3)).astype('uint8'))
        buf = _io.BytesIO()
        img.save(buf, format='JPEG', quality=90)
        self.jpeg = buf.getvalue()

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        from PIL import Image
        img = Image.open(_io.BytesIO(self.jpeg))
        img = img.rotate(i % 360, resample=Image.BILINEAR)
        img = img.resize((224, 224), resample=Image.BICUBIC)
        return (np.asarray(img, dtype='float32') / 255.0,
                np.array([i % 2], dtype='int64'))


def run(ds, mode, num_workers=4, batch_size=8):
    from paddle_tpu.io import DataLoader
    kw = dict(batch_size=batch_size, to_tensor=False)
    if mode == 'sync':
        loader = DataLoader(ds, num_workers=0, **kw)
    elif mode == 'threads':
        loader = DataLoader(ds, num_workers=num_workers, **kw)
    elif mode == 'process':
        loader = DataLoader(ds, num_workers=num_workers,
                            use_process_workers=True, **kw)
    else:
        raise ValueError(mode)
    n = 0
    t0 = time.time()
    for xb, _ in loader:
        n += xb.shape[0]
    dt = time.time() - t0
    return n / dt, dt


def main():
    workers = int(os.environ.get('BENCH_DL_WORKERS', '4'))
    for name, ds in [('numpy_heavy', NumpyHeavy()),
                     ('pil_heavy', PILHeavy())]:
        for mode in ('sync', 'threads', 'process'):
            # warm one epoch (forkserver start, native ring build)
            run(ds, mode, num_workers=workers)
            sps, dt = run(ds, mode, num_workers=workers)
            print(json.dumps({'pipeline': name, 'mode': mode,
                              'workers': 0 if mode == 'sync' else workers,
                              'nproc': os.cpu_count(),
                              'samples_per_sec': round(sps, 1),
                              'epoch_s': round(dt, 3)}), flush=True)


if __name__ == '__main__':
    main()
