#!/usr/bin/env python
"""Ring-attention evidence on the virtual CPU mesh.

Two claims, two measurements suited to THIS box (devices are
time-sliced on one core, so wall-clock tracks TOTAL work, while the
striped layout's win is about the per-step CRITICAL PATH on parallel
hardware):

1. MEASURED — the causal ring's lax.cond skip of fully-masked future
   blocks: causal wall-clock should be ~half of non-causal on the
   serialized mesh (the skip removes ~half the total block FLOPs).
2. EXACT SCHEDULE — per-device flash-kernel tile counts for the
   contiguous vs striped layouts.  The busiest device bounds the
   per-step critical path on real parallel chips; striping halves it.

    python tools/bench_ring.py [--t 2048] [--bh 4] [--d 64] [--sp 4]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def tile_counts(sp, nq, nk):
    """Flash-kernel tiles computed per device over a full ring pass
    (the pl.when skip drops tiles above the causal diagonal)."""
    full = nq * nk
    diag = sum(min(nk, (qi * 1 + 1)) for qi in range(nq))  # bq == bk
    strict = diag  # same skip bound; the extra masked diagonal tile
    #                is zeroed in-kernel, not skipped
    contig = [r * full + diag for r in range(sp)]
    striped = [(r + 1) * diag + (sp - 1 - r) * strict
               for r in range(sp)]
    return contig, striped


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--t', type=int, default=2048)
    ap.add_argument('--bh', type=int, default=4)
    ap.add_argument('--d', type=int, default=64)
    ap.add_argument('--sp', type=int, default=4)
    ap.add_argument('--iters', type=int, default=5)
    args = ap.parse_args()

    # CPU-only by design (the ring needs sp>1 devices; the dev setup
    # has one TPU): force the virtual CPU mesh even when the global
    # env points at the accelerator plugin.  The env vars alone latch
    # too late when sitecustomize pre-imports jax, so ALSO update the
    # live config before any backend initializes.
    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '')
        + f' --xla_force_host_platform_device_count={args.sp}')

    import functools
    import numpy as np
    import jax
    jax.config.update('jax_platforms', 'cpu')
    try:
        jax.config.update('jax_num_cpu_devices', args.sp)
    except AttributeError:
        pass  # older jax: XLA_FLAGS above covers it
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.ops.ring_attention import ring_attention

    rs = np.random.RandomState(0)
    BH, T, D, SP = args.bh, args.t, args.d, args.sp
    q, k, v = (jnp.asarray(rs.randn(BH, T, D), jnp.float32)
               for _ in range(3))
    mesh = Mesh(np.array(jax.devices()[:SP]).reshape(SP), ('sp',))
    spec = P(None, 'sp', None)

    def ring(causal):
        return jax.jit(jax.shard_map(
            functools.partial(ring_attention, axis_name='sp',
                              causal=causal, use_flash=False),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))

    def timeit(fn, *xs):
        jax.block_until_ready(fn(*xs))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(*xs)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.iters * 1e3

    ms_full = timeit(ring(False), q, k, v)
    ms_causal = timeit(ring(True), q, k, v)
    print(f'T={T} sp={SP} bh={BH} d={D} (einsum engine, serialized '
          f'CPU mesh -> wall-clock == total FLOPs)', file=sys.stderr)
    print(f'non-causal ring (all blocks): {ms_full:8.1f} ms',
          file=sys.stderr)
    print(f'causal ring (cond skip):      {ms_causal:8.1f} ms  '
          f'({ms_full / ms_causal:.2f}x less work)', file=sys.stderr)

    t_local = T // SP
    nq = nk = max(1, t_local // 128)
    contig, striped = tile_counts(SP, nq, nk)
    print(f'flash tile schedule (per-device, one ring pass, '
          f'{nq}x{nk} tiles/block):', file=sys.stderr)
    print(f'  contiguous: {contig}  max={max(contig)}', file=sys.stderr)
    print(f'  striped:    {striped}  max={max(striped)}',
          file=sys.stderr)
    print(f'  critical-path ratio (contig/striped): '
          f'{max(contig) / max(striped):.2f}x on parallel devices',
          file=sys.stderr)
    import json
    print(json.dumps({
        'noncausal_ms': ms_full, 'causal_ms': ms_causal,
        'skip_work_ratio': ms_full / ms_causal,
        'tiles_contig_max': max(contig),
        'tiles_striped_max': max(striped),
        'critical_path_ratio': max(contig) / max(striped)}))


if __name__ == '__main__':
    main()
