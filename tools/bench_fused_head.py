#!/usr/bin/env python
"""A/B the fused LM head (ops/fused_ce.py) against the unfused path.

Times a GPT-2-small training step with fused_head on/off on whatever
device jax sees (the real chip when the tunnel is up; --smoke for a
CPU sanity pass), and prints tokens/s + step ms + estimated MFU for
both.  This is the one-command measurement for VERDICT r3 task 2
(close the transformer MFU gap): run it on the chip, paste the table
into PERF.md.

Usage:
    python tools/bench_fused_head.py [--smoke] [--iters 15]
        [--batch 8] [--seq 1024] [--chunks 8]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def bench(fused, args):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt_small, gpt_tiny
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet, env as dist_env

    paddle.seed(0)
    if args.smoke:
        model = gpt_tiny(fused_head=fused,
                         fused_head_chunks=args.chunks)
        batch, seq = 2, 128
    else:
        model = gpt_small(max_seq_len=args.seq, dropout=0.0,
                          fused_head=fused,
                          fused_head_chunks=args.chunks)
        batch, seq = args.batch, args.seq
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    trainer = ParallelTrainer(model, opt,
                              lambda out, y: model.loss(out, y),
                              strategy=strategy)
    rs = np.random.RandomState(0)
    V = model.config.vocab_size
    ids = jax.device_put(
        rs.randint(0, V, size=(batch, seq)).astype('int64'))
    loss = None
    for _ in range(args.warmup):
        loss = trainer.step(ids, ids)
    jax.block_until_ready(loss)
    t0 = time.time()
    for _ in range(args.iters):
        loss = trainer.step(ids, ids)
    jax.block_until_ready(loss)
    # the readback stays INSIDE the timed region on purpose:
    # block_until_ready has returned early on tunnel-remote arrays
    # (PERF.md round-3 methodology), so the float() is the only
    # trustworthy completion barrier.  Its constant ~1 round trip
    # inflates both arms equally — the fused/unfused RATIO is the
    # number to trust; absolute tok/s carries the offset.
    float(np.asarray(loss).ravel()[0])
    dt = time.time() - t0
    toks = batch * seq * args.iters / dt
    # 6 * params * tokens FLOPs estimate (fwd+bwd), v5e peak 197 TF/s
    n_params = sum(
        int(np.prod(p.shape)) for p in model.parameters())
    flops = 6.0 * n_params * batch * seq / (dt / args.iters)
    mfu = flops / 197e12
    dist_env.set_mesh(None)
    return {'tokens_per_s': toks, 'ms_per_step': dt / args.iters * 1e3,
            'mfu_est': mfu, 'loss': float(np.asarray(loss).ravel()[0])}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true')
    ap.add_argument('--iters', type=int, default=15)
    ap.add_argument('--warmup', type=int, default=3)
    ap.add_argument('--batch', type=int, default=8)
    ap.add_argument('--seq', type=int, default=1024)
    ap.add_argument('--chunks', type=int, default=8)
    ap.add_argument('--arm', choices=['both', 'fused', 'unfused'],
                    default='both',
                    help='chunk sweeps only need the fused arm — the '
                         'unfused baseline does not depend on --chunks')
    args = ap.parse_args()
    if args.smoke:
        args.iters, args.warmup = 3, 2

    import jax
    print(f'device: {jax.devices()[0]}', file=sys.stderr)
    rows = {}
    arms = {'both': (False, True), 'fused': (True,),
            'unfused': (False,)}[args.arm]
    for fused in arms:
        name = 'fused' if fused else 'unfused'
        rows[name] = r = bench(fused, args)
        print(f"{name}: {r['tokens_per_s']:.0f} tok/s "
              f"({r['ms_per_step']:.1f} ms, MFU~{r['mfu_est']:.1%}) "
              f"loss={r['loss']:.4f}", file=sys.stderr)
    if len(rows) == 2:
        print(f"speedup: {rows['fused']['tokens_per_s'] / rows['unfused']['tokens_per_s']:.3f}x",
              file=sys.stderr)
    import json
    print(json.dumps(rows))


if __name__ == '__main__':
    main()
