#!/usr/bin/env python
"""Flash-attention block autotune sweep (PERF.md round-3 lead 4).

Run ON THE REAL CHIP; writes winners into
paddle_tpu/ops/flash_attention_tuning.json, which flash_attention()
consults per shape at call time.

    python tools/tune_flash.py                  # standard shape sweep
    python tools/tune_flash.py --tq 4096 --d 128
"""
import argparse
import sys

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--tq', type=int, default=None)
    ap.add_argument('--tk', type=int, default=None)
    ap.add_argument('--d', type=int, default=None)
    ap.add_argument('--bh', type=int, default=8)
    ap.add_argument('--no-causal', action='store_true')
    args = ap.parse_args()

    from paddle_tpu.ops.flash_attention import autotune_blocks

    if args.tq:
        shapes = [(args.tq, args.tk or args.tq, args.d or 128)]
    else:
        # the bench/model shapes: GPT-2 small T=1024 d=64, BERT s128
        # (too small for pallas — skipped by the gate), longctx bench
        # = GPT-2 small at T=4096 so d stays 64, long-ctx 4096/8192
        # at d=128 for the larger-model face
        shapes = [(1024, 1024, 64), (2048, 2048, 64), (4096, 4096, 64),
                  (2048, 2048, 128), (4096, 4096, 128),
                  (8192, 8192, 128)]
    causal = not args.no_causal
    for tq, tk, d in shapes:
        best, ms = autotune_blocks(tq, tk, d, causal=causal, bh=args.bh)
        print(f'T={tq}x{tk} d={d} causal={causal}: best blocks={best} '
              f'({ms:.2f} ms/call)', flush=True)


if __name__ == '__main__':
    main()
