#!/usr/bin/env python
"""check_ckpt — verify a checkpoint directory's commit manifests.

Operator-facing triage for the question "which step can I actually
restore?" after a host died mid-save:

    python tools/check_ckpt.py RUN_DIR             # summary + latest
    python tools/check_ckpt.py RUN_DIR --no-checksums   # sizes only
    python tools/check_ckpt.py RUN_DIR --step 120       # one step
    python tools/check_ckpt.py RUN_DIR --quiet          # just the step
    python tools/check_ckpt.py RUN_DIR --deep           # forensic mode

Exit codes: 0 = at least one verified step exists, 1 = none do,
2 = usage error.  Prints the latest COMMITTED+VERIFIED step on the
last stdout line, so scripts can `$(... | tail -1)`.

``--deep`` re-hashes EVERY shard of every committed step against the
manifest digests and classifies each failure, exiting with a distinct
code per class so automation can branch on the cause:

    3 = torn       (file truncated / size mismatch / some-but-not-all
                    of a host's shards missing, or 2-phase acks with
                    no final manifest)
    4 = missing host  (ALL shards attributed to some host are gone, or
                    a host's 2-phase ack never landed — the pod lost a
                    worker mid-commit)
    5 = digest mismatch  (sizes intact, bytes rotted — storage-level
                    corruption)
    6 = precompile manifest invalid  (the run dir carries a
                    _PADDLE_PRECOMPILE.json sidecar — tools/
                    precompile.py's AOT warm-start set — but some
                    listed compile-cache entry is missing, torn, or
                    the cache is disabled: a restore would fall back
                    to full recompilation)
    7 = rank-set mismatch  (``--cluster`` only: the hosts attributed
                    in a multi-process manifest do not cover the
                    recorded ``process_count`` — some rank's shards
                    were never part of the commit, or the manifest's
                    own hosts/process_count disagree; a restore on
                    the recorded topology would be missing state)

``--deep --cluster`` additionally validates each committed step's
per-host shard set against the ``process_count`` the multi-process
save recorded in its manifest (save_host_shard / save_sharded both
record it).

When several classes occur, missing-host wins over torn over digest
over rank-set over precompile (ordered by how actionable the triage
is).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.core import compile_cache as CC  # noqa: E402
from paddle_tpu.resilience import manifest as M  # noqa: E402

EXIT_TORN = 3
EXIT_MISSING_HOST = 4
EXIT_DIGEST = 5
EXIT_PRECOMPILE = 6
EXIT_RANK_SET = 7


def _step_dirs(directory, prefix):
    out = []
    for f in sorted(os.listdir(directory)):
        tag = f[len(prefix) + 1:]
        if f.startswith(prefix + '_') and tag.isdigit():
            out.append((int(tag), os.path.join(directory, f)))
    return sorted(out)


def deep_check(step_dir, cluster=False):
    """Forensic classification of one step dir.

    Returns (classes, details): `classes` ⊆ {'torn', 'missing_host',
    'digest', 'rank_set'}, `details` human-readable lines.  Re-hashes
    every manifest-recorded file (full read — this is the slow,
    thorough mode) and cross-checks the two-phase commit records when
    present: a host whose EVERY shard is absent (or whose ack is
    missing from a half-committed dir) is a lost worker, not a torn
    file.

    `cluster` additionally audits the RANK SET of a multi-process
    save: the hosts attributed across the manifest's files must cover
    exactly ``range(process_count)`` as recorded at save time, and
    the manifest's own ``hosts`` field must agree — a manifest that
    certifies 2 ranks of a 4-process save restores silently
    incomplete state on the recorded topology."""
    doc = M.read_manifest(step_dir)
    classes, details = set(), []
    if doc is None:
        intents = M.read_intents(step_dir)
        if intents:
            classes.add('torn')
            details.append(
                f'half-committed: {len(intents)} two-phase ack(s) '
                f'(hosts {sorted(intents)}) but no final manifest')
        else:
            details.append('uncommitted (no manifest, no acks)')
        return classes, details
    algo = doc.get('algo', 'sha256')
    per_host = {}            # host -> [rel, ...]
    missing_by_host = {}     # host -> [rel, ...]
    for rel, meta in sorted(doc.get('files', {}).items()):
        host = meta.get('host', 0)
        per_host.setdefault(host, []).append(rel)
        p = os.path.join(step_dir, rel)
        if not os.path.isfile(p):
            missing_by_host.setdefault(host, []).append(rel)
            continue
        size = os.path.getsize(p)
        if size != meta.get('size'):
            classes.add('torn')
            details.append(
                f'{rel}: size {size} != recorded {meta.get("size")}')
            continue
        if algo in meta and M.file_checksum(p, algo) != meta[algo]:
            classes.add('digest')
            details.append(f'{rel}: {algo} mismatch (size intact)')
    for host, missing in sorted(missing_by_host.items()):
        if len(missing) == len(per_host[host]):
            classes.add('missing_host')
            details.append(
                f'host {host}: ALL {len(missing)} shard(s) missing')
        else:
            classes.add('torn')
            details.extend(f'{rel}: missing' for rel in missing[:5])
    hosts = doc.get('hosts')
    if hosts:
        for h in range(hosts):
            if h not in per_host:
                classes.add('missing_host')
                details.append(
                    f'host {h}: no files attributed in the manifest')
    if cluster:
        procs = doc.get('process_count')
        attributed = set(per_host)
        if procs is None:
            classes.add('rank_set')
            details.append(
                'manifest records no process_count — not a '
                'multi-process save (or saved before the cluster '
                'format); the rank set cannot be validated')
        else:
            expected = set(range(int(procs)))
            if hosts is not None and int(hosts) != int(procs):
                classes.add('rank_set')
                details.append(
                    f'manifest hosts={hosts} disagrees with recorded '
                    f'process_count={procs}')
            extra = sorted(attributed - expected)
            absent = sorted(expected - attributed)
            if extra:
                classes.add('rank_set')
                details.append(
                    f'shards attributed to rank(s) {extra} outside '
                    f'the recorded process_count={procs}')
            if absent:
                classes.add('rank_set')
                details.append(
                    f'rank(s) {absent} of process_count={procs} own '
                    'no shard in the manifest')
    return classes, details


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='check_ckpt',
        description='Verify commit manifests in a CheckpointManager '
                    'directory and print the latest committed step.')
    ap.add_argument('directory', help='checkpoint run directory')
    ap.add_argument('--prefix', default='step',
                    help='step-dir prefix (default: step)')
    ap.add_argument('--step', type=int, default=None,
                    help='verify only this step')
    ap.add_argument('--no-checksums', action='store_true',
                    help='skip checksum recompute (sizes/presence '
                         'only — fast triage for TB-scale dirs)')
    ap.add_argument('--deep', action='store_true',
                    help='re-hash every per-host shard against the '
                         'manifest digests (and audit the '
                         '_PADDLE_PRECOMPILE.json AOT sidecar when '
                         'present) and exit with a distinct code per '
                         'failure class: 3=torn, 4=missing host, '
                         '5=digest mismatch, 6=precompile manifest '
                         'invalid')
    ap.add_argument('--cluster', action='store_true',
                    help='with --deep: validate each committed step\'s '
                         'per-host shard set against the manifest\'s '
                         'recorded process_count (multi-process '
                         'saves); exit 7 on a rank-set mismatch')
    ap.add_argument('--adopt', action='store_true',
                    help='write commit manifests for UNCOMMITTED step '
                         'dirs (migrates checkpoints from before '
                         'verified commits — only run this on dirs '
                         'you trust to be complete)')
    ap.add_argument('--quiet', action='store_true',
                    help='print only the latest committed step')
    args = ap.parse_args(argv)

    if not os.path.isdir(args.directory):
        print(f'error: {args.directory} is not a directory',
              file=sys.stderr)
        return 2

    dirs = _step_dirs(args.directory, args.prefix)
    if args.step is not None:
        dirs = [(s, p) for s, p in dirs if s == args.step]
        if not dirs:
            print(f'error: no {args.prefix}_{args.step} under '
                  f'{args.directory}', file=sys.stderr)
            return 1

    latest_ok = -1
    deep_classes = set()
    for s, p in dirs:
        if args.deep:
            classes, details = deep_check(p, cluster=args.cluster)
            deep_classes |= classes
            ok_deep = not classes and M.read_manifest(p) is not None
            if ok_deep:
                latest_ok = max(latest_ok, s)
            if not args.quiet:
                status = 'ok (deep)' if ok_deep else \
                    'FAIL [' + ', '.join(sorted(classes) or
                                         ['uncommitted']) + ']'
                print(f'{args.prefix}_{s}: {status}')
                for line in details[:8]:
                    print(f'    {line}')
            continue
        doc = M.read_manifest(p)
        if doc is None and args.adopt:
            M.write_manifest(p, step=s)
            doc = M.read_manifest(p)
            if not args.quiet:
                print(f'{args.prefix}_{s}: adopted (manifest written)')
        if doc is None:
            status = 'UNCOMMITTED (no manifest — torn or in-flight)'
        else:
            ok, errors = M.verify_manifest(
                p, checksums=not args.no_checksums)
            if ok:
                status = 'ok ({} files{})'.format(
                    len(doc.get('files', {})),
                    ', sizes only' if args.no_checksums else '')
                latest_ok = max(latest_ok, s)
            else:
                status = 'CORRUPT: ' + '; '.join(errors[:5])
        if not args.quiet:
            print(f'{args.prefix}_{s}: {status}')

    torn = [f for f in os.listdir(args.directory) if '.torn-' in f]
    if torn and not args.quiet:
        print(f'quarantined: {", ".join(sorted(torn))}')

    precompile_bad = False
    pc_present = args.deep and os.path.exists(
        os.path.join(args.directory, CC.PRECOMPILE_MANIFEST))
    pc_doc = CC.read_precompile_manifest(args.directory) \
        if pc_present else None
    if pc_present:
        # an unparseable sidecar must FAIL the audit, not read as
        # 'no sidecar' — a restore would silently fall back to full
        # recompilation
        # a declared AOT warm-start set rides with this run dir:
        # audit every listed compile-cache entry so a restore target's
        # deserialization path is provable, not hoped-for
        ok_pc, pc_errors = CC.verify_precompile_manifest(args.directory)
        precompile_bad = not ok_pc
        if not args.quiet:
            n = len((pc_doc or {}).get('entries', []))
            status = f'ok ({n} AOT entries verified)' if ok_pc else \
                'FAIL [precompile]'
            print(f'precompile manifest: {status}')
            for line in pc_errors[:8]:
                print(f'    {line}')

    if not args.quiet:
        print('latest committed step:', latest_ok)
    else:
        print(latest_ok)
    if args.deep and (deep_classes or precompile_bad):
        # precedence: a lost worker beats a torn file beats bit rot
        # beats an inconsistent rank set beats a cold AOT set — the
        # operator's next action differs per class
        if 'missing_host' in deep_classes:
            return EXIT_MISSING_HOST
        if 'torn' in deep_classes:
            return EXIT_TORN
        if 'digest' in deep_classes:
            return EXIT_DIGEST
        if 'rank_set' in deep_classes:
            return EXIT_RANK_SET
        return EXIT_PRECOMPILE
    return 0 if latest_ok >= 0 else 1


if __name__ == '__main__':
    sys.exit(main())
