#!/usr/bin/env python
"""check_ckpt — verify a checkpoint directory's commit manifests.

Operator-facing triage for the question "which step can I actually
restore?" after a host died mid-save:

    python tools/check_ckpt.py RUN_DIR             # summary + latest
    python tools/check_ckpt.py RUN_DIR --no-checksums   # sizes only
    python tools/check_ckpt.py RUN_DIR --step 120       # one step
    python tools/check_ckpt.py RUN_DIR --quiet          # just the step

Exit codes: 0 = at least one verified step exists, 1 = none do,
2 = usage error.  Prints the latest COMMITTED+VERIFIED step on the
last stdout line, so scripts can `$(... | tail -1)`.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.resilience import manifest as M  # noqa: E402


def _step_dirs(directory, prefix):
    out = []
    for f in sorted(os.listdir(directory)):
        tag = f[len(prefix) + 1:]
        if f.startswith(prefix + '_') and tag.isdigit():
            out.append((int(tag), os.path.join(directory, f)))
    return sorted(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='check_ckpt',
        description='Verify commit manifests in a CheckpointManager '
                    'directory and print the latest committed step.')
    ap.add_argument('directory', help='checkpoint run directory')
    ap.add_argument('--prefix', default='step',
                    help='step-dir prefix (default: step)')
    ap.add_argument('--step', type=int, default=None,
                    help='verify only this step')
    ap.add_argument('--no-checksums', action='store_true',
                    help='skip checksum recompute (sizes/presence '
                         'only — fast triage for TB-scale dirs)')
    ap.add_argument('--adopt', action='store_true',
                    help='write commit manifests for UNCOMMITTED step '
                         'dirs (migrates checkpoints from before '
                         'verified commits — only run this on dirs '
                         'you trust to be complete)')
    ap.add_argument('--quiet', action='store_true',
                    help='print only the latest committed step')
    args = ap.parse_args(argv)

    if not os.path.isdir(args.directory):
        print(f'error: {args.directory} is not a directory',
              file=sys.stderr)
        return 2

    dirs = _step_dirs(args.directory, args.prefix)
    if args.step is not None:
        dirs = [(s, p) for s, p in dirs if s == args.step]
        if not dirs:
            print(f'error: no {args.prefix}_{args.step} under '
                  f'{args.directory}', file=sys.stderr)
            return 1

    latest_ok = -1
    for s, p in dirs:
        doc = M.read_manifest(p)
        if doc is None and args.adopt:
            M.write_manifest(p, step=s)
            doc = M.read_manifest(p)
            if not args.quiet:
                print(f'{args.prefix}_{s}: adopted (manifest written)')
        if doc is None:
            status = 'UNCOMMITTED (no manifest — torn or in-flight)'
        else:
            ok, errors = M.verify_manifest(
                p, checksums=not args.no_checksums)
            if ok:
                status = 'ok ({} files{})'.format(
                    len(doc.get('files', {})),
                    ', sizes only' if args.no_checksums else '')
                latest_ok = max(latest_ok, s)
            else:
                status = 'CORRUPT: ' + '; '.join(errors[:5])
        if not args.quiet:
            print(f'{args.prefix}_{s}: {status}')

    torn = [f for f in os.listdir(args.directory) if '.torn-' in f]
    if torn and not args.quiet:
        print(f'quarantined: {", ".join(sorted(torn))}')

    if not args.quiet:
        print('latest committed step:', latest_ok)
    else:
        print(latest_ok)
    return 0 if latest_ok >= 0 else 1


if __name__ == '__main__':
    sys.exit(main())
