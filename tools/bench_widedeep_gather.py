#!/usr/bin/env python
"""A/B the Wide&Deep fused single-table gather against reference-style
per-field tables (VERDICT r4 task 5, PERF round-3 lead 3).

Times a full Wide&Deep training step (criteo-like: 26 sparse fields of
100k rows, 13 dense, AMP) with `fused_gather` on/off on whatever
device jax sees, and prints examples/s for both plus the speedup.
Kept-or-killed verdict: the fused gather stays the default only if it
wins on chip.

Usage:
    python tools/bench_widedeep_gather.py [--smoke] [--iters 20]
        [--batch 16384]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def bench(fused, args):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.widedeep import WideDeep
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet, env as dist_env

    paddle.seed(0)
    if args.smoke:
        batch, fields, dense_dim, hidden = 256, [1000] * 4, 4, (32,)
    else:
        batch, fields, dense_dim, hidden = (args.batch, [100_000] * 26,
                                            13, (400, 400, 400))
    model = WideDeep(fields, dense_dim=dense_dim, embed_dim=16,
                     hidden=hidden, fused_gather=fused)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    bce = nn.BCEWithLogitsLoss()
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    trainer = ParallelTrainer(model, opt, lambda o, y: bce(o, y),
                              n_inputs=2, strategy=strategy)
    rs = np.random.RandomState(0)
    ids = jax.device_put(np.stack(
        [rs.randint(0, f, size=batch) for f in fields],
        axis=1).astype('int64'))
    dense = jax.device_put(rs.rand(batch, dense_dim).astype('float32'))
    y = jax.device_put(
        rs.randint(0, 2, size=(batch, 1)).astype('float32'))
    loss = None
    for _ in range(args.warmup):
        loss = trainer.step(ids, dense, y)
    jax.block_until_ready(loss)
    t0 = time.time()
    for _ in range(args.iters):
        loss = trainer.step(ids, dense, y)
    jax.block_until_ready(loss)
    # readback inside the timed region: the only trustworthy barrier
    # over the tunnel (PERF.md round-3 methodology); inflates both
    # arms equally, the ratio is the number to trust
    float(np.asarray(loss).ravel()[0])
    dt = time.time() - t0
    dist_env.set_mesh(None)
    return {'examples_per_s': batch * args.iters / dt,
            'ms_per_step': dt / args.iters * 1e3,
            'loss': float(np.asarray(loss).ravel()[0])}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true')
    ap.add_argument('--iters', type=int, default=20)
    ap.add_argument('--warmup', type=int, default=4)
    ap.add_argument('--batch', type=int, default=16384)
    args = ap.parse_args()
    if args.smoke:
        args.iters, args.warmup = 3, 2

    import jax
    print(f'device: {jax.devices()[0]}', file=sys.stderr)
    rows = {}
    for fused in (True, False):
        name = 'fused' if fused else 'per_field'
        rows[name] = r = bench(fused, args)
        print(f"{name}: {r['examples_per_s']:.0f} ex/s "
              f"({r['ms_per_step']:.1f} ms) loss={r['loss']:.4f}",
              file=sys.stderr)
    rows['speedup_fused_over_per_field'] = (
        rows['fused']['examples_per_s'] /
        rows['per_field']['examples_per_s'])
    print(f"speedup: {rows['speedup_fused_over_per_field']:.3f}x",
          file=sys.stderr)
    print(json.dumps(rows))


if __name__ == '__main__':
    main()
