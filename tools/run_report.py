#!/usr/bin/env python
"""run_report — merge per-host telemetry JSONL into one run report.

The telemetry layer (paddle_tpu.telemetry) streams rank-tagged events
to one ``telemetry-r<rank>.jsonl`` per host; resilience additionally
drops ``flightrec-*.json`` flight-recorder dumps next to checkpoints.
This CLI merges all of them and reconstructs what happened:

    python tools/run_report.py <dir>            # human report
    python tools/run_report.py <dir> --json     # bench/CI schema
    python tools/run_report.py a.jsonl b.jsonl  # explicit files

Report sections:
  * step-time percentiles per loop tag (p50/p90/p99, from the
    accumulators' flushed ``steps`` events);
  * compile: total seconds + per-name breakdown, retrace count;
  * device-step vs host-wait split (step_time vs dataloader wait);
  * collectives census (per-op calls/bytes, when a mesh step emitted
    one) side by side with the compile-time COST-MODEL PREDICTION
    (``collective_cost`` events: torus wire bytes + alpha-beta time
    estimate per op — analysis.costmodel) and, when a chip session
    profiled them, OBSERVED per-collective timings
    (``collective_observed`` events — the fit input for
    tools/calibrate_costmodel.py);
  * the auto-sharding plan (``plan_selected``): which (mesh,
    PartitionSpec) candidate the planner chose and its predicted
    wire/peak numbers joined against the observed census — every
    auto-sharded run reports predicted-vs-actual for the plan that
    was picked;
  * the serving section (``serve_step``/``serve_request`` joined):
    TTFT/TPOT percentiles, tokens/s, eviction/preemption counts by
    cause, per-request timeline rows, lifecycle traces
    (``serve_trace``) and any ``slo_breach``/``drift_detected``
    monitor alerts;
  * the resilience event timeline (preemption, nan_skip/rollback,
    checkpoint save/commit/restore/quarantine, SLO breaches and
    drift detections) in wall-clock order.

Multi-host merges: per-host wall clocks drift (pods give no NTP
guarantee), so each host's events are re-anchored to its first
``steps`` event before ordering — SPMD stepping is lockstep, making
that the one cross-host moment the streams share.  The applied
offsets land in ``clock_skew``; anchoring is skipped when any host
never stepped (nothing trustworthy to anchor on).

``--json`` emits one stable dict (schema_version 1, additively
extended) that bench.py and CI consume; tests/test_event_telemetry.py
schema-checks it.

Stdlib-only on purpose: it must run on a dev machine against JSONL
scraped off a dead worker, with no jax install.
"""
import argparse
import glob
import json
import os
import sys
import time

SCHEMA_VERSION = 1

RESILIENCE_KINDS = (
    'preemption', 'nan_skip', 'nan_rollback', 'nan_fatal',
    'checkpoint_save', 'checkpoint_commit', 'checkpoint_restore',
    'checkpoint_quarantine', 'flight_dump', 'crash',
    'commit_intent', 'commit_finalize', 'reshape_restore',
    'retry', 'restart_backoff', 'fault_injected',
    # watchdog / collective-layer supervision (PR 10): blown deadlines,
    # straggler attribution, lost heartbeat quorum, cluster aborts —
    # each row carries its rank, so a merged multi-host timeline shows
    # WHO hung and who merely waited
    'timeout', 'straggler', 'quorum_lost', 'coordinated_abort',
    # rolling SLO/drift monitors (telemetry.monitors): an SLO breach
    # or a predicted-vs-observed drift detection belongs on the same
    # timeline as the failures it predicts
    'slo_breach', 'drift_detected',
    # live cluster-view edges (telemetry.cluster monitors): who the
    # joined view blamed, and when the per-rank losses split
    'straggler_suspect', 'rank_divergence',
    # a fused K-chunk that exceeded the armed watchdog budget
    'fused_clamp',
    # the self-healing actuator (resilience.supervisor): how each
    # incident terminated (swap/hold/backoff/degraded + stage), and
    # the applied plan swap itself — the observe->act loop's act half
    # belongs on the same timeline as the sensor edges that caused it
    'remediation', 'plan_swap',
    # memory observatory (telemetry.memory + MemoryMonitor): live
    # bytes crossed the budget watermark — the edge the supervisor
    # re-plans on with a tightened hbm_budget_gb
    'memory_pressure',
    # collective flight recorder (distributed.collective): the first
    # divergent collective across ranks, with trigger/op/step/ranks
    # and per-rank call sites — the attributed refinement of a
    # generic timeout or rank_divergence
    'collective_mismatch')

# spans (kind='span', name=...) that belong on the resilience
# timeline: the 2-phase commit barrier wait and the restore itself
RESILIENCE_SPAN_NAMES = ('commit_barrier', 'checkpoint_restore')

# -- the EVENT_KINDS coverage contract ----------------------------------------
# telemetry.recorder.EVENT_KINDS is the emission vocabulary; this pair
# is the CONSUMPTION side.  The recorder meta-test asserts every
# declared kind is either in RENDERED_KINDS (analyze() reads it into a
# report section / the timeline) or in IGNORED_KINDS with a written
# reason — so an event can never again be emitted and silently dropped
# (the PR-12 serve_step/serve_request bug, prevented structurally).
RENDERED_KINDS = RESILIENCE_KINDS + (
    'steps',                # step-time percentiles / split / scalars
    'compile', 'retrace',   # compile section
    'compile_cache',        # cache section
    'collectives', 'collective_cost', 'collective_observed',
    'plan_selected',        # plan section
    'profile_capture',      # profile section
    'serve_step', 'serve_request', 'serve_trace',  # serving section
    'serve_reject',         # serving section: admission shed trail
    'fleet_event',          # serving section: router control plane
    'lint_finding',         # lint section
    'span',                 # spans table + resilience span rows
    'memory_compiled',      # memory section: per-module three-way rows
    'memory_sample',        # memory section: live sampler ticks
)
IGNORED_KINDS = {
    'run_meta': 'per-run header (argv/rank/backend): provenance '
                'metadata, not a report row',
    'scalar': 'user scalar stream — consumed by the TensorBoard/'
              'VisualDL exporters, not the merged report',
    'lockcheck': 'runtime lock-checker disarm summary (cycles/'
                 'violations/hold stats): a debug diagnostic read '
                 'directly from its own report(), not a run row',
}


def _median(vals):
    """Proper even-count median (two-rank clusters must not anchor
    the skew baseline on the slower rank)."""
    if not vals:
        return None
    vs = sorted(vals)
    n = len(vs)
    return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1] + vs[n // 2])


def _percentiles(times_ms):
    if not times_ms:
        return {}
    ts = sorted(times_ms)
    n = len(ts)

    def pct(q):
        return round(ts[min(n - 1, int(n * q))], 4)

    return {'steps': n,
            'mean_ms': round(sum(ts) / n, 4),
            'p50_ms': pct(0.50), 'p90_ms': pct(0.90),
            'p99_ms': pct(0.99), 'max_ms': round(ts[-1], 4)}


def discover(paths):
    """Expand dirs/files into (jsonl_files, flightrec_files)."""
    jsonls, flights = [], []
    for p in paths:
        if os.path.isdir(p):
            jsonls += sorted(glob.glob(
                os.path.join(p, 'telemetry-*.jsonl')))
            jsonls += sorted(glob.glob(
                os.path.join(p, '**', 'telemetry-*.jsonl'),
                recursive=True))
            flights += sorted(glob.glob(
                os.path.join(p, '**', 'flightrec-*.json'),
                recursive=True))
        elif p.endswith('.jsonl'):
            jsonls.append(p)
        elif p.endswith('.json'):
            flights.append(p)
    # de-dup while keeping order (dir glob may double-match)
    seen = set()
    jsonls = [f for f in jsonls
              if not (f in seen or seen.add(f))]
    return jsonls, flights


def load_events(jsonl_files, flight_files):
    """All events from every source, plus per-file metadata.
    Flight dumps contribute their embedded event rings (rank-tagged
    from the dump header); duplicate (ts, kind, rank) records — an
    event both streamed and ring-dumped — collapse to one."""
    events, sources = [], []
    for f in jsonl_files:
        n = 0
        with open(f) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue        # torn final line of a dead worker
                if isinstance(rec, dict) and 'kind' in rec:
                    rec.setdefault('rank', 0)
                    events.append(rec)
                    n += 1
        sources.append({'file': f, 'records': n, 'type': 'jsonl'})
    for f in flight_files:
        try:
            with open(f) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        rank = doc.get('rank', 0)
        n = 0
        for rec in doc.get('events', []):
            if isinstance(rec, dict) and 'kind' in rec:
                rec = dict(rec)
                rec.setdefault('rank', rank)
                events.append(rec)
                n += 1
        sources.append({'file': f, 'records': n, 'type': 'flightrec',
                        'counters': doc.get('counters', {})})
    seen = set()
    out = []
    for e in events:
        # monotonic 't' joins the key so two DISTINCT same-kind events
        # in the same rounded microsecond survive; a record that was
        # both streamed and ring-dumped shares all four fields
        k = (e.get('ts'), e.get('t'), e.get('kind'), e.get('rank'))
        if k in seen:
            continue
        seen.add(k)
        out.append(e)
    skew = normalize_clock_skew(out)
    out.sort(key=lambda e: e.get('ts') or 0)
    return out, sources, skew


def normalize_clock_skew(events):
    """Anchor each host's wall clock to its first ``steps`` event.

    ts is per-host wall-clock; hosts drift by seconds on real pods,
    which used to mis-order the merged resilience timeline (a rank-1
    preemption could sort before the rank-0 steps that preceded it).
    SPMD training steps in lockstep, so the first flushed ``steps``
    event is the one instant every host's stream shares: shift each
    rank by (its anchor - earliest anchor).  Mutates ts in place and
    returns {rank: applied_offset_s}; skipped (returns {}) unless at
    least two ranks exist and EVERY rank emitted steps events — a
    host that never stepped has no trustworthy anchor."""
    anchors = {}
    ranks = set()
    for e in events:
        r = e.get('rank', 0)
        ranks.add(r)
        ts = e.get('ts')
        if e.get('kind') == 'steps' and ts is not None:
            if r not in anchors or ts < anchors[r]:
                anchors[r] = ts
    if len(ranks) < 2 or set(anchors) != ranks:
        return {}
    base = min(anchors.values())
    offsets = {r: round(a - base, 6) for r, a in anchors.items()}
    if not any(offsets.values()):
        return {}
    for e in events:
        off = offsets.get(e.get('rank', 0))
        if off and e.get('ts') is not None:
            e['ts'] = round(e['ts'] - off, 6)
    return offsets


def analyze(events, sources, skew=None):
    """The merged run report as one dict (the --json schema)."""
    by_kind = {}
    for e in events:
        by_kind.setdefault(e['kind'], []).append(e)

    # -- step-time percentiles + host-wait split per loop tag ----
    step_stats, split = {}, {}
    scalars_last = {}
    total_steps = 0
    for ev in by_kind.get('steps', ()):
        tag = ev.get('tag', 'train')
        st = step_stats.setdefault(tag, {'times_ms': [], 'waits_ms': [],
                                         'n': 0})
        st['n'] += ev.get('n', 0)
        total_steps += ev.get('n', 0)
        st['times_ms'] += [t for t in ev.get('step_time_ms') or []
                           if t is not None]
        st['waits_ms'] += [w for w in ev.get('wait_ms') or []
                           if w is not None]
        for k, col in ev.items():
            if k in ('kind', 'ts', 't', 'rank', 'tag', 'n', 'step',
                     'step_lo', 'step_hi', 'step_time_ms', 'wait_ms'):
                continue
            if isinstance(col, list) and col:
                vals = [v for v in col if v is not None]
                if vals:
                    scalars_last.setdefault(tag, {})[k] = vals[-1]
    steps_out = {}
    for tag, st in step_stats.items():
        steps_out[tag] = _percentiles(st['times_ms'])
        steps_out[tag]['count'] = st['n']
        dev_ms = sum(st['times_ms'])
        wait_ms = sum(st['waits_ms'])
        if dev_ms or wait_ms:
            tot = dev_ms + wait_ms
            split[tag] = {
                'device_step_ms': round(dev_ms, 3),
                'host_wait_ms': round(wait_ms, 3),
                'host_wait_frac': round(wait_ms / tot, 6) if tot else 0.0,
            }

    # -- compile / retrace ---------------------------------------
    compile_events = by_kind.get('compile', [])
    per_name = {}
    for e in compile_events:
        row = per_name.setdefault(e.get('name', '?'),
                                  {'count': 0, 'total_s': 0.0})
        row['count'] += 1
        row['total_s'] = round(row['total_s'] + (e.get('dur_s') or 0.0),
                               6)
    compile_out = {
        'count': len(compile_events),
        'total_s': round(sum(e.get('dur_s') or 0.0
                             for e in compile_events), 6),
        'per_name': per_name,
    }
    retraces = by_kind.get('retrace', [])
    retrace_out = {'count': len(retraces)}
    if retraces:
        worst = max(retraces, key=lambda e: e.get('variants', 0))
        retrace_out['max_variants'] = worst.get('variants')
        retrace_out['worst'] = worst.get('name')

    # -- persistent compile cache: hit rate + compile time saved --
    cc_events = by_kind.get('compile_cache', [])
    compile_cache = None
    if cc_events:
        actions = {}
        per_name = {}
        for e in cc_events:
            a = e.get('action', '?')
            row = actions.setdefault(a, {'count': 0, 'bytes': 0})
            row['count'] += 1
            row['bytes'] += e.get('bytes') or 0
            nm = per_name.setdefault(e.get('name', '?'),
                                     {'hits': 0, 'misses': 0})
            # 'deserialize' refines a 'hit' (same lookup), so only
            # hit/miss count toward the rate — one event per lookup
            if a == 'hit':
                nm['hits'] += 1
            elif a == 'miss':
                nm['misses'] += 1
        hits = actions.get('hit', {}).get('count', 0)
        misses = actions.get('miss', {}).get('count', 0)
        lookups = hits + misses
        compile_cache = {
            'actions': actions,
            'hits': hits,
            'misses': misses,
            'lookups': lookups,
            'hit_rate': round(hits / lookups, 4) if lookups else None,
            'deserialized': actions.get('deserialize',
                                        {}).get('count', 0),
            'serialized': actions.get('serialize', {}).get('count', 0),
            'quarantined': actions.get('quarantine',
                                       {}).get('count', 0),
            'warm_start_entries': sum(e.get('count') or 0
                                      for e in cc_events
                                      if e.get('action') == 'warm_start'),
            'compile_time_saved_s': round(sum(
                e.get('saved_s') or 0.0 for e in cc_events
                if e.get('action') == 'deserialize'), 6),
            'per_name': per_name,
        }

    # -- collectives: observed census vs compile-time prediction --
    coll = by_kind.get('collectives', [])
    collectives = None
    if coll:
        last = coll[-1]
        collectives = {'per_op': last.get('per_op', {}),
                       'total_bytes': last.get('total_bytes', 0),
                       'mesh': last.get('mesh')}
    cost = by_kind.get('collective_cost', [])
    collectives_predicted = None
    if cost:
        last = cost[-1]
        collectives_predicted = {
            'per_op': last.get('per_op', {}),
            'wire_bytes_total': last.get('wire_bytes_total', 0),
            'est_us_total': last.get('est_us_total', 0.0),
            'quant_collectives': last.get('quant_collectives'),
            'mesh': last.get('mesh')}
    # profiled per-collective timings (telemetry.profile capture
    # windows): the observed side calibrate_costmodel.py fits
    # alpha/beta from.  Events carry per-CALL us, one event per call
    # site (instr) per window — average each call site across windows,
    # then sum call sites per op, so the per-op observed_us is a
    # per-step total comparable to the census est_us no matter how
    # many windows the run captured.
    per_instr = {}
    for e in by_kind.get('collective_observed', ()):
        op = e.get('op')
        if op is None:
            continue
        # `name` (the emitting loop) joins the key: two trainers in
        # one run may reuse an instr name across different compiled
        # modules — their per-call timings must not blend
        key = (op, e.get('name'), e.get('instr'))
        r = per_instr.setdefault(
            key, {'us': [], 'wire_bytes': 0, 'phases': 0, 'calls': 0,
                  'wire_dtype': None})
        r['us'].append(e.get('us') or 0.0)
        r['wire_bytes'] = max(r['wire_bytes'],
                              e.get('wire_bytes') or 0)
        r['phases'] = max(r['phases'], e.get('phases') or 0)
        r['calls'] += e.get('calls') or 1
        r['wire_dtype'] = e.get('wire_dtype') or r['wire_dtype']
    observed_us = {}
    for (op, _name, _instr), r in per_instr.items():
        row = observed_us.setdefault(
            op, {'us': 0.0, 'wire_bytes': 0, 'phases': 0, 'calls': 0,
                 'wire_dtype': None})
        row['us'] = round(row['us'] + sum(r['us']) / len(r['us']), 3)
        row['wire_bytes'] += r['wire_bytes']
        row['phases'] += r['phases']
        row['calls'] += r['calls']
        row['wire_dtype'] = r['wire_dtype'] or row['wire_dtype']
    collectives_cmp = None
    if collectives or collectives_predicted or observed_us:
        ops = set((collectives or {}).get('per_op', {})) | set(
            (collectives_predicted or {}).get('per_op', {})) | set(
            observed_us)
        collectives_cmp = {}
        for op in sorted(ops):
            obs = (collectives or {}).get('per_op', {}).get(op, {})
            pred = (collectives_predicted or {}).get(
                'per_op', {}).get(op, {})
            prof = observed_us.get(op, {})
            row = {
                'observed_calls': obs.get('calls'),
                'observed_bytes': obs.get('bytes'),
                'observed_us': prof.get('us'),
                'observed_wire_bytes': prof.get('wire_bytes') or None,
                'observed_phases': prof.get('phases') or None,
                'predicted_wire_bytes': pred.get('wire_bytes'),
                'predicted_est_us': pred.get('est_us'),
                'predicted_phases': pred.get('phases'),
                # the wire-dtype dimension: the compiled module's
                # payload element type (s8 under quantized
                # collectives) — prediction first, profiler join as
                # fallback, so the 2-4x byte claim is auditable per op
                'wire_dtype': (pred.get('wire_dtype')
                               or obs.get('wire_dtype')
                               or prof.get('wire_dtype')),
            }
            # the closed loop: profiled us over the cost-model
            # estimate, per op — what calibration is meant to pull
            # toward 1.0.  Both sides are per-step totals: the census
            # sums est_us over an op's call sites, and the profiler
            # emits each call site's per-execution us once.
            o_us, p_us = row['observed_us'], row['predicted_est_us']
            if o_us and p_us:
                row['us_ratio'] = round(o_us / p_us, 4)
            collectives_cmp[op] = row

    # -- auto-sharding plan: predicted-vs-actual for the chosen plan --
    plan = None
    plan_events = by_kind.get('plan_selected', [])
    if plan_events:
        last = plan_events[-1]
        plan = {
            'name': last.get('name'),
            'chips': last.get('chips'),
            'winner': last.get('winner'),
            'candidates_scored': last.get('candidates_scored'),
            'hbm_budget_bytes': last.get('hbm_budget_bytes'),
            'predicted_wire_bytes': last.get('wire_bytes'),
            'predicted_est_us': last.get('est_us'),
            'predicted_compute_us': last.get('compute_us'),
            'predicted_peak_bytes': last.get('peak_bytes'),
        }
        obs_bytes = (collectives or {}).get('total_bytes')
        plan['observed_bytes'] = obs_bytes
        obs_us = round(sum(r['us'] for r in observed_us.values()), 3) \
            if observed_us else None
        plan['observed_us'] = obs_us
        pred_us = plan.get('predicted_est_us')
        if obs_us and pred_us:
            plan['us_ratio'] = round(obs_us / pred_us, 4)

    # -- profile capture windows (telemetry.profile) --------------
    profile = None
    cap_events = by_kind.get('profile_capture', [])
    if cap_events:
        ok = [e for e in cap_events if not e.get('error')]
        last = (ok or cap_events)[-1]
        profile = {
            'windows': len(cap_events),
            'errors': len(cap_events) - len(ok),
            'collective_observed': sum(
                e.get('collective_observed') or 0 for e in cap_events),
            'last': {k: last.get(k) for k in (
                'name', 'step_lo', 'step_hi', 'device_us_per_step',
                'collective_us_per_step', 'collective_frac', 'trace',
                'error') if last.get(k) is not None},
        }

    # -- serving: the serve_step / serve_request join --------------
    # (emitted since PR 12, silently dropped until now)
    serving = None
    serve_steps = by_kind.get('serve_step', [])
    serve_reqs = by_kind.get('serve_request', [])
    serve_rejects = by_kind.get('serve_reject', [])
    fleet_events = by_kind.get('fleet_event', [])
    if serve_steps or serve_reqs or serve_rejects or fleet_events:
        ttft_ms = [r['ttft_s'] * 1000.0 for r in serve_reqs
                   if r.get('ttft_s') is not None]
        tpot_ms = [r['tpot_s'] * 1000.0 for r in serve_reqs
                   if r.get('tpot_s') is not None]
        # span tokens + carried prefill first tokens - preemption
        # rollbacks = the engine's delivered-token accounting
        decoded = sum((e.get('decoded') or 0)
                      + (e.get('prefilled') or 0)
                      - (e.get('discarded') or 0)
                      for e in serve_steps)
        ts = [e['ts'] for e in serve_steps if e.get('ts') is not None]
        wall = (max(ts) - min(ts)) if len(ts) > 1 else None
        by_cause = {}
        completed = evicted = 0
        for r in serve_reqs:
            cause = r.get('reason') or '?'
            by_cause[cause] = by_cause.get(cause, 0) + 1
            if r.get('state') == 'done':
                completed += 1
            else:
                evicted += 1
        requests_rows = [
            {k: r.get(k) for k in (
                'rid', 'state', 'reason', 'prompt_len', 'tokens',
                'ttft_s', 'tpot_s', 'preemptions', 'age_s', 'rank')
             if r.get(k) is not None}
            for r in serve_reqs]
        traces = {e['rid']: e.get('trace') or []
                  for e in by_kind.get('serve_trace', ())
                  if e.get('rid') is not None}
        serving = {
            'requests': len(serve_reqs),
            'completed': completed,
            'evicted': evicted,
            'by_cause': by_cause,
            'preemptions': sum(r.get('preemptions') or 0
                               for r in serve_reqs),
            'ttft_ms': _percentiles(ttft_ms),
            'tpot_ms': _percentiles(tpot_ms),
            'interventions': len(serve_steps),
            'decoded_tokens': decoded,
            'tokens_per_s': (round(decoded / wall, 3)
                             if wall else None),
            'last_step': {k: serve_steps[-1].get(k) for k in (
                'live', 'batch', 'span', 'queued', 'free_blocks',
                'total_blocks')} if serve_steps else None,
            'slo_breaches': [
                {k: e.get(k) for k in (
                    'what', 'observed_s', 'budget_s', 'observed_frac',
                    'threshold_frac', 'rank') if e.get(k) is not None}
                for e in by_kind.get('slo_breach', ())],
            'drift_detected': [
                {k: e.get(k) for k in (
                    'cause', 'op', 'instr', 'us_ratio', 'band',
                    'name', 'rank') if e.get(k) is not None}
                for e in by_kind.get('drift_detected', ())],
            'request_timeline': requests_rows,
            'traces': traces,
        }
        # admission shed trail (serve_reject): typed refusals are a
        # load signal, not an error — a front door that never sheds
        # under overload is one that OOMed instead
        if serve_rejects:
            shed_by_reason = {}
            for e in serve_rejects:
                reason = e.get('reason') or '?'
                shed_by_reason[reason] = \
                    shed_by_reason.get(reason, 0) + 1
            serving['rejected'] = len(serve_rejects)
            serving['shed_by_reason'] = shed_by_reason
        # router control plane (fleet_event): dispatch retries,
        # drains, warm-spare promotions, replica deaths — the fleet's
        # failure-handling story lines up against the request rows
        if fleet_events:
            by_action = {}
            for e in fleet_events:
                action = e.get('action') or '?'
                by_action[action] = by_action.get(action, 0) + 1
            serving['fleet'] = {
                'events': len(fleet_events),
                'by_action': by_action,
                'timeline': [
                    {k: e.get(k) for k in (
                        'action', 'replica', 'rid', 'cause',
                        'offset', 'rank') if e.get(k) is not None}
                    for e in fleet_events],
            }

    # -- memory: predicted vs compiled vs live ---------------------
    # One row per compiled module (newest memory_compiled wins — a
    # retrace replaces its module's row, same as the live registry),
    # joined with the sampler's live stream.  The predicted/compiled
    # ratio is the memory analogue of collectives_cmp's us_ratio: the
    # number calibration is meant to pull toward 1.0 so the planner's
    # HBM gate stops lying.
    memory = None
    mem_compiled = by_kind.get('memory_compiled', [])
    mem_samples = by_kind.get('memory_sample', [])
    if mem_compiled or mem_samples:
        modules = {}
        for e in mem_compiled:
            modules[e.get('name', '?')] = {
                k: e.get(k) for k in (
                    'source', 'predicted_peak_bytes',
                    'compiled_peak_bytes', 'argument_bytes',
                    'output_bytes', 'temp_bytes', 'alias_bytes',
                    'code_bytes', 'ratio')
                if e.get(k) is not None}
        ratios = [row['ratio'] for row in modules.values()
                  if row.get('ratio') is not None]
        live = None
        if mem_samples:
            last = mem_samples[-1]
            live = {k: last.get(k) for k in (
                'source', 'device_bytes', 'device_peak_bytes',
                'device_limit_bytes', 'host_rss', 'budget_bytes')
                if last.get(k) is not None}
            live['samples'] = len(mem_samples)
            peaks = [s.get('device_bytes') for s in mem_samples
                     if s.get('device_bytes') is not None]
            if peaks:
                live['max_device_bytes'] = max(peaks)
        memory = {
            'modules': modules,
            'live': live,
            'ratio_mean': (round(sum(ratios) / len(ratios), 4)
                           if ratios else None),
            'pressure_events': len(by_kind.get('memory_pressure', ())),
        }

    # -- lint findings -------------------------------------------
    lint = {}
    for e in by_kind.get('lint_finding', ()):
        lint[e.get('severity', '?')] = \
            lint.get(e.get('severity', '?'), 0) + 1

    # -- resilience timeline -------------------------------------
    timeline = []
    t0 = events[0]['ts'] if events else 0
    for e in events:
        is_res_span = (e['kind'] == 'span'
                       and e.get('name') in RESILIENCE_SPAN_NAMES)
        if e['kind'] not in RESILIENCE_KINDS and not is_res_span:
            continue
        kind = f"span:{e['name']}" if is_res_span else e['kind']
        row = {'t_rel_s': round((e.get('ts') or t0) - t0, 3),
               'kind': kind, 'rank': e.get('rank', 0)}
        for k in ('step', 'signum', 'strikes', 'rollbacks', 'path',
                  'moved_to', 'dur_s', 'dispatch_s', 'error',
                  'fault', 'seed', 'host', 'hosts', 'attempt',
                  'delay_s', 'mesh', 'saved_mesh',
                  'op', 'tag', 'budget_s', 'elapsed_s', 'missing',
                  'peer', 'heartbeat_age_s', 'live', 'stale',
                  'reason', 'deadline_s', 'clamped_from_s',
                  'what', 'cause', 'rid', 'observed_s', 'us_ratio',
                  'instr', 'observed_frac',
                  'skew', 'behind', 'hb_stale', 'spread', 'band',
                  'world', 'max_step', 'requested', 'fits',
                  'suspect',
                  'trigger', 'policy', 'outcome', 'stage',
                  'triggers', 'kinds', 'from_mesh', 'to_mesh',
                  'assignment', 'candidate_s', 'incumbent_s',
                  'margin', 'seq', 'ranks', 'site', 'sites',
                  'observed_bytes', 'peak_bytes', 'budget_bytes',
                  'watermark', 'frac', 'source', 'hbm_budget_gb'):
            if e.get(k) is not None:
                row[k] = e[k]
        timeline.append(row)

    # -- watchdog / collective supervision summary ----------------
    watchdog = None
    wd_kinds = ('timeout', 'straggler', 'quorum_lost',
                'coordinated_abort')
    if any(by_kind.get(k) for k in wd_kinds):
        watchdog = {}
        for k in wd_kinds:
            rows = by_kind.get(k, [])
            if not rows:
                continue
            per_rank = {}
            for e in rows:
                r = e.get('rank', 0)
                per_rank[r] = per_rank.get(r, 0) + 1
            watchdog[k] = {'count': len(rows), 'per_rank': per_rank}
        faults = by_kind.get('fault_injected', [])
        if faults:
            per_rank = {}
            for e in faults:
                r = e.get('rank', 0)
                per_rank[r] = per_rank.get(r, 0) + 1
            watchdog['fault_injected'] = {'count': len(faults),
                                          'per_rank': per_rank}

    # -- cluster: per-rank step skew + straggler attribution -------
    # Per-rank step-time stats (the tag-keyed section above blends
    # ranks — fine for one host, blind for a cluster).  With >= 2
    # stepping ranks, compute each rank's skew vs the cluster median
    # p50 and join the live plane's straggler_suspect /
    # rank_divergence edges.
    rank_steps = {}
    for ev in by_kind.get('steps', ()):
        r = ev.get('rank', 0)
        st = rank_steps.setdefault(
            r, {'times_ms': [], 'n': 0, 'last_step': None,
                'tags': set()})
        st['n'] += ev.get('n', 0)
        st['tags'].add(ev.get('tag', 'train'))
        st['times_ms'] += [t for t in ev.get('step_time_ms') or []
                           if t is not None]
        hi = ev.get('step_hi')
        if hi is not None:
            st['last_step'] = (hi if st['last_step'] is None
                               else max(st['last_step'], hi))
    cluster = None
    if len(rank_steps) >= 2:
        per_rank = {}
        p50s = []
        for r, st in sorted(rank_steps.items()):
            pct = _percentiles(st['times_ms'])
            row = {'steps': st['n'],
                   'last_step': st['last_step'],
                   'tags': sorted(st['tags'])}
            row.update({k: pct.get(k) for k in
                        ('mean_ms', 'p50_ms', 'p99_ms') if pct})
            per_rank[r] = row
            if pct.get('p50_ms'):
                p50s.append(pct['p50_ms'])
        med = _median(p50s)
        max_step = max((st['last_step'] for st in rank_steps.values()
                        if st['last_step'] is not None), default=None)
        worst = None
        for r, row in per_rank.items():
            if med and row.get('p50_ms'):
                row['skew'] = round(row['p50_ms'] / med, 4)
                if worst is None or row['skew'] > \
                        per_rank[worst]['skew']:
                    worst = r
            if max_step is not None and row.get('last_step') is not None:
                row['behind'] = max_step - row['last_step']
        cluster = {
            'ranks': {str(r): row for r, row in per_rank.items()},
            'max_step': max_step,
            'median_p50_ms': med,
            'straggler': ({'rank': worst,
                           'skew': per_rank[worst]['skew']}
                          if worst is not None
                          and per_rank[worst].get('skew', 0) >= 1.5
                          else None),
            'suspects': [
                {k: e.get(k) for k in (
                    'suspect', 'cause', 'skew', 'behind', 'hb_stale',
                    'max_step') if e.get(k) is not None}
                for e in by_kind.get('straggler_suspect', ())],
            'divergence': [
                {k: e.get(k) for k in (
                    'spread', 'band', 'per_rank', 'max_step')
                 if e.get(k) is not None}
                for e in by_kind.get('rank_divergence', ())],
        }

    ranks = sorted({e.get('rank', 0) for e in events})
    spans = {}
    for e in by_kind.get('span', ()):
        row = spans.setdefault(e.get('name', '?'),
                               {'count': 0, 'total_s': 0.0})
        row['count'] += 1
        row['total_s'] = round(row['total_s'] + (e.get('dur_s') or 0.0),
                               6)
    return {
        'schema_version': SCHEMA_VERSION,
        'hosts': ranks,
        'n_events': len(events),
        'sources': sources,
        'steps': steps_out,
        'total_steps': total_steps,
        'split': split,
        'compile': compile_out,
        'compile_cache': compile_cache,
        'retraces': retrace_out,
        'collectives': collectives,
        'collectives_predicted': collectives_predicted,
        'collectives_cmp': collectives_cmp,
        'plan': plan,
        'profile': profile,
        'serving': serving,
        'memory': memory,
        'clock_skew': skew or {},
        'cluster': cluster,
        'watchdog': watchdog,
        'lint_findings': lint,
        'spans': spans,
        'scalars_last': scalars_last,
        'timeline': timeline,
    }


def render(report, stream=None):
    out = stream or sys.stdout
    p = lambda *a: print(*a, file=out)      # noqa: E731
    p('================ paddle_tpu run report ================')
    p(f"hosts: {report['hosts']}   events: {report['n_events']}   "
      f"sources: {len(report['sources'])}")
    if report['steps']:
        p('\n-- step times --')
        for tag, st in report['steps'].items():
            if not st.get('steps'):
                p(f'  [{tag}] {st.get("count", 0)} steps (no timings)')
                continue
            p(f'  [{tag}] n={st["count"]}  mean={st["mean_ms"]:.2f}ms  '
              f'p50={st["p50_ms"]:.2f}  p90={st["p90_ms"]:.2f}  '
              f'p99={st["p99_ms"]:.2f}  max={st["max_ms"]:.2f}')
            sp = report['split'].get(tag)
            if sp:
                p(f'        device-step {sp["device_step_ms"]:.1f}ms '
                  f'vs host-wait {sp["host_wait_ms"]:.1f}ms '
                  f'({sp["host_wait_frac"]:.1%} waiting)')
    c = report['compile']
    p(f'\n-- compile --\n  {c["count"]} compiles, '
      f'{c["total_s"]:.2f}s total')
    for name, row in sorted(c['per_name'].items()):
        p(f'    {name}: {row["count"]}x {row["total_s"]:.2f}s')
    r = report['retraces']
    p(f'  retraces: {r["count"]}'
      + (f' (worst: {r.get("worst")} at {r.get("max_variants")} '
         'variants)' if r['count'] else ''))
    cc = report.get('compile_cache')
    if cc:
        rate = (f'{cc["hit_rate"]:.0%}' if cc.get('hit_rate') is not None
                else 'n/a')
        p(f'  cache: {cc["hits"]}/{cc["lookups"]} lookups hit ({rate}), '
          f'{cc["deserialized"]} deserialized, '
          f'{cc["serialized"]} serialized'
          + (f', {cc["quarantined"]} quarantined'
             if cc['quarantined'] else '')
          + (f', {cc["warm_start_entries"]} warm-start entries'
             if cc['warm_start_entries'] else ''))
        if cc.get('compile_time_saved_s'):
            p(f'  cache saved ~{cc["compile_time_saved_s"]:.2f}s of '
              'trace+lower')
        for name, row in sorted(cc['per_name'].items()):
            if name != '?':
                p(f'    {name}: {row["hits"]} hit / '
                  f'{row["misses"]} miss')
    if report['collectives'] or report.get('collectives_predicted'):
        co = report['collectives'] or report['collectives_predicted']
        p(f'\n-- collectives (mesh {co.get("mesh")}) --')
        cmp_rows = report.get('collectives_cmp') or {}
        p(f'    {"op":<20}{"observed":>22}{"predicted (cost model)":>28}')
        for op, row in sorted(cmp_rows.items()):
            if row.get('wire_dtype') and row['wire_dtype'] != 'f32':
                op = f'{op}[{row["wire_dtype"]}]'
            obs_parts = []
            if row['observed_calls'] is not None:
                obs_parts.append(f'{row["observed_calls"]}x '
                                 f'{row["observed_bytes"]:,} B')
            if row.get('observed_us') is not None:
                obs_parts.append(f'{row["observed_us"]:.0f} us')
                if row.get('us_ratio'):
                    obs_parts.append(f'(x{row["us_ratio"]:.2f})')
            obs = ' '.join(obs_parts) or '-'
            pred = '-'
            if row['predicted_wire_bytes'] is not None:
                pred = (f'{row["predicted_wire_bytes"]:,} B wire '
                        f'~{row["predicted_est_us"]:.0f} us')
            p(f'    {op:<20}{obs:>22}{pred:>28}')
        if report['collectives']:
            p(f'    observed total: '
              f'{report["collectives"]["total_bytes"]:,} bytes/step')
        if report.get('collectives_predicted'):
            cp = report['collectives_predicted']
            p(f'    predicted total: {cp["wire_bytes_total"]:,} wire '
              f'bytes/step, ~{cp["est_us_total"]:.0f} us on the wire')
    if report.get('plan'):
        pl = report['plan']
        w = pl.get('winner') or {}
        p('\n-- auto-sharding plan --')
        p(f'    {pl.get("name")}: winner {w.get("mesh")} '
          f'[{w.get("assignment")}]'
          + (f' +{w["fallback"]}' if w.get('fallback') else '')
          + f' of {pl.get("candidates_scored")} candidates')
        if pl.get('predicted_wire_bytes') is not None:
            p(f'    predicted: {pl["predicted_wire_bytes"]:,} wire '
              f'bytes/step, ~{pl.get("predicted_est_us", 0):.0f} us '
              'collectives, peak '
              f'{(pl.get("predicted_peak_bytes") or 0) / (1 << 30):.2f}'
              ' GiB')
        if pl.get('observed_bytes') is not None:
            obs_line = (f'    observed:  {pl["observed_bytes"]:,} '
                        'collective bytes/step')
            if pl.get('observed_us'):
                obs_line += f', {pl["observed_us"]:.0f} us'
                if pl.get('us_ratio'):
                    obs_line += f' (x{pl["us_ratio"]:.2f} of predicted)'
            p(obs_line)
    if report.get('profile'):
        pr = report['profile']
        last = pr.get('last') or {}
        p('\n-- profile captures --')
        p(f'    {pr["windows"]} window(s), '
          f'{pr["collective_observed"]} collective_observed event(s)'
          + (f', {pr["errors"]} failed' if pr.get('errors') else ''))
        if last.get('device_us_per_step') is not None:
            frac = last.get('collective_frac') or 0.0
            p(f'    last window [{last.get("name")}] steps '
              f'{last.get("step_lo")}-{last.get("step_hi")}: '
              f'{last["device_us_per_step"]:.0f} us/step device, '
              f'{last.get("collective_us_per_step", 0):.0f} us '
              f'({frac:.1%}) in collectives')
    if report.get('serving'):
        sv = report['serving']
        p('\n-- serving --')
        p(f'    {sv["requests"]} requests: {sv["completed"]} '
          f'completed, {sv["evicted"]} evicted '
          f'({", ".join(f"{c}:{n}" for c, n in sorted(sv["by_cause"].items()))})'
          + (f', {sv["preemptions"]} preemption(s)'
             if sv['preemptions'] else ''))
        tk = sv.get('tokens_per_s')
        p(f'    {sv["decoded_tokens"]} tokens over '
          f'{sv["interventions"]} interventions'
          + (f' ({tk:.0f} tokens/s)' if tk else ''))
        for label, pct in (('TTFT', sv['ttft_ms']),
                           ('TPOT', sv['tpot_ms'])):
            if pct:
                p(f'    {label}: p50={pct["p50_ms"]:.1f}ms '
                  f'p99={pct["p99_ms"]:.1f}ms '
                  f'max={pct["max_ms"]:.1f}ms (n={pct["steps"]})')
        last = sv.get('last_step')
        if last:
            p(f'    last intervention: {last.get("live")} live / '
              f'batch {last.get("batch")} / {last.get("queued")} '
              f'queued / {last.get("free_blocks")} of '
              f'{last.get("total_blocks")} blocks free')
        if sv.get('rejected'):
            sheds = ', '.join(
                f'{r}:{n}' for r, n in
                sorted(sv['shed_by_reason'].items()))
            p(f'    {sv["rejected"]} shed at admission ({sheds})')
        fleet = sv.get('fleet')
        if fleet:
            acts = ', '.join(f'{a}:{n}' for a, n in
                             sorted(fleet['by_action'].items()))
            p(f'    fleet: {fleet["events"]} control event(s) '
              f'({acts})')
            for e in fleet['timeline'][:8]:
                p(f'      {e.get("action")}: '
                  + ' '.join(f'{k}={e[k]}' for k in
                             ('replica', 'rid', 'cause', 'offset')
                             if e.get(k) is not None))
        for b in sv['slo_breaches']:
            p(f'    SLO BREACH: {b}')
        for d in sv['drift_detected']:
            p(f'    DRIFT: {d}')
        rows = sv['request_timeline']
        for r in rows[:8]:
            ttft = r.get('ttft_s')
            p(f'      {r.get("rid")}: {r.get("state")}'
              f'/{r.get("reason")} prompt={r.get("prompt_len")} '
              f'tokens={r.get("tokens")}'
              + (f' ttft={ttft * 1000:.0f}ms'
                 if ttft is not None else '')
              + (f' preempted x{r["preemptions"]}'
                 if r.get('preemptions') else ''))
        if len(rows) > 8:
            p(f'      ... {len(rows) - 8} more request(s) '
              '(--json has all)')
    if report.get('memory'):
        mem = report['memory']
        p('\n-- memory (predicted vs compiled vs live) --')
        mods = mem.get('modules') or {}
        if mods:
            p(f'    {"module":<26}{"predicted":>14}{"compiled":>14}'
              f'{"ratio":>8}')
            for name, row in sorted(mods.items()):
                pred = row.get('predicted_peak_bytes')
                comp = row.get('compiled_peak_bytes')
                ratio = row.get('ratio')
                p(f'    {name:<26}'
                  f'{(f"{pred:,} B" if pred is not None else "-"):>14}'
                  f'{(f"{comp:,} B" if comp is not None else "-"):>14}'
                  f'{(f"x{ratio:.2f}" if ratio is not None else "-"):>8}')
        if mem.get('ratio_mean') is not None:
            p(f'    mean predicted/compiled ratio: '
              f'x{mem["ratio_mean"]:.2f} (calibration pulls this '
              'toward 1.0)')
        live = mem.get('live')
        if live:
            bits = [f'{live["samples"]} sample(s) '
                    f'[{live.get("source", "?")}]']
            if live.get('device_bytes') is not None:
                bits.append(f'{live["device_bytes"]:,} B live')
            if live.get('max_device_bytes') is not None:
                bits.append(f'{live["max_device_bytes"]:,} B high-water')
            if live.get('host_rss') is not None:
                bits.append(f'rss {live["host_rss"]:,} B')
            if live.get('budget_bytes') is not None:
                bits.append(f'budget {live["budget_bytes"]:,} B')
            p(f'    live: {"  ".join(bits)}')
        if mem.get('pressure_events'):
            p(f'    MEMORY PRESSURE: {mem["pressure_events"]} '
              'event(s) (see resilience timeline)')
    if report.get('cluster'):
        cl = report['cluster']
        p('\n-- cluster (per-rank step skew) --')
        for r, row in sorted(cl['ranks'].items()):
            bits = [f'n={row.get("steps")}']
            if row.get('p50_ms') is not None:
                bits.append(f'p50={row["p50_ms"]:.2f}ms')
            if row.get('skew') is not None:
                bits.append(f'skew=x{row["skew"]:.2f}')
            if row.get('last_step') is not None:
                bits.append(f'step={row["last_step"]}')
            if row.get('behind'):
                bits.append(f'behind={row["behind"]}')
            p(f'    rank {r}: {"  ".join(bits)}')
        if cl.get('straggler'):
            s = cl['straggler']
            p(f'    straggler: rank {s["rank"]} at x{s["skew"]:.2f} '
              'the cluster median')
        for s in cl.get('suspects', ()):
            p(f'    SUSPECT (live): {s}')
        for d in cl.get('divergence', ()):
            p(f'    DIVERGENCE (live): {d}')
    if report.get('clock_skew'):
        p('\n-- clock skew (per-host anchor offsets applied) --')
        for r, off in sorted(report['clock_skew'].items()):
            p(f'    rank {r}: {off:+.3f}s')
    if report.get('watchdog'):
        p('\n-- watchdog / collective supervision --')
        for kind, row in sorted(report['watchdog'].items()):
            ranks = ', '.join(f'r{r}:{n}' for r, n in
                              sorted(row['per_rank'].items()))
            p(f'    {kind}: {row["count"]} ({ranks})')
    if report['lint_findings']:
        p(f'\n-- lint findings --\n    {report["lint_findings"]}')
    if report['scalars_last']:
        p('\n-- last scalars --')
        for tag, vals in report['scalars_last'].items():
            pretty = ', '.join(f'{k}={v:.5g}'
                               for k, v in sorted(vals.items()))
            p(f'    [{tag}] {pretty}')
    if report['timeline']:
        p('\n-- resilience timeline --')
        for row in report['timeline']:
            extra = {k: v for k, v in row.items()
                     if k not in ('t_rel_s', 'kind', 'rank')}
            p(f'  +{row["t_rel_s"]:9.3f}s r{row["rank"]} '
              f'{row["kind"]}' + (f'  {extra}' if extra else ''))
    else:
        p('\n-- resilience timeline --\n  (clean run: no events)')
    p('=======================================================')


def report_once(paths, as_json=False, stream=None):
    """One discover -> merge -> analyze -> render pass.  Returns the
    report dict, or None when nothing was found."""
    jsonls, flights = discover(paths)
    if not jsonls and not flights:
        return None
    events, sources, skew = load_events(jsonls, flights)
    report = analyze(events, sources, skew)
    out = stream or sys.stdout
    if as_json:
        print(json.dumps(report, indent=1, sort_keys=True), file=out)
    else:
        render(report, stream=out)
    return report


def follow(paths, interval_s=5.0, as_json=False, max_refreshes=None,
           stream=None, clear=None):
    """Live-tail mode: re-render the report from a RUNNING job's
    JSONL/flight-ring every `interval_s` seconds instead of waiting
    for job exit.  Safe against concurrent writers: the JSONL loader
    already skips a torn final line, and flight dumps are written
    atomically.  Stops on Ctrl-C (or after `max_refreshes` passes —
    tests/CI).  Returns the number of render passes."""
    out = stream or sys.stdout
    if clear is None:
        clear = out.isatty() and not as_json
    # status chatter goes to stdout only for the human renderer —
    # under --json stdout must stay a clean stream of report
    # documents (one per refresh), so stamps/waits route to stderr
    chat = sys.stderr if as_json else out
    n = 0
    try:
        while True:
            if clear:
                print('\x1b[2J\x1b[H', end='', file=out)
            report = report_once(paths, as_json=as_json, stream=out)
            if report is None:
                print(f'run_report --follow: waiting for telemetry '
                      f'under {paths} ...', file=chat)
            else:
                import datetime
                stamp = datetime.datetime.now().strftime('%H:%M:%S')
                print(f'[--follow {stamp}: {report["n_events"]} '
                      f'events, refresh every {interval_s:g}s, '
                      'Ctrl-C to stop]', file=chat)
            for s in {out, chat}:
                try:
                    s.flush()
                except (OSError, ValueError):
                    pass
            n += 1
            if max_refreshes is not None and n >= max_refreshes:
                return n
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return n


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='run_report',
        description='Merge per-host telemetry JSONL (+ flight-recorder '
                    'dumps) into one run report.')
    ap.add_argument('paths', nargs='+',
                    help='telemetry dirs, telemetry-*.jsonl files, '
                         'and/or flightrec-*.json dumps')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable report for bench/CI')
    ap.add_argument('--follow', action='store_true',
                    help='live-tail a RUNNING job: re-render every '
                         '--interval seconds instead of requiring '
                         'job exit (Ctrl-C to stop)')
    ap.add_argument('--interval', type=float, default=5.0,
                    help='refresh period for --follow (seconds, '
                         'default 5)')
    ap.add_argument('--refreshes', type=int, default=None,
                    help='with --follow: stop after N renders '
                         '(default: until Ctrl-C)')
    args = ap.parse_args(argv)

    if args.follow:
        follow(args.paths, interval_s=args.interval,
               as_json=args.json, max_refreshes=args.refreshes)
        return 0
    report = report_once(args.paths, as_json=args.json)
    if report is None:
        print('run_report: no telemetry-*.jsonl or flightrec-*.json '
              f'under {args.paths}', file=sys.stderr)
        return 2
    return 0


if __name__ == '__main__':
    sys.exit(main())
