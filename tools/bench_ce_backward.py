#!/usr/bin/env python
"""Micro-benchmark: WHY is the unfused CE head slow on TPU?

Hypothesis (round-4 chip session 2): the backward of the hard-label
gather (`take_along_axis`) is a scatter-add into the [B*T, V] logits
buffer, which XLA lowers to a serialized scatter on TPU.  The classic
fix is the fused softmax-CE backward: d logits = softmax - one_hot,
dense elementwise math, no scatter.

Times three formulations of mean-NLL at GPT-2 bench shape
([8192, 50304] bf16 logits) on the live device:

  gather   : -take_along_axis(log_softmax(x))         (autodiff scatter)
  onehot   : -sum(one_hot * log_softmax(x))           (dense fwd+bwd)
  customvjp: paddle_tpu F.cross_entropy               (whatever it does now)

Usage: python tools/bench_ce_backward.py [--n 8192] [--v 50304]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def timeit(fn, *args, iters=10):
    import jax

    def barrier(o):
        # single-ELEMENT readback: a full np.asarray would ship the
        # [N, V] gradient over the tunnel inside the timed region,
        # swamping the fast arms' few-ms steps
        return float(np.asarray(o.reshape(-1)[0]))

    out = fn(*args)
    jax.block_until_ready(out)
    barrier(out)                                # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    barrier(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--n', type=int, default=8192)
    ap.add_argument('--v', type=int, default=50304)
    ap.add_argument('--iters', type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    print(f'device: {jax.devices()[0]}', file=sys.stderr)

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(args.n, args.v), jnp.bfloat16)
    lab = jnp.asarray(rs.randint(0, args.v, size=(args.n,)), jnp.int32)

    def nll_gather(x, lab):
        logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, lab[:, None], axis=-1).mean()

    def nll_onehot(x, lab):
        logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        oh = (lab[:, None] == jnp.arange(x.shape[-1])[None, :])
        return -jnp.sum(jnp.where(oh, logp, 0.0)) / x.shape[0]

    def nll_paddle(x, lab):
        import paddle_tpu.nn.functional as F
        from paddle_tpu import Tensor
        return F.cross_entropy(Tensor(x), Tensor(lab)).value

    rows = {}
    for name, fn in [('gather', nll_gather), ('onehot', nll_onehot),
                     ('paddle', nll_paddle)]:
        g = jax.jit(jax.grad(fn))
        ms = timeit(g, x, lab, iters=args.iters)
        rows[name] = ms
        print(f'{name:8s} grad: {ms:8.2f} ms', file=sys.stderr, flush=True)
    import json
    print(json.dumps(rows))


if __name__ == '__main__':
    main()
