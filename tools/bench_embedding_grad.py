#!/usr/bin/env python
"""Embedding-gradient strategy A/B at GPT bench shapes (round-5 CPU
census lead: the wte scatter-add is 5.5% of step bytes and the last
remaining scatter in the train step — the op class whose serialized
form cost 6.66x in the CE head, PERF.md round 4).

Strategies for dW[V,H] from ids[N] and upstream g[N,H]:
  scatter     — zeros.at[ids].add(g): the current XLA lowering of the
                embedding-lookup vjp (row-wise scatter-add).
  onehot_dot  — one_hot(ids)[N,V]^T @ g -> dot_general on the MXU;
                trades an 824 MB bf16 one-hot operand for zero scatter
                (HBM-roofline ~1 ms at v5e: may still win if scatter
                serializes).
  sort_seg    — sort ids, segment_sum over sorted rows (XLA lowers the
                segment sum to a scatter over a SORTED index vector,
                which the TPU backend can turn into windowed adds).

Prints one JSON line {strategy: ms}.  Chip verdict decides whether the
embedding vjp gets a custom dense path (like _softmax_nll did).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true')
    ap.add_argument('--vocab', type=int, default=50304)
    ap.add_argument('--hidden', type=int, default=768)
    ap.add_argument('--tokens', type=int, default=8 * 1024)
    ap.add_argument('--iters', type=int, default=30)
    args = ap.parse_args()
    if args.smoke:
        args.vocab, args.tokens, args.iters = 1024, 512, 3

    import jax
    import jax.numpy as jnp
    from jax import lax

    V, H, N = args.vocab, args.hidden, args.tokens
    print(f'device: {jax.devices()[0]}  V={V} H={H} N={N}',
          file=sys.stderr)
    rs = np.random.RandomState(0)
    ids = jax.device_put(rs.randint(0, V, size=N).astype('int32'))
    g = jax.device_put(rs.randn(N, H).astype('float32')
                       .astype('bfloat16'))

    def dw_scatter(ids, g):
        return jnp.zeros((V, H), jnp.float32).at[ids].add(
            g.astype(jnp.float32))

    def dw_onehot_dot(ids, g):
        oh = jax.nn.one_hot(ids, V, dtype=jnp.bfloat16)      # [N, V]
        return lax.dot_general(
            oh, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [V, H]

    def dw_sort_seg(ids, g):
        order = jnp.argsort(ids)
        # indices_are_sorted is the whole point of this strategy: it
        # sets the hint on the lowered scatter so the TPU backend can
        # use windowed adds instead of the generic path
        return jax.ops.segment_sum(
            g[order].astype(jnp.float32), ids[order], num_segments=V,
            indices_are_sorted=True)

    impls = {'scatter': dw_scatter, 'onehot_dot': dw_onehot_dot,
             'sort_seg': dw_sort_seg}
    ref = None
    out = {}
    for name, fn in impls.items():
        jf = jax.jit(fn)
        dw = jf(ids, g)
        jax.block_until_ready(dw)
        got = np.asarray(dw, dtype='float64')
        if ref is None:
            ref = got
        else:       # all strategies must agree (bf16-level tolerance)
            np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)
        t0 = time.time()
        for _ in range(args.iters):
            dw = jf(ids, g)
        jax.block_until_ready(dw)
        # scalar-slice barrier: a full [V,H] readback (~154 MB) would
        # swamp the 1-2 ms kernel deltas this bench discriminates
        float(np.asarray(dw[0, 0]))
        dt = (time.time() - t0) / args.iters * 1e3
        out[name] = round(dt, 3)
        print(f'{name}: {dt:.3f} ms', file=sys.stderr)
    print(json.dumps(out))


if __name__ == '__main__':
    main()
