"""Shared environment setup for the chip tools.

Import (and call) BEFORE the first `import jax` in any entry point
that compiles on the real chip: recompiles are the riskiest window
through the dev tunnel (a killed compile wedges it), so every tool
shares one persistent XLA compilation cache.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_jax_cache():
    path = os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',
                                 os.path.join(REPO, '.jax_cache'))
    # the dev box's sitecustomize imports jax at interpreter boot, so
    # the env var alone is latched too late for THIS process (it still
    # reaches subprocess children); apply to the live config as well
    if 'jax' in sys.modules:
        import jax
        try:
            jax.config.update('jax_compilation_cache_dir', path)
        except AttributeError:
            pass
