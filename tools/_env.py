"""Shared environment setup for the chip tools.

Import (and call) BEFORE the first `import jax` in any entry point
that compiles on the real chip: recompiles are the riskiest window
through the dev tunnel (a killed compile wedges it), so every tool
shares one persistent XLA compilation cache.
"""
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_jax_cache():
    os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',
                          os.path.join(REPO, '.jax_cache'))
