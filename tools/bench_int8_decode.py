#!/usr/bin/env python
"""Decode-level int8 A/B (SURVEY §2 item 72 follow-through): GPT-2
small KV-cache generation with bf16 vs int8 (quantize_dynamic_int8)
projections.  The decode step is weight-bandwidth-bound, so int8
weights (half of bf16 in HBM) should raise decoded tokens/s if the
op-level win (tools/bench_int8_matmul.py) carries into the full
module.  Kept-or-killed: int8 decode becomes a documented serving
default only if this wins on chip.

Prints one JSON line {bf16: tok/s, int8: tok/s, speedup}.
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def bench(use_int8, args):
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt_small, gpt_tiny
    from paddle_tpu.quantization import quantize_dynamic_int8

    paddle.seed(0)
    if args.smoke:
        model, batch, prompt, new = gpt_tiny(), 2, 8, 8
    else:
        model = gpt_small(max_seq_len=args.prompt + args.new,
                          dropout=0.0)
        batch, prompt, new = args.batch, args.prompt, args.new
    model.eval()
    if use_int8:
        quantize_dynamic_int8(model)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, model.config.vocab_size,
                     size=(batch, prompt)).astype('int64')
    t0 = time.time()
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                         temperature=0)
    np.asarray(out.value)
    print(f'{"int8" if use_int8 else "bf16"} warmup (incl. compile): '
          f'{time.time() - t0:.1f}s', file=sys.stderr)
    t0 = time.time()
    for i in range(args.iters):
        out = model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                             temperature=0, seed=i)
        np.asarray(out.value)     # tunnel-proof completion barrier
    dt = time.time() - t0
    return batch * new * args.iters / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true')
    ap.add_argument('--iters', type=int, default=5)
    ap.add_argument('--batch', type=int, default=8)
    ap.add_argument('--prompt', type=int, default=128)
    ap.add_argument('--new', type=int, default=128)
    args = ap.parse_args()
    if args.smoke:
        args.iters = 2

    import jax
    print(f'device: {jax.devices()[0]}', file=sys.stderr)
    rows = {}
    for use_int8 in (False, True):
        name = 'int8' if use_int8 else 'bf16'
        rows[name] = v = bench(use_int8, args)
        print(f'{name}: {v:.0f} decoded tok/s', file=sys.stderr)
    rows['speedup_int8_over_bf16'] = rows['int8'] / rows['bf16']
    print(f"speedup: {rows['speedup_int8_over_bf16']:.3f}x",
          file=sys.stderr)
    print(json.dumps(rows))


if __name__ == '__main__':
    main()
