#!/usr/bin/env python
"""profile_run — capture → parse → emit → (optionally) fit, in one
shot: the self-profiling loop's end-to-end driver.

Runs a built-in data-parallel workload on whatever accelerator is
present (a dp=8 virtual CPU mesh by default — no chip needed), with
the sampled profiler (``telemetry.profile``) capturing a trace window
mid-training.  Profiled collectives are census-matched against the
compiled module and land as real ``collective_observed`` telemetry —
**zero hand-written fixtures** — which:

* ``tools/run_report.py`` joins into populated observed_us / us_ratio
  columns (plan + collectives sections), and
* ``tools/calibrate_costmodel.py`` fits into a calibration table the
  auto-sharding planner consumes (``--fit calibration.json`` does the
  fit right here).

That closes the loop the PR-4/6 cost model opened: predict (planner)
→ measure (this driver) → re-calibrate (the fitted table) → predict
better.

    python tools/profile_run.py                        # CPU mesh, report
    python tools/profile_run.py --fit calibration.json # + fit the table
    python tools/profile_run.py --json                 # run_report schema
    python tools/profile_run.py --model lenet --dp 8 --steps 16

Exit codes: 0 = profiled collectives landed; 1 = the run produced no
``collective_observed`` events (the loop did NOT close); 2 = bad args.
"""
import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog='profile_run',
        description='Capture an on-device trace window over a built-in '
                    'dp-mesh workload, emit collective_observed '
                    'telemetry, and optionally fit a calibration '
                    'table from it.')
    ap.add_argument('--model', choices=('mlp', 'lenet'), default='mlp',
                    help='built-in workload (default mlp: fast '
                         'compile, real dp all-reduces)')
    ap.add_argument('--dp', type=int, default=8,
                    help='data-parallel mesh size (default 8; forced '
                         'virtual CPU devices when no multi-device '
                         'backend is configured; 0 = all visible '
                         'devices — the chip-session posture)')
    ap.add_argument('--batch', type=int, default=None,
                    help='global batch (default: model-specific)')
    ap.add_argument('--steps', type=int, default=10,
                    help='train steps to run (default 10)')
    ap.add_argument('--start', type=int, default=3,
                    help='first profiled step (default 3 — past '
                         'compile/warmup)')
    ap.add_argument('--window', type=int, default=2,
                    help='steps per capture window (default 2)')
    ap.add_argument('--every', type=int, default=100,
                    help='steps between window starts (default 100: '
                         'one window in a short run)')
    ap.add_argument('--out', default=None,
                    help='output dir for telemetry JSONL + trace '
                         'artifacts (default: a fresh temp dir)')
    ap.add_argument('--fit', metavar='CALIBRATION_JSON', default=None,
                    help='after the run, fit a costmodel calibration '
                         'table from the emitted events '
                         '(tools/calibrate_costmodel.py) to this path')
    ap.add_argument('--calibration', default=None,
                    help='existing calibration table to load for the '
                         'PREDICTED side (A/B a previous fit)')
    ap.add_argument('--no-plan', action='store_true',
                    help='skip the auto-sharding planner (no '
                         'plan_selected event; collectives_cmp still '
                         'populates)')
    ap.add_argument('--json', action='store_true',
                    help='print the full run_report --json document')
    return ap.parse_args(argv)


def _force_virtual_mesh(dp):
    """A dp>1 run on a single-device CPU backend gets XLA's virtual
    host devices — set BEFORE jax imports (bench/tpu_lint posture)."""
    plat = os.environ.get('JAX_PLATFORMS', '')
    if plat not in ('', 'cpu'):
        return          # a real multi-device backend is configured
    os.environ['JAX_PLATFORMS'] = 'cpu'
    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={dp}'
        ).strip()


def build_workload(model_name, batch, dp):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    rs = np.random.RandomState(0)
    if model_name == 'lenet':
        from paddle_tpu.vision.models import LeNet
        net = LeNet()
        loss = nn.CrossEntropyLoss()
        b = batch or 8 * dp
        x = rs.randn(b, 1, 28, 28).astype('float32')
        y = rs.randint(0, 10, size=(b, 1)).astype('int64')
    else:
        net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                            nn.Linear(128, 16))
        loss = nn.MSELoss()
        b = batch or 16 * dp
        x = rs.randn(b, 64).astype('float32')
        y = rs.randn(b, 16).astype('float32')
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    return net, opt, loss, x, y


def main(argv=None):
    args = parse_args(argv)
    args.steps = max(1, args.steps)
    if args.dp > 0:
        _force_virtual_mesh(args.dp)
    out = os.path.abspath(args.out or tempfile.mkdtemp(
        prefix='profile_run_'))
    os.makedirs(out, exist_ok=True)

    import jax
    from paddle_tpu import telemetry
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import env as dist_env

    n_dev = len(jax.devices())
    dp = args.dp if args.dp > 0 else n_dev
    if n_dev < dp:
        print(f'profile_run: only {n_dev} devices for --dp {dp}',
              file=sys.stderr)
        return 2
    print(f'profile_run: {args.model} on dp={dp} '
          f'({jax.devices()[0].platform}), out={out}', file=sys.stderr)

    telemetry.enable(out)
    prev_mesh = dist_env.get_mesh()
    mesh = dist_env.build_mesh({'dp': dp})
    dist_env.set_mesh(mesh)
    try:
        net, opt, loss_fn, x, y = build_workload(
            args.model, args.batch, dp)
        schedule = telemetry.ProfileSchedule(
            every=args.every, steps=args.window, start=args.start,
            dir=out)
        tr = ParallelTrainer(
            net, opt, lambda o, t: loss_fn(o, t), mesh=mesh,
            auto_shard=not args.no_plan, profile=schedule,
            calibration=args.calibration)
        for _ in range(args.steps):
            loss = tr.step(x, y)
        jax.block_until_ready(loss)
        windows = tr.finish_profile(sync=loss)
        observed = telemetry.events('collective_observed')
    finally:
        dist_env.set_mesh(prev_mesh)
        telemetry.disable()

    # -- join through run_report (the artifact consumers see) ------------
    import run_report as rr
    jsonls, flights = rr.discover([out])
    events, sources, skew = rr.load_events(jsonls, flights)
    report = rr.analyze(events, sources, skew)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        rr.render(report)

    n_ratio = sum(1 for row in (report.get('collectives_cmp')
                                or {}).values() if row.get('us_ratio'))
    print(f'profile_run: {len(windows)} window(s), '
          f'{len(observed)} collective_observed event(s), '
          f'{n_ratio} op(s) with us_ratio', file=sys.stderr)
    ok = bool(observed)
    if not ok and dp <= 1:
        # a single-device session has no collectives to observe; the
        # capture/breakdown evidence alone is the success there
        print('profile_run: single-device run — no collectives to '
              'observe (capture breakdown only)', file=sys.stderr)
        ok = True

    if args.fit and not observed:
        # visible, even when the run counts as ok (dp<=1): a consumer
        # expecting a fresh table must not mistake silence for success
        print(f'profile_run: --fit {args.fit} SKIPPED — no '
              'collective_observed samples to fit from',
              file=sys.stderr)
    if ok and args.fit and observed:
        import calibrate_costmodel as cc
        rc = cc.main([out, '-o', args.fit])
        if rc != 0:
            print(f'profile_run: calibration fit failed (rc={rc})',
                  file=sys.stderr)
            ok = False
        else:
            print(f'profile_run: calibration table written to '
                  f'{args.fit}', file=sys.stderr)
    if not ok and not observed:
        print('profile_run: NO collective_observed events were '
              'produced — the predicted-vs-observed loop did not '
              'close (check the profile_capture events for errors)',
              file=sys.stderr)
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
