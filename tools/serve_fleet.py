#!/usr/bin/env python
"""serve_fleet — multi-replica serving: N engine workers behind ONE
door (paddle_tpu/serving/router.py).

    # one replica worker (what the router spawns; also usable alone)
    python tools/serve_fleet.py worker --config serve.json \\
        --port-file /tmp/r0.port [--warmup] [--host 127.0.0.1]

    # a whole fleet: N active replicas + S warm spares + the door
    python tools/serve_fleet.py up --config serve.json \\
        --replicas 2 --spares 1 [--port 8901] [--workdir DIR]

CONFIG is the same JSON ``tools/precompile.py --serve`` reads:
ServeConfig fields plus ``"model"`` ('tiny' | 'small') and
``"model_kwargs"``.  Workers run on the CPU backend with the repo on
PYTHONPATH (the ChaosCluster env posture); each publishes
``{"port": ..., "pid": ...}`` through its --port-file once
``/healthz`` answers, which is the router's readiness handshake.

``up`` binds the door to 127.0.0.1 by default — same posture as the
single-engine frontend; set PADDLE_TPU_FRONTEND_HOST to widen.
Requests that hit the door survive replica death mid-stream: the
router replays prompt+emitted-prefix on a survivor and the
per-request position-keyed sampling discipline makes the resumed
stream bit-exact (see README "Serving front door").

Exit codes: 0 clean shutdown, 1 startup failure, 2 usage error.
"""
import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_config(path):
    with open(path) as f:
        return json.load(f)


def build_engine(doc):
    """Model + engine from a serve-config document — the exact
    builder ``precompile --serve`` uses, so a fleet worker's
    fingerprints match the AOT-warmed cache."""
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt as _gpt
    from paddle_tpu.serving import ServeConfig, ServingEngine
    builders = {'tiny': _gpt.gpt_tiny, 'small': _gpt.gpt_small}
    name = doc.get('model', 'tiny')
    if name not in builders:
        raise SystemExit(f'unknown model {name!r} '
                         f'(have {sorted(builders)})')
    paddle.seed(0)
    kw = dict(doc.get('model_kwargs') or {})
    kw.setdefault('dropout', 0.0)
    model = builders[name](**kw)
    model.eval()
    return ServingEngine(model, ServeConfig.from_json(doc))


def run_worker(args):
    from paddle_tpu.serving.frontend import ServingFrontend
    doc = _load_config(args.config)
    engine = build_engine(doc)
    if args.warmup:
        engine.warmup()
    fe = ServingFrontend(engine, port=args.port,
                         host=args.host).start()
    if args.port_file:
        tmp = args.port_file + '.tmp'
        with open(tmp, 'w') as f:
            json.dump({'port': fe.port, 'pid': os.getpid()}, f)
        os.replace(tmp, args.port_file)   # atomic: no partial reads
    print(f'[serve_fleet] worker ready on {fe.url}', flush=True)
    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    fe.stop()
    return 0


def launch_fleet(config_path, replicas=2, spares=0, workdir=None,
                 warmup_spares=True, extra_env=None):
    """Spawn the worker set and return a started
    (:class:`FleetRouter`, handles) pair — the importable form
    bench.py's --frontdoor-smoke and the chaos drill use."""
    from paddle_tpu.serving.router import FleetRouter, ReplicaHandle
    workdir = workdir or os.path.join('.', '_fleet')
    active, warm = [], []
    for i in range(replicas):
        active.append(ReplicaHandle.spawn(
            f'r{i}', config_path, workdir, extra_env=extra_env))
    for i in range(spares):
        warm.append(ReplicaHandle.spawn(
            f's{i}', config_path, workdir, warmup=warmup_spares,
            extra_env=extra_env))
    try:
        for rep in active + warm:
            rep.wait_ready()
    except Exception:
        for rep in active + warm:
            rep.kill()
        raise
    return FleetRouter(active, spares=warm)


def run_up(args):
    from paddle_tpu.serving.frontend import FRONTEND_HOST_ENV
    from paddle_tpu.serving.router import FleetFrontend
    host = os.environ.get(FRONTEND_HOST_ENV, '127.0.0.1')
    try:
        router = launch_fleet(args.config, replicas=args.replicas,
                              spares=args.spares,
                              workdir=args.workdir)
    except Exception as e:
        print(f'[serve_fleet] fleet failed to start: {e!r}',
              file=sys.stderr)
        return 1
    router.start_health_loop()
    door = FleetFrontend(router, port=args.port, host=host).start()
    print(f'[serve_fleet] door open on {door.url} '
          f'({args.replicas} replicas, {args.spares} spares)',
          flush=True)
    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    door.stop()
    router.stop()
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='serve_fleet',
        description='multi-replica serving fleet (worker + door)')
    sub = ap.add_subparsers(dest='cmd', required=True)

    w = sub.add_parser('worker', help='one engine replica')
    w.add_argument('--config', required=True,
                   help='serve-config JSON (precompile --serve form)')
    w.add_argument('--port-file',
                   help='publish {"port", "pid"} here once ready')
    w.add_argument('--port', type=int, default=0)
    w.add_argument('--host', default='127.0.0.1')
    w.add_argument('--warmup', action='store_true',
                   help='run engine.warmup() before opening the door')

    u = sub.add_parser('up', help='N replicas + spares + the door')
    u.add_argument('--config', required=True)
    u.add_argument('--replicas', type=int, default=2)
    u.add_argument('--spares', type=int, default=0)
    u.add_argument('--port', type=int, default=0)
    u.add_argument('--workdir', default=None)

    args = ap.parse_args(argv)
    if args.cmd == 'worker':
        return run_worker(args)
    return run_up(args)


if __name__ == '__main__':
    sys.exit(main())
