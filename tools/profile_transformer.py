#!/usr/bin/env python
"""Transformer step profiler (BERT/GPT) — the transformer counterpart
of tools/profile_resnet.py.

Measures the EXACT bench.py train step with amortized in-graph chains
where useful, because single dispatches through the dev tunnel carry
~100 ms round-trip (PERF.md) and cannot time kernels.

Usage (real chip):
    python tools/profile_transformer.py --model gpt   [--batch 8 --seq 1024]
    python tools/profile_transformer.py --model bert  [--batch 64 --seq 128]

Prints: cost_analysis flops/bytes, measured ms/step (best of 3),
TFLOPS-equivalent (6*N*tokens/s), and the top optimized-HLO op census.
"""
import argparse
import collections
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def build(model_name, batch, seq):
    import paddle_tpu as paddle
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed import env as dist_env

    dist_env.set_mesh(None)
    paddle.seed(0)
    if model_name == 'gpt':
        from paddle_tpu.models.gpt import gpt_small
        model = gpt_small(max_seq_len=seq, dropout=0.0)
        n_params = 124e6
    else:
        from paddle_tpu.models.bert import bert_base
        model = bert_base(max_seq_len=seq, dropout=0.0)
        n_params = 110e6
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())
    st = fleet.DistributedStrategy()
    st.amp = True
    st.amp_configs['use_pure_fp16'] = True
    tr = ParallelTrainer(model, opt, lambda o, y: model.loss(o, y),
                         strategy=st)
    rs = np.random.RandomState(0)
    V = model.config.vocab_size
    ids = rs.randint(0, V, size=(batch, seq)).astype('int64')
    if model_name == 'gpt':
        lbl = ids
    else:
        lbl = np.where(rs.rand(batch, seq) < 0.15,
                       rs.randint(0, V, size=(batch, seq)), -100) \
            .astype('int64')
    return tr, ids, lbl, n_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--model', choices=('gpt', 'bert'), default='gpt')
    ap.add_argument('--batch', type=int, default=None)
    ap.add_argument('--seq', type=int, default=None)
    ap.add_argument('--iters', type=int, default=15)
    args = ap.parse_args()
    batch = args.batch or (8 if args.model == 'gpt' else 64)
    seq = args.seq or (1024 if args.model == 'gpt' else 128)

    import jax
    print(f'device: {jax.devices()[0]}', flush=True)
    tr, ids, lbl, n_params = build(args.model, batch, seq)
    # device-resident inputs, exactly like bench.py: measure compute,
    # not the host link
    ids = jax.device_put(ids)
    lbl = jax.device_put(lbl)

    t0 = time.time()
    loss = None
    for _ in range(3):
        loss = tr.step(ids, lbl)
    float(np.asarray(loss))
    print(f'warmup (3 steps incl. compile): {time.time() - t0:.0f}s '
          f'loss={float(np.asarray(loss)):.4f}', flush=True)

    best = None
    for _ in range(3):
        t0 = time.time()
        for _ in range(args.iters):
            loss = tr.step(ids, lbl)
        float(np.asarray(loss))
        dt = (time.time() - t0) / args.iters
        best = dt if best is None or dt < best else best
    toks = batch * seq / best
    print(f'{args.model} b={batch} T={seq}: {best * 1000:.1f} ms/step '
          f'{toks:.0f} tokens/s '
          f'(~{6 * n_params * toks / 1e12:.1f} TFLOPS-eq, '
          f'{6 * n_params * toks / 1e12 / 197 * 100:.0f}% of v5e peak)',
          flush=True)

    # cost analysis LAST: lower().compile() goes through the AOT path
    # and does NOT reuse jit's in-memory executable — it recompiles.
    # Running it after the timing loop keeps the chip idle while
    # measuring (PERF.md methodology rule 2)
    compiled = getattr(tr, '_compiled', None)
    analysis = None
    if compiled is not None and hasattr(compiled, 'lower'):
        try:
            import jax.numpy as jnp
            from paddle_tpu.core import rng as rng_mod
            lowered = compiled.lower(
                tr.params, tr.buffers, tr.opt_state,
                jnp.asarray(1), rng_mod.next_key(),
                *(jnp.asarray(a) for a in (ids, lbl)))
            analysis = lowered.compile()
            ca = analysis.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            print(f"cost: {ca.get('flops', 0):.3e} flops/step, "
                  f"{ca.get('bytes accessed', 0):.3e} bytes/step",
                  flush=True)
        except Exception as e:
            print(f'cost_analysis unavailable: {e!r}', flush=True)

    # optimized-HLO op census (where do the ops go)
    if analysis is not None:
        try:
            import re
            hlo = analysis.as_text()
            ops = collections.Counter(
                m.group(1) for m in re.finditer(
                    r'^\s*(?:ROOT )?\S+ = \S+ (\w+)\(', hlo,
                    re.MULTILINE))
            print('top HLO ops:', ops.most_common(12), flush=True)
        except Exception as e:
            print(f'hlo census unavailable: {e!r}', flush=True)


if __name__ == '__main__':
    main()
