#!/usr/bin/env python
"""Transformer step profiler (BERT/GPT) — the transformer counterpart
of tools/profile_resnet.py.

Measures the EXACT bench.py train step with amortized in-graph chains
where useful, because single dispatches through the dev tunnel carry
~100 ms round-trip (PERF.md) and cannot time kernels.

Usage (real chip):
    python tools/profile_transformer.py --model gpt   [--batch 8 --seq 1024]
    python tools/profile_transformer.py --model bert  [--batch 64 --seq 128]

Prints: cost_analysis flops/bytes, measured ms/step (best of 3),
TFLOPS-equivalent (6*N*tokens/s), and the top optimized-HLO op census
(via the shared ``profiler.op_summary`` / ``analysis.hlo`` parser —
the ad-hoc Counter census this script used to carry is gone).

``--emit-telemetry`` additionally captures an on-device trace window
around one timing rep through the shared capture/parse API
(``telemetry.capture``), leaving telemetry JSONL + a
``profile_capture`` breakdown (and census-matched
``collective_observed`` events on multi-device runs) in ``--out`` for
tools/run_report.py / tools/calibrate_costmodel.py.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def build(model_name, batch, seq):
    import paddle_tpu as paddle
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed import env as dist_env

    dist_env.set_mesh(None)
    paddle.seed(0)
    if model_name == 'gpt':
        from paddle_tpu.models.gpt import gpt_small
        model = gpt_small(max_seq_len=seq, dropout=0.0)
        n_params = 124e6
    else:
        from paddle_tpu.models.bert import bert_base
        model = bert_base(max_seq_len=seq, dropout=0.0)
        n_params = 110e6
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())
    st = fleet.DistributedStrategy()
    st.amp = True
    st.amp_configs['use_pure_fp16'] = True
    tr = ParallelTrainer(model, opt, lambda o, y: model.loss(o, y),
                         strategy=st)
    rs = np.random.RandomState(0)
    V = model.config.vocab_size
    ids = rs.randint(0, V, size=(batch, seq)).astype('int64')
    if model_name == 'gpt':
        lbl = ids
    else:
        lbl = np.where(rs.rand(batch, seq) < 0.15,
                       rs.randint(0, V, size=(batch, seq)), -100) \
            .astype('int64')
    return tr, ids, lbl, n_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--model', choices=('gpt', 'bert'), default='gpt')
    ap.add_argument('--batch', type=int, default=None)
    ap.add_argument('--seq', type=int, default=None)
    ap.add_argument('--iters', type=int, default=15)
    ap.add_argument('--emit-telemetry', action='store_true',
                    help='capture a trace window around one timing '
                         'rep and stream telemetry JSONL to --out')
    ap.add_argument('--out', default=None,
                    help='telemetry/trace output dir for '
                         '--emit-telemetry (default: '
                         'tools/chip_out/profile_<model>)')
    args = ap.parse_args()
    batch = args.batch or (8 if args.model == 'gpt' else 64)
    seq = args.seq or (1024 if args.model == 'gpt' else 128)
    out = args.out or os.path.join('tools', 'chip_out',
                                   f'profile_{args.model}')

    import jax
    from paddle_tpu import telemetry
    print(f'device: {jax.devices()[0]}', flush=True)
    if args.emit_telemetry:
        telemetry.enable(out)
    tr, ids, lbl, n_params = build(args.model, batch, seq)
    # device-resident inputs, exactly like bench.py: measure compute,
    # not the host link
    ids = jax.device_put(ids)
    lbl = jax.device_put(lbl)

    t0 = time.time()
    loss = None
    for _ in range(3):
        loss = tr.step(ids, lbl)
    float(np.asarray(loss))
    print(f'warmup (3 steps incl. compile): {time.time() - t0:.0f}s '
          f'loss={float(np.asarray(loss)):.4f}', flush=True)

    best = None
    for _ in range(3):
        t0 = time.time()
        for _ in range(args.iters):
            loss = tr.step(ids, lbl)
        float(np.asarray(loss))
        dt = (time.time() - t0) / args.iters
        best = dt if best is None or dt < best else best

    if args.emit_telemetry:
        # a SEPARATE short traced window AFTER the headline reps: the
        # window close pays block_until_ready + trace parse + the
        # compiled_text lowering — none of which may touch the
        # best-of-3 measurement (PERF.md methodology)
        n_trace = min(args.iters, 4)
        with telemetry.capture(
                os.path.join(out, 'trace'), name=args.model,
                hlo_text_fn=tr.compiled_text,
                mesh_shape=(dict(tr.mesh.shape)
                            if tr.mesh is not None else None),
                steps=n_trace) as cap:
            for _ in range(n_trace):
                loss = tr.step(ids, lbl)
            cap.sync = loss
        win = cap.windows[-1] if cap.windows else {}
        print(f'trace window ({n_trace} steps): '
              f'{win.get("device_us_per_step", 0):.0f} us/step '
              f'device, '
              f'{win.get("collective_us_per_step", 0):.0f} us '
              f'collectives ({len(cap.observed)} '
              'collective_observed)', flush=True)
    toks = batch * seq / best
    print(f'{args.model} b={batch} T={seq}: {best * 1000:.1f} ms/step '
          f'{toks:.0f} tokens/s '
          f'(~{6 * n_params * toks / 1e12:.1f} TFLOPS-eq, '
          f'{6 * n_params * toks / 1e12 / 197 * 100:.0f}% of v5e peak)',
          flush=True)

    # census LAST: compiled_text() lowers through the AOT path (it
    # does not reuse jit's in-memory executable), so running it after
    # the timing loop keeps the chip idle while measuring (PERF.md
    # methodology rule 2).  One shared lowering serves the module
    # cost totals AND the per-op table (profiler.op_summary over the
    # analysis.hlo parser — and nothing at all when the persistent
    # compile cache already holds this step's text).
    try:
        tr.op_summary(ids, lbl, top=12)
    except Exception as e:
        print(f'op census unavailable: {e!r}', flush=True)
    if args.emit_telemetry:
        telemetry.disable()
        print(f'telemetry JSONL + trace artifacts: {out}', flush=True)


if __name__ == '__main__':
    main()
