#!/usr/bin/env python
"""Scan-over-layers decode A/B (GPTConfig.scan_decode_blocks).

The unrolled decode module's ~900 s remote compile twice wedged the
round-4 tunnel; scanning one block body over stacked per-layer params
shrinks the module ~num_layers-fold.  CPU measured compile -28% but
runtime +71% (models/gpt.py GPTConfig comment) — this A/B decides
whether the TPU compile shrink is worth the TPU runtime delta.
Token-exact parity between the two forms is locked in
tests/test_kv_cache.py.

Prints one JSON line with per-arm warmup (trace+compile+first run)
seconds and decoded tok/s.  Kept-or-killed: scan becomes the decode
default only if tok/s holds within ~5% AND compile drops materially.
"""
import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# UNLIKE the other chip tools, this one must NOT reuse the shared
# persistent XLA cache: compile time IS the decision metric, and a
# warm cache would collapse both arms' warmup_s to cache-load time.
# A fresh temp dir per invocation keeps every compile cold (the
# sitecustomize imports jax at boot, so set the live config too).
_cache_dir = tempfile.mkdtemp(prefix='scan_decode_jax_cache_')
os.environ['JAX_COMPILATION_CACHE_DIR'] = _cache_dir
if 'jax' in sys.modules:
    import jax as _jax
    try:
        _jax.config.update('jax_compilation_cache_dir', _cache_dir)
    except AttributeError:
        pass


def bench(scan, args):
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt_small, gpt_tiny

    paddle.seed(0)
    if args.smoke:
        model, batch, prompt, new = (
            gpt_tiny(scan_decode_blocks=scan), 2, 8, 8)
    else:
        model = gpt_small(max_seq_len=args.prompt + args.new,
                          dropout=0.0, scan_decode_blocks=scan)
        batch, prompt, new = args.batch, args.prompt, args.new
    model.eval()
    rs = np.random.RandomState(0)
    ids = rs.randint(0, model.config.vocab_size,
                     size=(batch, prompt)).astype('int64')
    t0 = time.time()
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                         temperature=0)
    np.asarray(out.value)
    warmup_s = time.time() - t0
    print(f'{"scan" if scan else "unrolled"} warmup '
          f'(trace+compile+run): {warmup_s:.1f}s', file=sys.stderr)
    t0 = time.time()
    for i in range(args.iters):
        out = model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                             temperature=0, seed=i)
        np.asarray(out.value)     # tunnel-proof completion barrier
    dt = time.time() - t0
    return {'warmup_s': round(warmup_s, 1),
            'tokens_per_s': batch * new * args.iters / dt}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--smoke', action='store_true')
    ap.add_argument('--iters', type=int, default=5)
    ap.add_argument('--batch', type=int, default=8)
    ap.add_argument('--prompt', type=int, default=128)
    ap.add_argument('--new', type=int, default=128)
    args = ap.parse_args()
    if args.smoke:
        args.iters = 2

    import jax
    print(f'device: {jax.devices()[0]}', file=sys.stderr)
    rows = {}
    # scan arm FIRST: if the unrolled compile wedges the tunnel we
    # still learn what the scan compile costs
    for scan in (True, False):
        name = 'scan' if scan else 'unrolled'
        rows[name] = r = bench(scan, args)
        print(f"{name}: {r['tokens_per_s']:.0f} tok/s "
              f"(warmup {r['warmup_s']}s)", file=sys.stderr)
    rows['speedup_scan_over_unrolled'] = (
        rows['scan']['tokens_per_s'] / rows['unrolled']['tokens_per_s'])
    rows['compile_ratio'] = (rows['scan']['warmup_s'] /
                             max(rows['unrolled']['warmup_s'], 1e-9))
    print(json.dumps(rows))


if __name__ == '__main__':
    main()
