#!/usr/bin/env python
"""One-command chip session: run every hardware-blocked measurement in
CHIPDAY.md order, persisting per-step artifacts so a mid-session tunnel
wedge loses nothing.

    python tools/chip_session.py            # run all pending steps
    python tools/chip_session.py --watch    # poll until the tunnel
                                            # answers, then run

Design rules (learned the hard way — see PERF.md and the verify skill):
- every step runs in ITS OWN subprocess with a GENEROUS timeout
  (killing a python mid-TPU-compile wedges the tunnel for hours);
- each step's stdout/stderr land in tools/chip_out/<step>.log, and a
  step that already has a .ok marker is skipped on re-run;
- after any step fails or times out, a 90s preflight decides between
  continuing and stopping (a dead tunnel fails everything downstream
  anyway — better to leave the queue intact for the next window).
"""
import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, 'tools', 'chip_out')

# persistent XLA compilation cache for every child (recompiles are the
# riskiest tunnel window); harmless no-op where unsupported
sys.path.insert(0, REPO)
from tools._env import setup_jax_cache  # noqa: E402
setup_jax_cache()

# (name, argv, timeout_s) — order matters: cheap/valuable first, the
# historical wedge offender (gptgen inside bench.py) is covered by
# bench.py's own per-config isolation + TIMEOUT_SCALE.
STEPS = [
    ('bench', [sys.executable, 'bench.py'], 3 * 3600),
    ('fused_head_ab',
     [sys.executable, 'tools/bench_fused_head.py', '--iters', '15'],
     45 * 60),
    ('ce_backward',
     [sys.executable, 'tools/bench_ce_backward.py'], 30 * 60),
    ('tune_flash', [sys.executable, 'tools/tune_flash.py'], 3 * 3600),
    # re-measure the two flash-bound train configs WITH the tuned
    # blocks (the 'bench' step above ran before the table existed);
    # single-config runs record into bench_results.json, so the
    # stale-merge serves the tuned numbers
    ('bench_gpt_posttune',
     [sys.executable, 'bench.py', '--config', 'gpt'], 45 * 60),
    ('bench_longctx_posttune',
     [sys.executable, 'bench.py', '--config', 'longctx'], 60 * 60),
    ('census_gpt',
     [sys.executable, 'tools/profile_transformer.py', '--model', 'gpt'],
     45 * 60),
    ('census_bert',
     [sys.executable, 'tools/profile_transformer.py', '--model', 'bert'],
     45 * 60),
    ('profile_resnet', [sys.executable, 'tools/profile_resnet.py'],
     45 * 60),
    # self-profiling closed loop: capture a sampled trace window over
    # the built-in dp workload on the REAL chips, emit
    # collective_observed telemetry, and fit the calibration table
    # the auto-sharding planner consumes (ROADMAP item-3 follow-up:
    # the fitter finally has an on-device producer).  --dp 0 = every
    # visible device; artifacts (traces + telemetry JSONL +
    # calibration.json) land in the committed evidence dir
    ('profile_collectives',
     [sys.executable, 'tools/profile_run.py', '--dp', '0',
      '--out', 'tools/chip_out/profile_run',
      '--fit', 'tools/chip_out/calibration.json'], 45 * 60),
    ('perf_experiments', [sys.executable, 'tools/perf_experiments.py'],
     2 * 3600),
    ('int8_matmul', [sys.executable, 'tools/bench_int8_matmul.py'],
     30 * 60),
    ('widedeep_gather',
     [sys.executable, 'tools/bench_widedeep_gather.py'], 45 * 60),
    ('embedding_grad',
     [sys.executable, 'tools/bench_embedding_grad.py'], 30 * 60),
    # chunk-size sweep LAST (fused arm only — the unfused baseline is
    # already in fused_head_ab.log and does not depend on --chunks);
    # touch tools/chip_out/fused_head_c{4,16}.ok beforehand to skip
    # when the default-8 MFU already hit target
    ('fused_head_c4',
     [sys.executable, 'tools/bench_fused_head.py', '--iters', '10',
      '--chunks', '4', '--arm', 'fused'], 30 * 60),
    ('fused_head_c16',
     [sys.executable, 'tools/bench_fused_head.py', '--iters', '10',
      '--chunks', '16', '--arm', 'fused'], 30 * 60),
    # VERY LAST: compiles two gptgen-sized decode modules (the known
    # wedge class) — a timeout here must not cost any other step, and
    # the window is generous enough that a kill should never fire
    ('int8_decode',
     [sys.executable, 'tools/bench_int8_decode.py'], 3 * 3600),
    ('scan_decode',
     [sys.executable, 'tools/bench_scan_decode.py'], 3 * 3600),
]


def log(msg):
    print(f'[chip_session +{time.strftime("%H:%M:%S")}] {msg}',
          file=sys.stderr, flush=True)


def preflight(timeout_s=90):
    """True iff the accelerator answers a tiny jit within timeout_s.
    Runs in a child so a wedged tunnel cannot hang US."""
    code = ('import jax, numpy as np, jax.numpy as jnp;'
            'print(float(np.asarray(jax.jit(lambda a: a.sum())'
            '(jnp.ones((8, 8))))))')
    try:
        p = subprocess.run([sys.executable, '-c', code], cwd=REPO,
                           capture_output=True, timeout=timeout_s)
        return p.returncode == 0 and b'64.0' in p.stdout
    except subprocess.TimeoutExpired:
        return False


def collect_memsnap(name, timeout_s=120):
    """Archive a device-memory snapshot right after a step finishes
    (memory observatory satellite): per-device HBM stats from
    ``device.memory_stats()`` plus host RSS land in
    tools/chip_out/<step>.mem.json.  Runs in a child — same rule as
    preflight: a wedged tunnel must not hang the session driver — and
    a probe failure only logs; the step's own verdict stands."""
    code = (
        'import json\n'
        'import jax\n'
        'from paddle_tpu.telemetry import memory as mem\n'
        'print(json.dumps({\n'
        '    "platform": jax.devices()[0].platform,\n'
        '    "num_devices": len(jax.local_devices()),\n'
        '    "devices": mem.device_memory_stats(),\n'
        '    "host_rss_bytes": mem.host_rss_bytes(),\n'
        '}))\n')
    try:
        p = subprocess.run([sys.executable, '-c', code], cwd=REPO,
                           capture_output=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f'{name}: memory snapshot probe timed out ({timeout_s}s)')
        return
    if p.returncode != 0:
        log(f'{name}: memory snapshot probe failed '
            f'(rc={p.returncode})')
        return
    try:
        snap = json.loads(p.stdout.decode().strip().splitlines()[-1])
    except (ValueError, IndexError):
        log(f'{name}: memory snapshot probe emitted no JSON')
        return
    snap['step'] = name
    snap['t'] = time.time()
    with open(os.path.join(OUT, f'{name}.mem.json'), 'w') as fh:
        json.dump(snap, fh, indent=1)
    rows = snap.get('devices') or []
    log(f'{name}: memory snapshot archived '
        f'({snap.get("num_devices", 0)} device(s), '
        f'{len(rows)} with HBM stats)')


def collect_flightrecs(name):
    """Copy any telemetry flight-recorder dumps a step left behind
    (flightrec-*.json next to checkpoints / scratch dirs under the
    repo) into the committed evidence dir — a tunnel death right after
    a preemption/NaN event must not lose its post-mortem.  Dumps are
    renamed '<step>__<orig>' so successive steps never clobber."""
    import shutil
    dst_dir = os.path.join(OUT, 'flightrec')
    skip = {'.git', '.jax_cache', '.pytest_cache', '__pycache__',
            'node_modules'}
    found = 0
    for root, dirs, files in os.walk(REPO):
        dirs[:] = [d for d in dirs if d not in skip]
        if os.path.abspath(root).startswith(os.path.abspath(dst_dir)):
            continue
        for f in files:
            if not (f.startswith('flightrec-') and f.endswith('.json')):
                continue
            src = os.path.join(root, f)
            os.makedirs(dst_dir, exist_ok=True)
            dst = os.path.join(dst_dir, f'{name}__{f}')
            try:
                if not os.path.exists(dst) or \
                        os.path.getmtime(src) > os.path.getmtime(dst):
                    shutil.copy2(src, dst)
                    found += 1
            except OSError:
                pass
    if found:
        log(f'{name}: {found} flight-recorder dump(s) archived to '
            f'{dst_dir}')


def commit_artifacts(name, ok):
    """Commit the step's artifacts IMMEDIATELY (round-4 lesson: the
    only copies of a whole session's measurements lived in gitignored
    files and PERF.md prose — a later CPU smoke run overwrote them).
    Logs land on success AND failure; a failure log is evidence too."""
    paths = [os.path.join('tools', 'chip_out')]
    tuning = os.path.join(REPO, 'paddle_tpu', 'ops',
                          'flash_attention_tuning.json')
    if os.path.exists(tuning):
        paths.append(os.path.relpath(tuning, REPO))
    for attempt in range(3):
        try:
            subprocess.run(['git', 'add', '-A', '--'] + paths,
                           cwd=REPO, check=True, capture_output=True)
            staged = subprocess.run(
                ['git', 'diff', '--cached', '--quiet', '--'] + paths,
                cwd=REPO)
            if staged.returncode == 0:
                return          # nothing new
            # pathspec-scoped commit: a concurrent interactive session
            # may have unrelated files staged — those must not be
            # swept into a chip-evidence commit
            subprocess.run(
                ['git', 'commit', '-m',
                 f'chip evidence: {name} '
                 f'({"ok" if ok else "failed"})', '--'] + paths,
                cwd=REPO, check=True, capture_output=True)
            log(f'{name}: artifacts committed')
            return
        except subprocess.CalledProcessError as e:
            # index.lock contention with an interactive session is the
            # expected failure; back off and retry
            log(f'{name}: git commit attempt {attempt + 1} failed '
                f'({e.stderr.decode(errors="replace")[-200:]}); '
                'retrying in 15s')
            time.sleep(15)
    log(f'{name}: artifacts NOT committed after 3 attempts '
        '(left staged/untracked for manual pickup)')


def run_step(name, argv, timeout_s):
    okf = os.path.join(OUT, f'{name}.ok')
    if os.path.exists(okf):
        log(f'{name}: already done (rm {okf} to re-run)')
        return True
    logf = os.path.join(OUT, f'{name}.log')
    log(f'{name}: starting (timeout {timeout_s}s), log: {logf}')
    t0 = time.time()
    with open(logf, 'w') as fh:
        try:
            p = subprocess.run(argv, cwd=REPO, stdout=fh,
                               stderr=subprocess.STDOUT,
                               timeout=timeout_s)
        except subprocess.TimeoutExpired:
            log(f'{name}: TIMED OUT after {timeout_s}s')
            collect_flightrecs(name)
            collect_memsnap(name)
            commit_artifacts(name, ok=False)
            return False
    dt = time.time() - t0
    collect_flightrecs(name)
    collect_memsnap(name)
    if p.returncode == 0:
        with open(okf, 'w') as fh:
            fh.write(json.dumps({'t': time.time(), 'dur_s': dt}))
        log(f'{name}: ok in {dt:.0f}s')
        commit_artifacts(name, ok=True)
        return True
    log(f'{name}: FAILED rc={p.returncode} after {dt:.0f}s '
        f'(tail: see {logf})')
    commit_artifacts(name, ok=False)
    return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--watch', action='store_true',
                    help='poll the tunnel every 120s until it answers')
    ap.add_argument('--only', default=None,
                    help='comma-separated step names')
    args = ap.parse_args()
    os.makedirs(OUT, exist_ok=True)

    if args.only:
        want = [w.strip() for w in args.only.split(',') if w.strip()]
        known = {s[0] for s in STEPS}
        bad = [w for w in want if w not in known]
        if bad:
            log(f'unknown step(s) {bad}; choose from {sorted(known)}')
            sys.exit(2)
        steps = [s for s in STEPS if s[0] in want]
    else:
        steps = STEPS

    if args.watch:
        n = 0
        while not preflight(90):
            n += 1
            log(f'tunnel dead (probe {n}); sleeping 120s')
            time.sleep(120)
    elif not preflight(120):
        log('tunnel not answering; aborting (re-run with --watch)')
        sys.exit(2)
    log('tunnel alive — running queued steps')

    failed = []
    for name, argv, timeout_s in steps:
        if not run_step(name, argv, timeout_s):
            failed.append(name)
            if not preflight(90):
                log('tunnel died mid-session; stopping so the queue '
                    'survives for the next window')
                pending = [s[0] for s in steps
                           if not os.path.exists(
                               os.path.join(OUT, f'{s[0]}.ok'))]
                log(f'pending steps: {pending}')
                sys.exit(3)
            log('tunnel still alive after failure; continuing')
    if failed:
        log(f'session finished with FAILED steps: {failed}')
        sys.exit(1)
    log('session complete — all steps ok')


if __name__ == '__main__':
    main()
