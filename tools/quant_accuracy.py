#!/usr/bin/env python
"""quant_accuracy — bit-accuracy convergence harness for the
quantized wire.

Trains the SAME model twice on the SAME data and rng stream — once
full width, once with ``ParallelTrainer(quant_collectives='int8')`` —
and gates on the final-loss delta: the EQuARX claim is 2-4x wire
reduction at negligible quality loss, and this harness is the
"negligible" half of that claim, runnable on the CPU smoke before any
chip time is spent.  The wire half rides along: each trainer's
compiled module is censused (analysis.hlo.collective_census) so the
report carries measured predicted-wire bytes per dtype, and
``bench.py --quant-smoke`` joins the same evidence through
run_report.

    python tools/quant_accuracy.py                   # lenet + gpt
    python tools/quant_accuracy.py --steps 60 --json
    python tools/quant_accuracy.py --master-accum    # exact-sum mode

Exit 0 iff every gate holds (loss deltas within --gate-rel, wire
reduction >= --gate-wire).
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# an 8-device virtual CPU mesh, forced BEFORE jax import (same posture
# as tests/conftest.py); the real-TPU tunnel env must not leak in
os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()
os.environ.setdefault('PADDLE_TPU_COMPILE_CACHE', '0')

import numpy as np  # noqa: E402


def _census(trainer, mesh):
    """Per-op predicted wire bytes (+ dtype tags) of the compiled
    step."""
    from paddle_tpu.analysis import hlo as _hlo
    census = _hlo.collective_census(
        _hlo.parse_module(trainer.compiled_text()),
        mesh_shape=dict(mesh.shape))
    return {
        'per_op': {op: {'calls': r['calls'],
                        'wire_bytes': r['wire_bytes'],
                        'wire_dtype': r.get('wire_dtype')}
                   for op, r in census.items()},
        'wire_bytes_total': sum(r['wire_bytes']
                                for r in census.values()),
    }


def _run(make_model, make_batch, loss_fn, *, quant, steps, seed,
         n_inputs=1, profile=None):
    """One training run; returns losses + wire census + compile
    counts."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import telemetry
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import env as dist_env

    prev = dist_env.get_mesh()
    mesh = dist_env.build_mesh({'dp': 8})
    dist_env.set_mesh(mesh)
    try:
        paddle.seed(seed)
        model = make_model()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        tr = ParallelTrainer(model, opt, loss_fn, mesh=mesh,
                             n_inputs=n_inputs,
                             quant_collectives=quant, profile=profile)
        batch = make_batch()
        losses = []
        compiles0 = len(telemetry.events('compile')) \
            if telemetry.active() else 0
        for i in range(steps):
            losses.append(float(np.asarray(tr.step(*batch))))
        jax.block_until_ready(losses[-1])
        if profile is not None:
            tr.finish_profile(sync=losses[-1])
        compiles = (len(telemetry.events('compile')) - compiles0) \
            if telemetry.active() else None
        out = {
            'final_loss': losses[-1],
            'first_loss': losses[0],
            'losses': [round(v, 6) for v in losses],
            'quant': (vars(tr._quant_active)
                      if tr._quant_active is not None else None),
            'compile_events': compiles,
            'census': _census(tr, mesh),
        }
        return out
    finally:
        dist_env.set_mesh(prev)


def run_lenet(quant=None, steps=40, seed=0, profile=None):
    """LeNet on synthetic MNIST-shaped data, dp=8."""
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet
    rs = np.random.RandomState(0)
    x = rs.randn(64, 1, 28, 28).astype('float32')
    y = rs.randint(0, 10, size=(64, 1)).astype('int64')
    ce = nn.CrossEntropyLoss()
    return _run(LeNet, lambda: (x, y), lambda o, t: ce(o, t),
                quant=quant, steps=steps, seed=seed, profile=profile)


def run_gpt(quant=None, steps=8, seed=0, profile=None):
    """gpt-tiny causal LM, a few steps, dp=8."""
    from paddle_tpu.models.gpt import gpt_tiny
    rs = np.random.RandomState(0)
    holder = {}

    def make():
        m = holder['m'] = gpt_tiny(max_seq_len=32)
        return m

    ids = None

    def batch():
        nonlocal ids
        if ids is None:
            V = holder['m'].config.vocab_size
            ids = rs.randint(0, V, size=(16, 32)).astype('int64')
        return (ids, ids)

    return _run(make, batch, lambda o, y: holder['m'].loss(o, y),
                quant=quant, steps=steps, seed=seed, profile=profile)


def compare(target, quant_cfg, steps, seed=0, profile=None):
    """Full-width vs quantized run of one target; returns the joined
    evidence row."""
    runner = {'lenet': run_lenet, 'gpt': run_gpt}[target]
    # quant=False, not None: None means "the env decides", and an
    # ambient PADDLE_TPU_QUANT_COLLECTIVES would silently quantize
    # the BASELINE too — the gate would then compare quantized vs
    # quantized and report the wire as pointless
    full = runner(quant=False, steps=steps, seed=seed)
    q = runner(quant=quant_cfg, steps=steps, seed=seed,
               profile=profile)
    fw = full['census']['wire_bytes_total']
    qw = max(1, q['census']['wire_bytes_total'])
    delta = abs(q['final_loss'] - full['final_loss'])
    denom = max(abs(full['first_loss'] - full['final_loss']), 1e-9)
    return {
        'target': target,
        'final_loss_full': full['final_loss'],
        'final_loss_quant': q['final_loss'],
        'loss_delta': round(delta, 6),
        # delta relative to the loss PROGRESS full-width made — "the
        # quantized run reached the same place", scale-free across
        # targets
        'loss_delta_rel': round(delta / denom, 6),
        'wire_bytes_full': fw,
        'wire_bytes_quant': qw,
        'wire_reduction': round(fw / qw, 3),
        'quant_active': q['quant'],
        'census_full': full['census']['per_op'],
        'census_quant': q['census']['per_op'],
        'compile_events_quant': q['compile_events'],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='quantized-wire vs full-width convergence gate')
    ap.add_argument('--targets', default='lenet,gpt')
    ap.add_argument('--steps', type=int, default=40,
                    help='lenet steps (gpt runs max(8, steps//5))')
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--block', type=int, default=256)
    ap.add_argument('--master-accum', action='store_true')
    ap.add_argument('--no-stochastic', action='store_true')
    ap.add_argument('--gate-rel', type=float, default=0.10,
                    help='max |final-loss delta| as a fraction of the '
                         'full-width loss progress')
    ap.add_argument('--gate-wire', type=float, default=2.0,
                    help='min full/quant predicted-wire-byte ratio')
    ap.add_argument('--json', action='store_true')
    args = ap.parse_args(argv)

    quant_cfg = {'block': args.block, 'min_bytes': 0,
                 'master_accum': args.master_accum,
                 'stochastic': not args.no_stochastic}
    rows = []
    failures = []
    for target in args.targets.split(','):
        target = target.strip()
        steps = args.steps if target == 'lenet' \
            else max(8, args.steps // 5)
        row = compare(target, quant_cfg, steps, seed=args.seed)
        rows.append(row)
        if row['loss_delta_rel'] > args.gate_rel:
            failures.append(
                f'{target}: quantized final loss drifted '
                f'{row["loss_delta_rel"] * 100:.1f}% of full-width '
                f'progress (gate {args.gate_rel * 100:.0f}%): '
                f'{row["final_loss_full"]:.5f} vs '
                f'{row["final_loss_quant"]:.5f}')
        if row['wire_reduction'] < args.gate_wire:
            failures.append(
                f'{target}: wire reduction x{row["wire_reduction"]} '
                f'below the x{args.gate_wire} gate')
        if not row['quant_active']:
            failures.append(f'{target}: quantized wire never armed '
                            '(trainer fell back to full width)')
    doc = {'ok': not failures, 'failures': failures, 'rows': rows,
           'config': quant_cfg}
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        for r in rows:
            print(f'{r["target"]}: full {r["final_loss_full"]:.5f} '
                  f'quant {r["final_loss_quant"]:.5f} '
                  f'(delta {r["loss_delta_rel"] * 100:.2f}% of '
                  f'progress), wire x{r["wire_reduction"]} '
                  f'({r["wire_bytes_full"]:,} -> '
                  f'{r["wire_bytes_quant"]:,} B)')
        for f in failures:
            print(f'FAIL: {f}')
        if not failures:
            print('ok')
    return 0 if not failures else 1


if __name__ == '__main__':
    sys.exit(main())
