#!/usr/bin/env python
"""tpu_lint — sweep Python sources for TPU compilation hazards.

The CLI front of paddle_tpu.analysis: AST-lints files/directories (no
imports, no device, no execution — safe on any tree), and optionally
deep-lints one callable's jaxpr.

    python tools/tpu_lint.py examples/ paddle_tpu/models/
    python tools/tpu_lint.py train.py --scope all       # audit host loops
    python tools/tpu_lint.py examples/ --json           # machine output
    python tools/tpu_lint.py x.py --disable host-sync
    python tools/tpu_lint.py --jaxpr pkg.mod:fn --shapes 8x128xf32,8xi32

Exit codes: 0 = no findings at/above --fail-on (default: high),
1 = findings at/above --fail-on, 2 = usage error.  CI and bench
scripts consume --json; the tier-1 self-lint gate
(tests/test_analysis.py) runs this over examples/ and
paddle_tpu/models/ and requires exit 0.

Suppress a finding with `# tpu-lint: disable=<rule-id>` on its line.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_SEVS = ('info', 'warn', 'high')


_DTYPE_TOKENS = {
    'f16': 'float16', 'f32': 'float32', 'f64': 'float64',
    'i8': 'int8', 'i16': 'int16', 'i32': 'int32', 'i64': 'int64',
    'u8': 'uint8', 'u32': 'uint32', 'bool': 'bool',
}


def _parse_shapes(spec):
    """'8x128xf32,8xi32' -> [ShapeDtypeStruct] (last token = dtype;
    short tokens f32/i32/bf16/... or any numpy dtype name)."""
    import numpy as np
    import jax.numpy as jnp
    import jax
    out = []
    for part in spec.split(','):
        toks = part.strip().split('x')
        tok = toks[-1]
        if tok == 'bf16':
            dtype = jnp.bfloat16
        else:
            dtype = np.dtype(_DTYPE_TOKENS.get(tok, tok))
        shape = tuple(int(t) for t in toks[:-1])
        out.append(jax.ShapeDtypeStruct(shape, dtype))
    return out


def _resolve(target):
    import importlib
    mod_name, _, fn_name = target.partition(':')
    if not fn_name:
        raise SystemExit(f'--jaxpr needs module:function, got {target!r}')
    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='tpu_lint',
        description='jaxpr/AST TPU lint: recompile hazards, host '
                    'syncs, sharding & dtype audits.')
    ap.add_argument('paths', nargs='*',
                    help='.py files or directories to AST-lint')
    ap.add_argument('--scope', choices=('traced', 'all'),
                    default='traced',
                    help="'traced' lints only code the framework will "
                         "trace (to_static/jit/forward); 'all' audits "
                         'every function (host step loops)')
    ap.add_argument('--disable', action='append', default=[],
                    metavar='RULE', help='rule id to skip (repeatable)')
    ap.add_argument('--fail-on', choices=_SEVS + ('never',),
                    default='high',
                    help='lowest severity that makes the exit code '
                         'non-zero (default: high)')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable output for CI/bench scripts')
    ap.add_argument('--jaxpr', metavar='MOD:FN',
                    help='additionally deep-lint one callable by '
                         'tracing its jaxpr (imports the module)')
    ap.add_argument('--shapes', metavar='SPEC',
                    help='example shapes for --jaxpr, e.g. '
                         '"8x128xf32,8xi32" (last token is the dtype)')
    args = ap.parse_args(argv)

    if not args.paths and not args.jaxpr:
        ap.print_usage(sys.stderr)
        print('tpu_lint: nothing to lint (give paths or --jaxpr)',
              file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f'tpu_lint: no such path: {p}', file=sys.stderr)
            return 2

    from paddle_tpu import analysis

    report = analysis.LintReport(name='tpu-lint')
    if args.paths:
        report.extend(analysis.lint_sources(
            args.paths, scope=args.scope, disable=args.disable))
    if args.jaxpr:
        try:
            fn = _resolve(args.jaxpr)
        except (ImportError, AttributeError, SystemExit) as e:
            print(f'tpu_lint: cannot resolve --jaxpr: {e}',
                  file=sys.stderr)
            return 2
        try:
            shapes = _parse_shapes(args.shapes) if args.shapes else []
        except (TypeError, ValueError) as e:
            print(f'tpu_lint: cannot parse --shapes: {e}',
                  file=sys.stderr)
            return 2
        report.extend(analysis.lint(fn, *shapes,
                                    disable=args.disable))

    if args.json:
        print(report.to_json(indent=2))
    else:
        print(report.render() if report else report.summary())

    if args.fail_on == 'never':
        return 0
    return 1 if report.at_least(args.fail_on) else 0


if __name__ == '__main__':
    sys.exit(main())
