#!/usr/bin/env python
"""tpu_lint — sweep Python sources for TPU compilation hazards.

The CLI front of paddle_tpu.analysis: AST-lints files/directories (no
imports, no device, no execution — safe on any tree), and optionally
deep-lints one callable's jaxpr.

    python tools/tpu_lint.py examples/ paddle_tpu/models/
    python tools/tpu_lint.py train.py --scope all       # audit host loops
    python tools/tpu_lint.py examples/ --json           # machine output
    python tools/tpu_lint.py x.py --disable host-sync
    python tools/tpu_lint.py --jaxpr pkg.mod:fn --shapes 8x128xf32,8xi32
    python tools/tpu_lint.py examples/ --hlo --mesh dp=8   # SPMD audit
    python tools/tpu_lint.py --plan --chips 8 [--hbm-gb 16]  # planner
    python tools/tpu_lint.py paddle_tpu/ --threads    # concurrency lint
    python tools/tpu_lint.py paddle_tpu/ --spmd     # SPMD contract lint

--threads swaps the sweep for the concurrency rules
(paddle_tpu.analysis.threads): guarded-by (annotated shared state
accessed outside its lock), blocking-under-lock (device syncs /
network / file IO / sleeps inside a critical section), and
daemon-thread-lifecycle (daemon threads with no stop/join path).
Pure source analysis, same suppression grammar; the tier-1 gate
(tests/test_analysis_threads.py) runs it over paddle_tpu/ at zero
HIGH.

--spmd swaps the sweep for the SPMD-contract rules
(paddle_tpu.analysis.spmd): rank-dependent-collective (a collective
reachable on only one side of a rank/process_index/env guard — the
deadlock hazard), collective-order (branch paths must issue identical
collective sequences; the HLO half joins hlo.collective_instrs
through `conditional`s on every --hlo audit), host-nondeterminism-
into-trace (time/env/host-random feeding traced values or collective
payloads without a broadcast) and unbroadcast-rng (host-local entropy
seeding per-rank keys).  Same suppression grammar; the tier-1 gate
(tests/test_analysis_spmd.py) runs it over paddle_tpu/ + tools/ at
zero HIGH.

--hlo escalates to the lowered-HLO SPMD audit (paddle_tpu.analysis.hlo):
each target step is lowered through jax.jit under a FORCED virtual
mesh (--mesh dp=8 / dp=4,tp=2 — CPU devices, no chip touched, no
execution), the compiled post-partitioner module is parsed, and the
HLO rules run: replicated-giant-hlo, collective-cost (ring
byte/latency estimates per all-reduce/all-gather/reduce-scatter/
all-to-all/collective-permute), resharding, peak-memory (liveness
high-water vs --hbm-gb).  For examples/ + paddle_tpu/models/ paths a
built-in suite of representative tiny step functions (GPT dp+tp,
WideDeep, LeNet — the models the examples train) is lowered; --jaxpr
targets are HLO-audited directly.

--plan runs the auto-sharding planner (paddle_tpu.analysis.planner)
over the same built-in suite: every dp/tp/pp factorization of --chips
(2D/3D torus layouts included) crossed with PartitionSpec assignments
(declared tp specs / fully replicated / fsdp dim-0) is lowered through
the partitioner and ranked by predicted step cost (torus-decomposed
collective wire time + a per-device compute floor) under the --hbm-gb
budget, with remat / half-batch fallback plans when nothing fits.
--plan and --hlo share one lowering per (target, mesh, shardings)
triple.  --calibration swaps measured alpha/beta (from
tools/calibrate_costmodel.py) into the cost model.

Exit codes: 0 = no findings at/above --fail-on (default: high),
1 = findings at/above --fail-on, 2 = usage error, or an --hlo
infra failure (mesh build / lower crashed: the text/JSON report is
still printed, with the error under "hlo_error").  CI and bench
scripts consume --json; the tier-1 self-lint gates
(tests/test_analysis.py, tests/test_analysis_hlo.py) run this over
examples/ and paddle_tpu/models/ (AST and --hlo) and require exit 0.

Suppress a finding with `# tpu-lint: disable=<rule-id>` on its line.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_SEVS = ('info', 'warn', 'high')


_DTYPE_TOKENS = {
    'f16': 'float16', 'f32': 'float32', 'f64': 'float64',
    'i8': 'int8', 'i16': 'int16', 'i32': 'int32', 'i64': 'int64',
    'u8': 'uint8', 'u32': 'uint32', 'bool': 'bool',
}


def _parse_shapes(spec):
    """'8x128xf32,8xi32' -> [ShapeDtypeStruct] (last token = dtype;
    short tokens f32/i32/bf16/... or any numpy dtype name)."""
    import numpy as np
    import jax.numpy as jnp
    import jax
    out = []
    for part in spec.split(','):
        toks = part.strip().split('x')
        tok = toks[-1]
        if tok == 'bf16':
            dtype = jnp.bfloat16
        else:
            dtype = np.dtype(_DTYPE_TOKENS.get(tok, tok))
        shape = tuple(int(t) for t in toks[:-1])
        out.append(jax.ShapeDtypeStruct(shape, dtype))
    return out


def _resolve(target):
    import importlib
    mod_name, _, fn_name = target.partition(':')
    if not fn_name:
        raise SystemExit(f'--jaxpr needs module:function, got {target!r}')
    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


# -- the lowered-HLO SPMD audit (--hlo) ---------------------------------------

def _parse_mesh(spec):
    """'dp=8' / 'dp=4,tp=2' -> ordered {axis: size}."""
    axes = {}
    for part in spec.split(','):
        name, _, size = part.strip().partition('=')
        if not size:
            raise ValueError(f'--mesh wants axis=size, got {part!r}')
        axes[name] = int(size)
    return axes


def _force_mesh_env(axes, min_devices=0):
    """Make enough virtual devices exist BEFORE jax imports.  The
    audit never executes device code, so CPU host devices are exactly
    as good as chips for lowering through the SPMD partitioner.
    Without --mesh the default is dp=8: forcing 1 device would make
    every SPMD rule silently vacuous.  ``min_devices`` raises the
    floor (--plan --chips N wants N devices regardless of --mesh)."""
    n = 1
    for v in (axes or {'dp': 8}).values():
        n *= v
    n = max(n, int(min_devices))
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={n}'
        ).strip()


def _build_mesh(axes):
    import numpy as np
    import jax
    from jax.sharding import Mesh
    if not axes:
        axes = {'dp': len(jax.devices())}
    n = 1
    for v in axes.values():
        n *= v
    devs = jax.devices()
    if n > len(devs):
        raise SystemExit(
            f'tpu_lint: mesh {axes} wants {n} devices but only '
            f'{len(devs)} exist (is JAX_PLATFORMS set to a fixed '
            'backend before the forced device count could apply?)')
    return Mesh(np.array(devs[:n]).reshape(tuple(axes.values())),
                tuple(axes.keys()))


def _run_hlo_suite(mesh, target_names, thresholds, disable,
                   lower_cache=None):
    """Lower + audit each built-in target (analysis.targets);
    returns {name: LintReport}.  `lower_cache` is the shared memo —
    when ``--plan`` already lowered this exact (target, mesh,
    shardings) triple, the audit reuses that compiled text instead of
    paying trace+lower a second time."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu import analysis
    from paddle_tpu.analysis import targets as _targets
    from paddle_tpu.distributed import env as _env
    reports, errors = {}, {}
    prev_mesh = _env.get_mesh()
    _env.set_mesh(mesh)     # model-internal maybe_shard constraints live
    try:
        for name in target_names:
            # per-target isolation: one broken lower must not discard
            # the audits of the targets that DO lower
            try:
                model, batch = _targets.TARGETS[name](mesh)
                params, buffers, p_sh, b_sh = _targets.target_state(
                    model, mesh)
                repl = NamedSharding(mesh, P())
                batch_sh = _targets.batch_shardings(mesh, batch)
                key = jax.random.PRNGKey(0)
                ck = _targets.cache_key(name, mesh.shape, p_sh,
                                        batch_sh, batch=batch)
                reports[name] = analysis.lint_hlo(
                    _targets.surrogate_step(model), params, buffers,
                    key, *batch, mesh=mesh,
                    in_shardings=(p_sh, b_sh, repl) + batch_sh,
                    thresholds=thresholds, disable=disable,
                    lower_cache=lower_cache, cache_key=ck,
                    name=f'hlo:{name}')
            except Exception as e:
                errors[name] = repr(e)
                print(f'tpu_lint: --hlo target {name} failed: {e!r}',
                      file=sys.stderr)
    finally:
        _env.set_mesh(prev_mesh)
    return reports, errors


def _run_plan_suite(target_names, chips, *, hbm_gb=None,
                    calibration=None, include_pp=True,
                    max_candidates=None, lower_cache=None):
    """Auto-sharding planner over the built-in targets; returns
    ({name: PlanResult}, {name: error})."""
    from paddle_tpu.analysis import planner
    results, errors = {}, {}
    for name in target_names:
        try:
            results[name] = planner.plan_target(
                name, chips=chips, hbm_budget_gb=hbm_gb,
                calibration=calibration, include_pp=include_pp,
                max_candidates=max_candidates,
                lower_cache=lower_cache)
        except Exception as e:
            errors[name] = repr(e)
            print(f'tpu_lint: --plan target {name} failed: {e!r}',
                  file=sys.stderr)
    return results, errors


def _render_hlo_extras(extras, out=sys.stdout):
    mesh = extras.get('mesh')
    print(f'    mesh={mesh} partitions={extras.get("n_partitions")}',
          file=out)
    census = extras.get('collectives') or {}
    if not census:
        print('    collectives: none', file=out)
    for op, row in sorted(census.items()):
        print(f'    {op}: {row["calls"]} calls, '
              f'{row["bytes"] / (1 << 20):.2f} MiB buffers, '
              f'{row["wire_bytes"] / (1 << 20):.2f} MiB wire, '
              f'~{row["est_us"]:.0f} us (ring, '
              f'{row["group_size"]} devices)', file=out)
    peak = extras.get('peak_bytes')
    budget = extras.get('hbm_budget_bytes')
    if peak is not None:
        line = f'    peak memory: {peak / (1 << 30):.3f} GiB per device'
        if budget is not None:
            line += f' (budget {budget / (1 << 30):.1f} GiB)'
        print(line, file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='tpu_lint',
        description='jaxpr/AST TPU lint: recompile hazards, host '
                    'syncs, sharding & dtype audits.')
    ap.add_argument('paths', nargs='*',
                    help='.py files or directories to AST-lint')
    ap.add_argument('--scope', choices=('traced', 'all'),
                    default='traced',
                    help="'traced' lints only code the framework will "
                         "trace (to_static/jit/forward); 'all' audits "
                         'every function (host step loops)')
    ap.add_argument('--disable', action='append', default=[],
                    metavar='RULE', help='rule id to skip (repeatable)')
    ap.add_argument('--fail-on', choices=_SEVS + ('never',),
                    default='high',
                    help='lowest severity that makes the exit code '
                         'non-zero (default: high)')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable output for CI/bench scripts')
    ap.add_argument('--jaxpr', metavar='MOD:FN',
                    help='additionally deep-lint one callable by '
                         'tracing its jaxpr (imports the module)')
    ap.add_argument('--shapes', metavar='SPEC',
                    help='example shapes for --jaxpr, e.g. '
                         '"8x128xf32,8xi32" (last token is the dtype)')
    ap.add_argument('--fused', type=int, metavar='K', default=None,
                    help='audit the --jaxpr target in its FUSED '
                         'posture (core.scan_loop, fused_steps=K): '
                         'the chunk-break rule flags host '
                         'callbacks/syncs that would force a K-step '
                         'chunk to split back into per-step '
                         'dispatches')
    ap.add_argument('--hlo', action='store_true',
                    help='lowered-HLO SPMD audit: lower step functions '
                         'through the partitioner under a forced mesh '
                         'and run the HLO rules (replicated-giant-hlo, '
                         'collective-cost, resharding, peak-memory). '
                         'Audits the built-in model suite for '
                         'examples//models/ paths and any --jaxpr '
                         'target; no device execution')
    ap.add_argument('--mesh', metavar='SPEC',
                    help='forced mesh axes for --hlo, e.g. "dp=8" or '
                         '"dp=4,tp=2" (virtual CPU devices are created '
                         'as needed; default: all visible devices on '
                         'one dp axis, forcing 8 virtual CPU devices '
                         'when the backend is not already pinned)')
    ap.add_argument('--hbm-gb', type=float, metavar='GiB',
                    help='per-device HBM budget the peak-memory rule '
                         'and the planner gate against (default: 16)')
    ap.add_argument('--plan', action='store_true',
                    help='auto-sharding planner: enumerate candidate '
                         'mesh shapes (dp/tp/pp factorizations of '
                         '--chips) and PartitionSpec assignments for '
                         'the built-in model suite, score each by '
                         'lowering through the partitioner (collective '
                         'wire cost + peak HBM, no execution) and '
                         'print the ranked plans; shares lowerings '
                         'with --hlo')
    ap.add_argument('--chips', type=int, metavar='N',
                    help='device count the planner plans for '
                         '(default: 8 virtual CPU devices)')
    ap.add_argument('--targets', metavar='NAMES',
                    help='comma-separated built-in targets for --plan '
                         '(gpt,widedeep,lenet; default: all)')
    ap.add_argument('--calibration', metavar='FILE',
                    help='measured alpha/beta calibration table '
                         '(tools/calibrate_costmodel.py output) the '
                         'cost model substitutes for its analytic '
                         'defaults')
    ap.add_argument('--max-candidates', type=int, metavar='K',
                    help='cap on lowered plan candidates per target')
    ap.add_argument('--no-pp', action='store_true',
                    help='exclude pipeline (pp>1) layouts from the '
                         'plan enumeration')
    ap.add_argument('--threads', action='store_true',
                    help='concurrency lint instead of the host-sync '
                         'sweep: guarded-by, blocking-under-lock and '
                         'daemon-thread-lifecycle over PATHS (pure '
                         'source analysis, no imports)')
    ap.add_argument('--spmd', action='store_true',
                    help='SPMD contract lint instead of the host-sync '
                         'sweep: rank-dependent-collective, '
                         'collective-order, host-nondeterminism-into-'
                         'trace and unbroadcast-rng over PATHS (pure '
                         'source analysis, no imports)')
    args = ap.parse_args(argv)

    if not args.paths and not args.jaxpr and not args.plan:
        ap.print_usage(sys.stderr)
        print('tpu_lint: nothing to lint (give paths, --jaxpr or '
              '--plan)', file=sys.stderr)
        return 2
    if args.threads and not args.paths:
        ap.print_usage(sys.stderr)
        print('tpu_lint: --threads needs paths to sweep',
              file=sys.stderr)
        return 2
    if args.spmd and not args.paths:
        ap.print_usage(sys.stderr)
        print('tpu_lint: --spmd needs paths to sweep',
              file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f'tpu_lint: no such path: {p}', file=sys.stderr)
            return 2

    mesh_axes = None
    if args.plan and not args.chips:
        args.chips = 8
    if args.hlo or args.plan:
        try:
            mesh_axes = _parse_mesh(args.mesh) if args.mesh else None
        except ValueError as e:
            print(f'tpu_lint: {e}', file=sys.stderr)
            return 2
        # BEFORE the first jax import (analysis pulls jax in)
        _force_mesh_env(mesh_axes, min_devices=args.chips or 0)

    from paddle_tpu import analysis

    report = analysis.LintReport(name='tpu-lint')
    if args.paths:
        if args.threads:
            report.extend(analysis.lint_threads_sources(
                args.paths, disable=args.disable))
        elif args.spmd:
            report.extend(analysis.lint_spmd_sources(
                args.paths, disable=args.disable))
        else:
            report.extend(analysis.lint_sources(
                args.paths, scope=args.scope, disable=args.disable))
    if args.jaxpr:
        try:
            fn = _resolve(args.jaxpr)
        except (ImportError, AttributeError, SystemExit) as e:
            print(f'tpu_lint: cannot resolve --jaxpr: {e}',
                  file=sys.stderr)
            return 2
        try:
            shapes = _parse_shapes(args.shapes) if args.shapes else []
        except (TypeError, ValueError) as e:
            print(f'tpu_lint: cannot parse --shapes: {e}',
                  file=sys.stderr)
            return 2
        report.extend(analysis.lint(fn, *shapes,
                                    disable=args.disable,
                                    fused_steps=args.fused))

    # one lowering memo shared by --plan and --hlo: the same
    # (target, mesh, shardings) triple is compiled exactly once no
    # matter how many surfaces ask for it.  The memo is additionally
    # backed by the PERSISTENT compile cache's text tier
    # (core.compile_cache via hlo.lower_text), so a repeated tpu_lint
    # invocation on unchanged targets reads its candidate modules off
    # disk — the stats delta below lands in --json as `cache_hits`.
    lower_cache = {}
    from paddle_tpu.core import compile_cache as _cc
    _cc_before = _cc.stats()
    plan_results = {}
    plan_error = None
    calibration = None
    if args.calibration:
        from paddle_tpu.analysis import costmodel as _costmodel
        try:
            calibration = _costmodel.load_calibration(args.calibration)
        except (OSError, ValueError) as e:
            print(f'tpu_lint: cannot load --calibration: {e}',
                  file=sys.stderr)
            return 2
    if args.plan:
        from paddle_tpu.analysis import targets as _targets_mod
        names = list(_targets_mod.TARGETS)
        if args.targets:
            names = [t.strip() for t in args.targets.split(',')
                     if t.strip()]
            unknown = [t for t in names
                       if t not in _targets_mod.TARGETS]
            if unknown:
                print(f'tpu_lint: unknown --targets {unknown} '
                      f'(have: {list(_targets_mod.TARGETS)})',
                      file=sys.stderr)
                return 2
        plan_results, plan_errors = _run_plan_suite(
            names, args.chips, hbm_gb=args.hbm_gb,
            calibration=calibration, include_pp=not args.no_pp,
            max_candidates=args.max_candidates,
            lower_cache=lower_cache)
        if plan_errors:
            plan_error = '; '.join(f'{t}: {e}'
                                   for t, e in plan_errors.items())

    hlo_reports = {}
    hlo_error = None
    if args.hlo:
        thresholds = {}
        if args.hbm_gb is not None:     # 0 is a legitimate budget
            thresholds['hbm_bytes'] = int(args.hbm_gb * (1 << 30))
        if calibration is not None:
            thresholds['calibration'] = calibration
        # inside the degrade-don't-discard region: a mesh that cannot
        # be built (e.g. a preset backend with fewer devices than the
        # forced count could create) must not throw away the AST/jaxpr
        # report already in hand
        mesh = None
        try:
            mesh = _build_mesh(mesh_axes)
        except SystemExit as e:
            hlo_error = str(e)
            print(f'{hlo_error} — --hlo audit skipped; AST/jaxpr '
                  'findings below are still valid', file=sys.stderr)
        if mesh is not None and mesh.devices.size <= 1:
            print('tpu_lint: --hlo resolved to a 1-device mesh — the '
                  'SPMD audit is vacuous (nothing is partitioned, no '
                  'collectives exist); pass --mesh, e.g. --mesh dp=8',
                  file=sys.stderr)
        # examples/ + models/ paths -> the built-in target suite (the
        # models those paths train); --jaxpr -> that callable directly.
        # Match whole path components, not substrings (tests/
        # test_models.py is NOT a models/ path).
        wants_suite = any(
            part in ('examples', 'models', 'serving')
            for p in args.paths
            for part in os.path.normpath(os.path.abspath(p))
            .split(os.sep))
        if not wants_suite and not args.jaxpr:
            print('tpu_lint: --hlo has nothing to audit for these '
                  'paths — it lowers the built-in model suite for '
                  'examples//models/ paths or a --jaxpr target; '
                  'AST/jaxpr findings below are NOT an SPMD audit',
                  file=sys.stderr)
        try:
            if wants_suite and mesh is not None:
                from paddle_tpu.analysis import targets as _tmod
                suite_reports, suite_errors = _run_hlo_suite(
                    mesh, list(_tmod.TARGETS), thresholds,
                    args.disable, lower_cache=lower_cache)
                hlo_reports.update(suite_reports)
                if suite_errors:
                    hlo_error = '; '.join(
                        f'{t}: {e}' for t, e in suite_errors.items())
            if args.jaxpr and mesh is not None:
                hlo_reports[args.jaxpr] = analysis.lint_hlo(
                    fn, *shapes, mesh=mesh, thresholds=thresholds,
                    disable=args.disable, name=f'hlo:{args.jaxpr}')
        except Exception as e:
            # do NOT discard the AST/jaxpr report already in hand: a
            # broken lower must not silently disable the rest of the
            # gate (bench's preflight parses stdout JSON regardless of
            # the exit code)
            hlo_error = repr(e)
            print(f'tpu_lint: --hlo audit failed: {hlo_error} — '
                  'AST/jaxpr findings below are still valid',
                  file=sys.stderr)
        for rep in hlo_reports.values():
            report.findings.extend(rep.findings)

    cache_hits = None
    if args.plan or args.hlo:
        after = _cc.stats()
        delta = lambda k: after.get(k, 0) - _cc_before.get(k, 0)  # noqa: E731
        cache_hits = {
            'persistent': delta('hit_hlo'),
            'persistent_misses': delta('miss_hlo'),
            'memo_entries': len(lower_cache),
            'enabled': _cc.enabled(),
        }
        if cache_hits['persistent']:
            print(f'tpu_lint: {cache_hits["persistent"]} lowering(s) '
                  'served from the persistent compile cache',
                  file=sys.stderr)

    if args.json:
        doc = json.loads(report.to_json())
        if args.hlo:
            doc['hlo'] = {n: json.loads(r.to_json())
                          for n, r in hlo_reports.items()}
            if hlo_error:
                doc['hlo_error'] = hlo_error
        if args.plan:
            doc['plan'] = {n: r.to_json()
                           for n, r in plan_results.items()}
            if plan_error:
                doc['plan_error'] = plan_error
        if cache_hits is not None:
            doc['cache_hits'] = cache_hits
        print(json.dumps(doc, indent=2))
    else:
        if args.paths or args.jaxpr:
            print(report.render() if report else report.summary())
        for tname, rep in hlo_reports.items():
            print(f'\n-- hlo audit [{tname}] --')
            _render_hlo_extras(rep.extras)
        for tname, res in plan_results.items():
            print()
            print(res.render())
        if cache_hits is not None and (cache_hits['persistent']
                                       or cache_hits['persistent_misses']):
            print(f'\ncompile cache: {cache_hits["persistent"]} hit / '
                  f'{cache_hits["persistent_misses"]} miss '
                  '(persistent lowering tier)')

    if hlo_error or plan_error:
        return 2
    if args.fail_on == 'never':
        return 0
    return 1 if report.at_least(args.fail_on) else 0


if __name__ == '__main__':
    sys.exit(main())
