#!/usr/bin/env python
"""tpu_lint — sweep Python sources for TPU compilation hazards.

The CLI front of paddle_tpu.analysis: AST-lints files/directories (no
imports, no device, no execution — safe on any tree), and optionally
deep-lints one callable's jaxpr.

    python tools/tpu_lint.py examples/ paddle_tpu/models/
    python tools/tpu_lint.py train.py --scope all       # audit host loops
    python tools/tpu_lint.py examples/ --json           # machine output
    python tools/tpu_lint.py x.py --disable host-sync
    python tools/tpu_lint.py --jaxpr pkg.mod:fn --shapes 8x128xf32,8xi32
    python tools/tpu_lint.py examples/ --hlo --mesh dp=8   # SPMD audit

--hlo escalates to the lowered-HLO SPMD audit (paddle_tpu.analysis.hlo):
each target step is lowered through jax.jit under a FORCED virtual
mesh (--mesh dp=8 / dp=4,tp=2 — CPU devices, no chip touched, no
execution), the compiled post-partitioner module is parsed, and the
HLO rules run: replicated-giant-hlo, collective-cost (ring
byte/latency estimates per all-reduce/all-gather/reduce-scatter/
all-to-all/collective-permute), resharding, peak-memory (liveness
high-water vs --hbm-gb).  For examples/ + paddle_tpu/models/ paths a
built-in suite of representative tiny step functions (GPT dp+tp,
WideDeep, LeNet — the models the examples train) is lowered; --jaxpr
targets are HLO-audited directly.

Exit codes: 0 = no findings at/above --fail-on (default: high),
1 = findings at/above --fail-on, 2 = usage error, or an --hlo
infra failure (mesh build / lower crashed: the text/JSON report is
still printed, with the error under "hlo_error").  CI and bench
scripts consume --json; the tier-1 self-lint gates
(tests/test_analysis.py, tests/test_analysis_hlo.py) run this over
examples/ and paddle_tpu/models/ (AST and --hlo) and require exit 0.

Suppress a finding with `# tpu-lint: disable=<rule-id>` on its line.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_SEVS = ('info', 'warn', 'high')


_DTYPE_TOKENS = {
    'f16': 'float16', 'f32': 'float32', 'f64': 'float64',
    'i8': 'int8', 'i16': 'int16', 'i32': 'int32', 'i64': 'int64',
    'u8': 'uint8', 'u32': 'uint32', 'bool': 'bool',
}


def _parse_shapes(spec):
    """'8x128xf32,8xi32' -> [ShapeDtypeStruct] (last token = dtype;
    short tokens f32/i32/bf16/... or any numpy dtype name)."""
    import numpy as np
    import jax.numpy as jnp
    import jax
    out = []
    for part in spec.split(','):
        toks = part.strip().split('x')
        tok = toks[-1]
        if tok == 'bf16':
            dtype = jnp.bfloat16
        else:
            dtype = np.dtype(_DTYPE_TOKENS.get(tok, tok))
        shape = tuple(int(t) for t in toks[:-1])
        out.append(jax.ShapeDtypeStruct(shape, dtype))
    return out


def _resolve(target):
    import importlib
    mod_name, _, fn_name = target.partition(':')
    if not fn_name:
        raise SystemExit(f'--jaxpr needs module:function, got {target!r}')
    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


# -- the lowered-HLO SPMD audit (--hlo) ---------------------------------------

def _parse_mesh(spec):
    """'dp=8' / 'dp=4,tp=2' -> ordered {axis: size}."""
    axes = {}
    for part in spec.split(','):
        name, _, size = part.strip().partition('=')
        if not size:
            raise ValueError(f'--mesh wants axis=size, got {part!r}')
        axes[name] = int(size)
    return axes


def _force_mesh_env(axes):
    """Make enough virtual devices exist BEFORE jax imports.  The
    audit never executes device code, so CPU host devices are exactly
    as good as chips for lowering through the SPMD partitioner.
    Without --mesh the default is dp=8: forcing 1 device would make
    every SPMD rule silently vacuous."""
    n = 1
    for v in (axes or {'dp': 8}).values():
        n *= v
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags + f' --xla_force_host_platform_device_count={n}'
        ).strip()


def _build_mesh(axes):
    import numpy as np
    import jax
    from jax.sharding import Mesh
    if not axes:
        axes = {'dp': len(jax.devices())}
    n = 1
    for v in axes.values():
        n *= v
    devs = jax.devices()
    if n > len(devs):
        raise SystemExit(
            f'tpu_lint: mesh {axes} wants {n} devices but only '
            f'{len(devs)} exist (is JAX_PLATFORMS set to a fixed '
            'backend before the forced device count could apply?)')
    return Mesh(np.array(devs[:n]).reshape(tuple(axes.values())),
                tuple(axes.keys()))


def _surrogate_step(model):
    """forward + scalar surrogate loss + grad wrt params: the comms /
    sharding / liveness story of a train step without dragging a
    real optimizer into the audit."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.jit import functional_call

    def step(params, buffers, key, *batch):
        def loss_fn(p):
            out, _ = functional_call(model, p, buffers, batch,
                                     key=key, training=True)
            return sum(jnp.square(l.astype(jnp.float32)).mean()
                       for l in jax.tree_util.tree_leaves(out))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        return loss, grads

    return step


def _target_state(model, mesh):
    """(params, buffers) as ShapeDtypeStructs + their shardings (the
    model's declared per-param specs resolved over the mesh — the
    same resolution ParallelTrainer does)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel.api import collect_param_shardings, make_spec
    params, buffers = model.functional_state()
    specs = collect_param_shardings(model)
    p_sh = {n: NamedSharding(mesh, make_spec(specs.get(n), v.ndim, mesh))
            for n, v in params.items()}
    repl = NamedSharding(mesh, P())
    b_sh = {n: repl for n in buffers}
    sds = lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype)  # noqa: E731
    return ({n: sds(v) for n, v in params.items()},
            {n: sds(v) for n, v in buffers.items()}, p_sh, b_sh)


def _hlo_target_gpt(mesh):
    """Tiny GPT in the dp(+tp) posture of examples/gpt_train_generate
    and examples/distributed_hybrid."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig
    paddle.seed(0)
    model = GPT(GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                          num_heads=4, max_seq_len=32, dropout=0.0))
    return model, (_ids_batch(mesh, (8, 16), 128),)


def _hlo_target_widedeep(mesh):
    """WideDeep sparse-gather model (paddle_tpu/models/widedeep)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.widedeep import WideDeep
    paddle.seed(0)
    model = WideDeep([16, 16, 16, 16], dense_dim=4, embed_dim=8,
                     shard_vocab=False)
    import jax
    import jax.numpy as jnp
    return model, (_ids_batch(mesh, (8, 4), 16),
                   jax.ShapeDtypeStruct((8, 4), jnp.float32))


def _hlo_target_lenet(mesh):
    """LeNet vision path of examples/mnist_lenet."""
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import LeNet
    import jax
    import jax.numpy as jnp
    paddle.seed(0)
    model = LeNet()
    return model, (jax.ShapeDtypeStruct((8, 1, 28, 28), jnp.float32),)


def _ids_batch(mesh, shape, vocab):
    import jax
    import jax.numpy as jnp
    del mesh, vocab     # shapes only: lowering never reads values
    return jax.ShapeDtypeStruct(shape, jnp.int32)


# target name -> builder(mesh) -> (model, example_batch); the suite
# proxies what examples/ + paddle_tpu/models/ actually train
_HLO_TARGETS = {
    'gpt': _hlo_target_gpt,
    'widedeep': _hlo_target_widedeep,
    'lenet': _hlo_target_lenet,
}


def _run_hlo_suite(mesh, targets, thresholds, disable):
    """Lower + audit each target; returns {name: LintReport}."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu import analysis
    from paddle_tpu.distributed import env as _env
    reports, errors = {}, {}
    prev_mesh = _env.get_mesh()
    _env.set_mesh(mesh)     # model-internal maybe_shard constraints live
    try:
        first_axis = next((a for a in mesh.axis_names
                           if mesh.shape[a] > 1), None)
        for name in targets:
            # per-target isolation: one broken lower must not discard
            # the audits of the targets that DO lower
            try:
                model, batch = _HLO_TARGETS[name](mesh)
                params, buffers, p_sh, b_sh = _target_state(model, mesh)
                repl = NamedSharding(mesh, P())
                batch_sh = tuple(
                    NamedSharding(mesh, P(first_axis))
                    if first_axis is not None and b.shape
                    and b.shape[0] % mesh.shape[first_axis] == 0
                    else repl
                    for b in batch)
                key = jax.random.PRNGKey(0)
                reports[name] = analysis.lint_hlo(
                    _surrogate_step(model), params, buffers, key,
                    *batch, mesh=mesh,
                    in_shardings=(p_sh, b_sh, repl) + batch_sh,
                    thresholds=thresholds, disable=disable,
                    name=f'hlo:{name}')
            except Exception as e:
                errors[name] = repr(e)
                print(f'tpu_lint: --hlo target {name} failed: {e!r}',
                      file=sys.stderr)
    finally:
        _env.set_mesh(prev_mesh)
    return reports, errors


def _render_hlo_extras(extras, out=sys.stdout):
    mesh = extras.get('mesh')
    print(f'    mesh={mesh} partitions={extras.get("n_partitions")}',
          file=out)
    census = extras.get('collectives') or {}
    if not census:
        print('    collectives: none', file=out)
    for op, row in sorted(census.items()):
        print(f'    {op}: {row["calls"]} calls, '
              f'{row["bytes"] / (1 << 20):.2f} MiB buffers, '
              f'{row["wire_bytes"] / (1 << 20):.2f} MiB wire, '
              f'~{row["est_us"]:.0f} us (ring, '
              f'{row["group_size"]} devices)', file=out)
    peak = extras.get('peak_bytes')
    budget = extras.get('hbm_budget_bytes')
    if peak is not None:
        line = f'    peak memory: {peak / (1 << 30):.3f} GiB per device'
        if budget is not None:
            line += f' (budget {budget / (1 << 30):.1f} GiB)'
        print(line, file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog='tpu_lint',
        description='jaxpr/AST TPU lint: recompile hazards, host '
                    'syncs, sharding & dtype audits.')
    ap.add_argument('paths', nargs='*',
                    help='.py files or directories to AST-lint')
    ap.add_argument('--scope', choices=('traced', 'all'),
                    default='traced',
                    help="'traced' lints only code the framework will "
                         "trace (to_static/jit/forward); 'all' audits "
                         'every function (host step loops)')
    ap.add_argument('--disable', action='append', default=[],
                    metavar='RULE', help='rule id to skip (repeatable)')
    ap.add_argument('--fail-on', choices=_SEVS + ('never',),
                    default='high',
                    help='lowest severity that makes the exit code '
                         'non-zero (default: high)')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable output for CI/bench scripts')
    ap.add_argument('--jaxpr', metavar='MOD:FN',
                    help='additionally deep-lint one callable by '
                         'tracing its jaxpr (imports the module)')
    ap.add_argument('--shapes', metavar='SPEC',
                    help='example shapes for --jaxpr, e.g. '
                         '"8x128xf32,8xi32" (last token is the dtype)')
    ap.add_argument('--hlo', action='store_true',
                    help='lowered-HLO SPMD audit: lower step functions '
                         'through the partitioner under a forced mesh '
                         'and run the HLO rules (replicated-giant-hlo, '
                         'collective-cost, resharding, peak-memory). '
                         'Audits the built-in model suite for '
                         'examples//models/ paths and any --jaxpr '
                         'target; no device execution')
    ap.add_argument('--mesh', metavar='SPEC',
                    help='forced mesh axes for --hlo, e.g. "dp=8" or '
                         '"dp=4,tp=2" (virtual CPU devices are created '
                         'as needed; default: all visible devices on '
                         'one dp axis, forcing 8 virtual CPU devices '
                         'when the backend is not already pinned)')
    ap.add_argument('--hbm-gb', type=float, metavar='GiB',
                    help='per-device HBM budget the peak-memory rule '
                         'gates against (default: 16)')
    args = ap.parse_args(argv)

    if not args.paths and not args.jaxpr:
        ap.print_usage(sys.stderr)
        print('tpu_lint: nothing to lint (give paths or --jaxpr)',
              file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f'tpu_lint: no such path: {p}', file=sys.stderr)
            return 2

    mesh_axes = None
    if args.hlo:
        try:
            mesh_axes = _parse_mesh(args.mesh) if args.mesh else None
        except ValueError as e:
            print(f'tpu_lint: {e}', file=sys.stderr)
            return 2
        # BEFORE the first jax import (analysis pulls jax in)
        _force_mesh_env(mesh_axes)

    from paddle_tpu import analysis

    report = analysis.LintReport(name='tpu-lint')
    if args.paths:
        report.extend(analysis.lint_sources(
            args.paths, scope=args.scope, disable=args.disable))
    if args.jaxpr:
        try:
            fn = _resolve(args.jaxpr)
        except (ImportError, AttributeError, SystemExit) as e:
            print(f'tpu_lint: cannot resolve --jaxpr: {e}',
                  file=sys.stderr)
            return 2
        try:
            shapes = _parse_shapes(args.shapes) if args.shapes else []
        except (TypeError, ValueError) as e:
            print(f'tpu_lint: cannot parse --shapes: {e}',
                  file=sys.stderr)
            return 2
        report.extend(analysis.lint(fn, *shapes,
                                    disable=args.disable))

    hlo_reports = {}
    hlo_error = None
    if args.hlo:
        thresholds = {}
        if args.hbm_gb is not None:     # 0 is a legitimate budget
            thresholds['hbm_bytes'] = int(args.hbm_gb * (1 << 30))
        # inside the degrade-don't-discard region: a mesh that cannot
        # be built (e.g. a preset backend with fewer devices than the
        # forced count could create) must not throw away the AST/jaxpr
        # report already in hand
        mesh = None
        try:
            mesh = _build_mesh(mesh_axes)
        except SystemExit as e:
            hlo_error = str(e)
            print(f'{hlo_error} — --hlo audit skipped; AST/jaxpr '
                  'findings below are still valid', file=sys.stderr)
        if mesh is not None and mesh.devices.size <= 1:
            print('tpu_lint: --hlo resolved to a 1-device mesh — the '
                  'SPMD audit is vacuous (nothing is partitioned, no '
                  'collectives exist); pass --mesh, e.g. --mesh dp=8',
                  file=sys.stderr)
        # examples/ + models/ paths -> the built-in target suite (the
        # models those paths train); --jaxpr -> that callable directly.
        # Match whole path components, not substrings (tests/
        # test_models.py is NOT a models/ path).
        wants_suite = any(
            part in ('examples', 'models')
            for p in args.paths
            for part in os.path.normpath(os.path.abspath(p))
            .split(os.sep))
        if not wants_suite and not args.jaxpr:
            print('tpu_lint: --hlo has nothing to audit for these '
                  'paths — it lowers the built-in model suite for '
                  'examples//models/ paths or a --jaxpr target; '
                  'AST/jaxpr findings below are NOT an SPMD audit',
                  file=sys.stderr)
        try:
            if wants_suite and mesh is not None:
                suite_reports, suite_errors = _run_hlo_suite(
                    mesh, list(_HLO_TARGETS), thresholds,
                    args.disable)
                hlo_reports.update(suite_reports)
                if suite_errors:
                    hlo_error = '; '.join(
                        f'{t}: {e}' for t, e in suite_errors.items())
            if args.jaxpr and mesh is not None:
                hlo_reports[args.jaxpr] = analysis.lint_hlo(
                    fn, *shapes, mesh=mesh, thresholds=thresholds,
                    disable=args.disable, name=f'hlo:{args.jaxpr}')
        except Exception as e:
            # do NOT discard the AST/jaxpr report already in hand: a
            # broken lower must not silently disable the rest of the
            # gate (bench's preflight parses stdout JSON regardless of
            # the exit code)
            hlo_error = repr(e)
            print(f'tpu_lint: --hlo audit failed: {hlo_error} — '
                  'AST/jaxpr findings below are still valid',
                  file=sys.stderr)
        for rep in hlo_reports.values():
            report.findings.extend(rep.findings)

    if args.json:
        doc = json.loads(report.to_json())
        if args.hlo:
            doc['hlo'] = {n: json.loads(r.to_json())
                          for n, r in hlo_reports.items()}
            if hlo_error:
                doc['hlo_error'] = hlo_error
        print(json.dumps(doc, indent=2))
    else:
        print(report.render() if report else report.summary())
        for tname, rep in hlo_reports.items():
            print(f'\n-- hlo audit [{tname}] --')
            _render_hlo_extras(rep.extras)

    if hlo_error:
        return 2
    if args.fail_on == 'never':
        return 0
    return 1 if report.at_least(args.fail_on) else 0


if __name__ == '__main__':
    sys.exit(main())
