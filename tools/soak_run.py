#!/usr/bin/env python
"""soak_run — property-based multi-process chaos soaks.

The executable half of paddle_tpu.resilience.{chaos.ChaosCluster,
plangen, watchdog}: generate a seeded, legal FaultPlan, spin a TRUE
multi-process cluster (N worker interpreters + elastic supervisor +
shared-filesystem KV collective transport), inject the plan, and gate
on invariants I1-I7:

    I1  restore() only ever yields a committed, verifiable step
    I2  committed steps are monotonic (modulo explicit restores)
    I3  every restore landed on a committed step
    I4  preemptions exited PREEMPTED_EXIT_CODE (117)
    I5  restarts stayed within the failure budget
    I6  no step is published twice after a restart without an
        intervening restore below it
    I7  the cluster completes (or exits preempted) within the
        deadline budget — it never deadlocks
    +   every rank's final state equals the uninterrupted reference
        (the workload is a pure function of the step index)

Usage:

    python tools/soak_run.py --procs 2 --seed 7 --steps 50   # one soak
    python tools/soak_run.py ... --once                # skip the
                                                       # same-seed
                                                       # replay check
    python tools/soak_run.py ... --break I6 --shrink   # deliberately
        # break an invariant, then shrink the failing plan to a
        # minimal reproducer and emit it as a pytest regression case
    python tools/soak_run.py --smoke --json            # CI gate:
        # golden plan/shrinker fixtures + one 2-process cluster spin

The default run executes the SAME seed twice and asserts the injected
fault sequences are identical per rank — the replayability contract.

Worker mode (internal, spawned by ChaosCluster): ``--worker``.
Exit code 0 iff every gate held.
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

GOLDENS = os.path.join(_REPO, 'tools', 'soak_goldens.json')

# the built-in smoke plan: one hung collective (the watchdog/abort
# path), one hard kill (crash recovery), one graceful preemption (the
# 117 path — folds the old chaos_run driver coverage), one torn
# manifest write (commit protocol).  Seeded; 2 processes; 12 steps.
SMOKE_PLAN = {
    'seed': 7,
    'name': 'cluster-smoke',
    'faults': [
        {'kind': 'collective_hang', 'at_step': 4, 'rank': 1,
         'delay_s': 30.0},
        {'kind': 'sigkill', 'at_step': 6, 'rank': 0},
        {'kind': 'sigterm', 'at_step': 9, 'rank': 1},
        # count=2 tears the shard AND the 2PC intent of ONE save
        # attempt; the replayed save after the next restart commits —
        # torn-then-recover, with a bounded, replay-stable sequence
        {'kind': 'torn_write', 'path': 'step_8', 'count': 2},
    ],
}


def _final_w(steps, world=1, quant=False):
    """The workload's exact final state: w_i = mean over `world`
    copies of (0.9*w_{i-1} + i), float32 throughout — pure in
    (step index, world), so ANY fault schedule that lets the cluster
    finish must reproduce it bit-for-bit on every rank.  The per-step
    mean IS part of the arithmetic: np.mean accumulates f32, and the
    sum of three identical f32 values rounds (3a needs up to 26
    mantissa bits), so mean-of-identical-replicas is only bitwise
    identity at power-of-two world sizes — the reference replays the
    exact collective the workers run instead of assuming it away.

    ``quant`` replays the quantized wire: each rank's contribution
    round-trips through the SAME deterministic block quantizer the
    transport frames with, BEFORE the mean — the quantized soak's
    bit-exact reference (host quantization is pure in the payload, so
    restarts replay it identically)."""
    import numpy as np
    w = np.arange(8.0, dtype='float32')
    for i in range(1, steps + 1):
        w = (w * np.float32(0.9)
             + np.float32(i) * np.ones(8, dtype='float32'))
        if world > 1:
            if quant:
                from paddle_tpu.distributed.collective import (
                    _frame_quant, _unframe)
                w = _unframe(_frame_quant(w), 'ref', 'ref', 0)
            w = np.stack([w] * world).mean(axis=0).astype(np.float32)
    return w


class _SoakMigration:
    """Duck-typed planner result/candidate for the soak workload: the
    host loop has no model to shard, so the 'winning plan' is just the
    world laid out as pure DP — what matters is exercising the full
    actuation seam (classify -> ladder -> coordinated-reshape
    restart), not the sharding math the in-process TrainerHost owns."""

    def __init__(self, world):
        self.mesh_axes = {'dp': int(world)}
        self.assignment = 'reshape'
        self.score_us = 1.0
        self.candidates = [self]
        self.fallbacks = []

    @property
    def winner(self):
        return self


class SoakHost:
    """Rank-0 supervisor host for the soak cluster: the swap under
    test is the CLUSTER seam — a durable ``reshape_request.json`` the
    elastic watch loop answers with a coordinated restart (no
    max_restarts burn, same posture as preemptions).

    The request FILE doubles as the cluster-lifetime exactly-once
    ledger: it survives the very restart it causes, so when the
    injected drift re-fires in the next incarnation (the chaos fault
    ledger flushes best-effort and can lose the record to the
    restart's SIGTERM) the ladder holds at ``request_swap`` instead of
    reshape-looping the cluster."""

    def __init__(self, workdir, world):
        self.workdir = workdir
        self.world = int(world)

    def calibration(self):
        return None

    def healthy_devices(self, incident):
        return list(range(self.world))

    def incumbent(self):
        return None, None

    def replan(self, devices, calibration):
        return _SoakMigration(len(devices))

    def precompile(self, plan, devices):
        pass            # nothing to compile on the host-loop path

    def request_swap(self, plan, devices, incident):
        from paddle_tpu import telemetry
        from paddle_tpu.resilience.supervisor import (
            read_reshape_request, write_reshape_request)
        if read_reshape_request(self.workdir) is not None:
            return False        # this cluster already actuated once
        seq = write_reshape_request(
            self.workdir, mesh=plan.mesh_axes,
            env={'PADDLE_TPU_SOAK_RESHAPED': '1'},
            reason=incident.get('trigger'))
        telemetry.event('plan_swap', seq=seq,
                        to_mesh=dict(plan.mesh_axes),
                        assignment=plan.assignment,
                        trigger=incident.get('trigger'),
                        policy=incident.get('policy'))
        # the restart this request triggers SIGTERMs us before the
        # JSONL buffer necessarily flushes: dump the flight ring so
        # load_run_events still sees the swap (and the fault ledger)
        telemetry.dump_flight(os.path.join(
            self.workdir, f'flightrec-reshape-{os.getpid()}.json'))
        return True


# =============================================================================
# worker (one rank of the ChaosCluster)
# =============================================================================

def worker_main():
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    jaxdist = (os.environ.get('PADDLE_TPU_SOAK_JAXDIST') == '1'
               and os.environ.get('PADDLE_TPU_SOAK_COORD'))
    if jaxdist:
        # must precede ANY jax computation (backend init): first
        # thing, before paddle_tpu pulls jax in.  A
        # jax.distributed-initialized cluster (clean soaks and real
        # pods; the coordination service cannot re-admit a SIGKILLed
        # task, so kill-plans run without it — the FileKVStore
        # transport carries the collectives either way.)
        import jax
        jax.distributed.initialize(
            coordinator_address=os.environ['PADDLE_TPU_SOAK_COORD'],
            num_processes=int(os.environ.get('PADDLE_TRAINERS_NUM',
                                             '1')),
            process_id=int(os.environ.get('PADDLE_TRAINER_ID', '0')),
            initialization_timeout=60)
    import numpy as np
    from paddle_tpu import telemetry
    from paddle_tpu.distributed.checkpoint import (
        save_host_shard, load_host_shard, latest_committed_step)
    from paddle_tpu.distributed.collective import (
        HostCollectives, CollectiveTimeout, CollectivePayloadError,
        CoordinatedAbort)
    from paddle_tpu.resilience import (
        install_shutdown, shutdown_requested, PREEMPTED_EXIT_CODE,
        CommitBarrierTimeout, WATCHDOG_EXIT_CODE)
    from paddle_tpu.resilience.chaos import (
        ChaosEngine, plan_from_env, load_run_events)
    from paddle_tpu.resilience.watchdog import Budget, Watchdog

    workdir = os.environ['PADDLE_TPU_CHAOS_DIR']
    steps = int(os.environ.get('PADDLE_TPU_CHAOS_STEPS', '12'))
    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    world = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    save_every = int(os.environ.get('PADDLE_TPU_SOAK_SAVE_EVERY', '2'))
    coll_t = float(os.environ.get(
        'PADDLE_TPU_SOAK_COLLECTIVE_TIMEOUT', '30'))
    barrier_t = float(os.environ.get(
        'PADDLE_TPU_SOAK_BARRIER_TIMEOUT', '20'))
    break_mode = os.environ.get('PADDLE_TPU_SOAK_BREAK', '')
    incarnation = (int(os.environ.get('PADDLE_ELASTIC_RESTART_COUNT',
                                      '0'))
                   + int(os.environ.get('PADDLE_ELASTIC_PREEMPT_COUNT',
                                        '0'))
                   + int(os.environ.get('PADDLE_ELASTIC_RESHAPE_COUNT',
                                        '0')))
    # cluster-obs runs flush at a short cadence so stats frames carry
    # fresh rolling windows even on short soaks
    flush_every = int(os.environ.get('PADDLE_TPU_SOAK_FLUSH', '8'))
    telemetry.enable(os.path.join(workdir, 'telemetry'),
                     flush_interval=flush_every)

    if jaxdist:
        import jax
        telemetry.event('run_meta', jax_distributed=True,
                        process_count=jax.process_count())

    plan = plan_from_env()
    engine = None
    if plan is not None:
        mine = plan.slice_for_rank(rank)
        if incarnation:
            # replay the fault ledger: one-shot faults a previous
            # incarnation already injected must not re-fire on the
            # replayed steps (a restarted worker re-killing itself at
            # the same step forever), while not-yet-reached faults
            # still do
            mine.mark_fired(load_run_events(workdir), rank=rank)
        engine = ChaosEngine(mine, rank=rank).activate()

    # the quantized-wire coverage class: every all-reduce below ships
    # block-scaled int8 + scales through the same crc frame — the
    # fault seams (corrupt-after-crc, SIGKILL mid-allreduce, hangs)
    # then exercise the quantized payload path.  quant_min_bytes=0:
    # the 8-float workload array must actually quantize.
    quant = os.environ.get('PADDLE_TPU_SOAK_QUANT') or None
    transport = HostCollectives(rank=rank, world=world,
                                timeout_s=coll_t,
                                quant=quant, quant_min_bytes=0)
    transport.clear_abort()
    budget = Budget.from_env(os.environ.get('PADDLE_TPU_WATCHDOG'))
    wd = None
    if budget is not None:
        wd = Watchdog(budget=budget, name='soak',
                      transport=transport, flight_dir=workdir).start()
    install_shutdown()

    # -- live cluster observability plane (default OFF) ------------------
    # every rank publishes stats frames over the SAME KV transport the
    # collectives ride; rank 0 aggregates + serves /cluster/status.json
    # on an ephemeral port recorded in <workdir>/cluster_port.json.
    # The per-step compute-vs-collective wall split feeds the frames
    # (via the step accumulator's extra columns) so the aggregator can
    # attribute a throttled rank: in a BSP step every rank's TOTAL time
    # equalizes through the allreduce barrier — only the straggler's
    # COMPUTE half inflates.
    from paddle_tpu.telemetry.cluster import (
        resolve_cluster_stats, enable_cluster_plane)
    import time as _time
    plane = None
    acc = None
    cs_interval = resolve_cluster_stats()
    if cs_interval is not None:
        plane = enable_cluster_plane(
            transport=transport, interval_s=cs_interval,
            serve=(True if rank == 0 else False),
            stale_after_s=float(os.environ.get(
                'PADDLE_TPU_SOAK_STALE_AFTER', '3.0')))
        if rank == 0 and plane.port is not None:
            from paddle_tpu.resilience.manifest import atomic_write
            atomic_write(
                os.path.join(workdir, 'cluster_port.json'),
                lambda f: f.write(json.dumps(
                    {'port': plane.port, 'pid': os.getpid(),
                     'incarnation': incarnation})))
        acc = telemetry.step_accumulator('soak',
                                         flush_interval=flush_every)

    # -- self-healing plan supervisor (default OFF) ----------------------
    # ChaosCluster(supervisor=...) arms it via PADDLE_TPU_SUPERVISOR.
    # Rank 0 runs the actuator against the SoakHost: the ladder's swap
    # rung writes the coordinated-reshape request the elastic watch
    # loop (reshape_dir=workdir) answers with a whole-cluster restart.
    from paddle_tpu.resilience.supervisor import (
        resolve_supervisor, PlanSupervisor)
    sup = None
    sup_cfg = resolve_supervisor(None)
    if sup_cfg is not None and rank == 0:
        sup = PlanSupervisor(SoakHost(workdir, world=world),
                             sup_cfg).start()

    ckpt = os.path.join(workdir, 'ckpt')
    w = np.arange(8.0, dtype=np.float32)
    start = 1
    latest = latest_committed_step(ckpt)
    if latest >= 0:
        shard = load_host_shard(ckpt, latest, rank)
        if shard is not None:
            w = shard['w'].astype(np.float32)
            start = latest + 1
            telemetry.event('checkpoint_restore', step=latest,
                            host=rank)
    if break_mode == 'I6' and incarnation and latest >= 0:
        # the DELIBERATE bug --break I6 asks for: republish the step
        # we just restored without rolling back below it — exactly
        # the double-publish invariant I6 exists to catch
        from paddle_tpu.resilience import manifest as _m
        _m.write_manifest(os.path.join(ckpt, f'step_{latest}'),
                          step=latest)
        telemetry.event('checkpoint_commit', step=latest, host=rank)

    def abort_exit(exc):
        telemetry.event('coordinated_abort', rank=rank,
                        reason=repr(exc)[:200])
        transport.request_abort(repr(exc))
        telemetry.dump_flight(os.path.join(
            workdir, f'flightrec-abort-r{rank}-{os.getpid()}.json'))
        if wd is not None:
            wd.stop()
        sys.exit(WATCHDOG_EXIT_CODE)

    try:
        for i in range(start, steps + 1):
            if wd is not None:
                wd.step_started(i, first=(i == start))
            transport.note_step(i)  # ledger entries tagged by step
            _t0 = _time.perf_counter()
            if engine is not None:
                engine.step(i)      # may SIGKILL/SIGTERM/throttle us
            if shutdown_requested():
                # preemption beats everything else this step could do:
                # a latched SIGTERM must exit 117 BEFORE a collective
                # timeout (a peer already gone) can reclassify this
                # clean preemption as a watchdog abort
                telemetry.dump_flight(os.path.join(
                    workdir, f'flightrec-preempt-r{rank}-{i}.json'))
                if wd is not None:
                    wd.stop()
                sys.exit(PREEMPTED_EXIT_CODE)
            w = (w * np.float32(0.9)
                 + np.float32(i) * np.ones(8, np.float32))
            _t_coll = _time.perf_counter()
            try:
                w = transport.allreduce(w, 'mean', tag=f'step{i}')
            except (CollectiveTimeout, CollectivePayloadError) as e:
                abort_exit(e)
            except CoordinatedAbort:
                telemetry.dump_flight(os.path.join(
                    workdir,
                    f'flightrec-abort-r{rank}-{os.getpid()}.json'))
                if wd is not None:
                    wd.stop()
                sys.exit(WATCHDOG_EXIT_CODE)
            _t_end = _time.perf_counter()
            if acc is not None:
                # compute = injected throttle + local update (the
                # straggler's inflated half); coll = barrier wait +
                # wire (the WAITERS' inflated half)
                acc.observe(step=i, step_time_s=_t_end - _t0,
                            loss=float(w[0]),
                            compute_ms=(_t_coll - _t0) * 1000.0,
                            coll_ms=(_t_end - _t_coll) * 1000.0)
            if i % save_every == 0:
                try:
                    save_host_shard(ckpt, i, rank,
                                    {'w': w,
                                     'step': np.asarray(i)},
                                    num_hosts=world,
                                    barrier_timeout=barrier_t)
                except CommitBarrierTimeout:
                    # an ack never arrived (peer died mid-step): the
                    # dir stays uncommitted and is swept later — the
                    # run continues on the previous committed step
                    pass
                except OSError as e:
                    # EIO/ENOSPC on a shard/intent write: a save is
                    # best-effort — losing one checkpoint must not
                    # kill training (restore falls back to the
                    # previous committed step); the file seam's
                    # io_error faults land here
                    telemetry.event('checkpoint_quarantine', step=i,
                                    host=rank, error=repr(e)[:200])
            if wd is not None:
                wd.step_finished(i)
            if shutdown_requested():
                telemetry.dump_flight(os.path.join(
                    workdir, f'flightrec-preempt-r{rank}-{i}.json'))
                if wd is not None:
                    wd.stop()
                sys.exit(PREEMPTED_EXIT_CODE)
    finally:
        if sup is not None:
            sup.stop(timeout=1.0)
        if acc is not None:
            acc.flush()
        if plane is not None:
            plane.close()       # publishes the final frame itself
        if wd is not None:
            wd.stop()
    with open(os.path.join(workdir, f'out_r{rank}.json'), 'w') as f:
        json.dump({'final_w': np.asarray(w).tolist(),
                   'final_step': steps,
                   'incarnation': incarnation}, f)
    return 0


# =============================================================================
# drivers
# =============================================================================

def _norm_sequence(report):
    """Per-rank injected sequences (cross-rank interleaving is
    timing-dependent; per-rank order is the deterministic contract)."""
    by_rank = {}
    for e in report['injected']:
        by_rank.setdefault(e.get('rank', 0), []).append(
            (e.get('fault'), e.get('step'), e.get('op')))
    return {r: v for r, v in sorted(by_rank.items())}


def _check_finals(report, steps, quant=False):
    import numpy as np
    ref = _final_w(steps, world=report.get('procs', 1), quant=quant)
    bad = []
    for r, doc in sorted(report.get('finals', {}).items()):
        if not np.array_equal(
                np.asarray(doc['final_w'], dtype=np.float32), ref):
            bad.append(f'rank {r} final state differs from the '
                       'uninterrupted reference')
    return bad


def run_soak(args, plan=None, workdir=None, extra_env=None):
    from paddle_tpu.resilience.chaos import ChaosCluster
    from paddle_tpu.resilience import plangen
    quant = bool(getattr(args, 'quant_wire', False))
    sup = getattr(args, 'supervisor', None) or None
    if plan is None:
        plan = plangen.generate_plan(
            args.seed, args.steps, args.procs, n_faults=args.faults,
            save_every=args.save_every,
            hang_s=4 * args.collective_timeout,
            quant_wire=quant, supervisor=bool(sup))
    if quant:
        extra_env = dict(extra_env or {},
                         PADDLE_TPU_SOAK_QUANT='int8')
    cluster = ChaosCluster(
        procs=args.procs, plan=plan, steps=args.steps,
        workdir=workdir, save_every=args.save_every,
        collective_timeout_s=args.collective_timeout,
        barrier_timeout_s=args.barrier_timeout,
        watchdog=args.watchdog, deadline_s=args.deadline,
        max_restarts=args.max_restarts,
        jax_distributed=args.jax_distributed,
        supervisor=sup, extra_env=extra_env)
    report = cluster.run()
    report['quant_wire'] = quant
    report['violations'] += _check_finals(report, args.steps,
                                          quant=quant) \
        if report['rc'] == 0 else []
    report['ok'] = not report['violations']
    return report, plan


def cmd_soak(args):
    """One (or, default, two — replay-verified) seeded soaks."""
    report, plan = run_soak(args)
    reports = [report]
    if not args.once:
        replay, _ = run_soak(args, plan=plan)
        reports.append(replay)
        a, b = _norm_sequence(report), _norm_sequence(replay)
        if a != b:
            report['violations'].append(
                'same seed did NOT reproduce the identical injected '
                f'sequence: {a} vs {b}')
            report['ok'] = False
        else:
            report['replay_identical'] = True
    out = dict(report)
    out['plan_kinds'] = [f['kind'] for f in out['plan']['faults']]
    if args.json:
        print(json.dumps(out, indent=1, sort_keys=True, default=str))
    else:
        print(f'soak: seed={args.seed} procs={args.procs} '
              f'steps={args.steps} faults={len(plan.faults)} '
              f'({", ".join(sorted(set(out["plan_kinds"])))})')
        for e in report['injected']:
            print(f'  injected: {e}')
        print(f'  incarnations={report["incarnations"]} '
              f'rc={report["rc"]} in {report["duration_s"]}s')
        if report.get('replay_identical'):
            print('  replay: identical injected sequence (seed '
                  f'{args.seed})')
        if report['ok']:
            print('  all invariants I1-I7 held')
        else:
            for v in report['violations']:
                print(f'  VIOLATION: {v}')
    return 0 if report['ok'] else 1


def cmd_shrink(args):
    """Break an invariant on purpose (or take a failing plan), shrink
    to the minimal reproducer, emit a regression test."""
    from paddle_tpu.resilience import plangen
    extra = {'PADDLE_TPU_SOAK_BREAK': args.break_invariant} \
        if args.break_invariant else None

    def failing(candidate):
        rep, _ = run_soak(args, plan=candidate, extra_env=extra)
        return not rep['ok']

    plan = plangen.generate_plan(
        args.seed, args.steps, args.procs, n_faults=args.faults,
        save_every=args.save_every,
        hang_s=4 * args.collective_timeout)
    print(f'shrink: initial plan has {len(plan.faults)} fault(s); '
          f'oracle = invariants under '
          f'{"--break " + args.break_invariant if args.break_invariant else "the plan"}')
    shrunk, runs = plangen.shrink(plan, failing,
                                  max_runs=args.max_shrink_runs,
                                  log=lambda m: print(f'  {m}'))
    path = args.emit_regression or os.path.join(
        os.getcwd(), 'test_chaos_regression.py')
    plangen.emit_regression(
        shrunk, path, procs=args.procs, steps=args.steps,
        violations=[f'deliberate --break {args.break_invariant}']
        if args.break_invariant else (),
        collective_timeout_s=args.collective_timeout,
        deadline_s=args.deadline)
    doc = {'initial_faults': len(plan.faults),
           'shrunk_faults': len(shrunk.faults),
           'shrunk_plan': json.loads(shrunk.to_json()),
           'oracle_runs': runs,
           'regression_test': path}
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(f'shrunk {len(plan.faults)} -> {len(shrunk.faults)} '
              f'fault(s) in {runs} oracle run(s)')
        for f in shrunk.faults:
            print(f'  {f}')
        print(f'regression test written to {path}')
    return 0


def cmd_smoke(args):
    """The CI gate: golden plan + golden shrunk-plan fixtures (the
    generator and the shrinker cannot drift silently), then ONE
    2-process ChaosCluster spin of the built-in smoke plan (hung
    collective -> watchdog abort, SIGKILL recovery, SIGTERM
    preemption, torn manifest — folds the old chaos_run subprocess
    driver coverage)."""
    from paddle_tpu.resilience import plangen
    from paddle_tpu.resilience.chaos import FaultPlan
    failures = []
    with open(GOLDENS) as f:
        gold = json.load(f)

    g = gold['plan_seed7']
    plan7 = plangen.generate_plan(7, g['steps'], g['procs'],
                                  save_every=g['save_every'],
                                  hang_s=g['hang_s'])
    fp = plangen.plan_fingerprint(plan7)
    if fp != g['fingerprint']:
        failures.append(
            f'generate_plan(seed=7) drifted: fingerprint {fp} != '
            f'golden {g["fingerprint"]} '
            f'(kinds now {[f.kind for f in plan7.faults]})')
    for kind in ('collective_hang', 'sigkill', 'torn_write'):
        if kind not in [f.kind for f in plan7.faults]:
            failures.append(f'seed-7 plan lost required kind {kind}')

    gs = gold['shrink_demo']

    def canned_oracle(candidate):
        kinds = [f.kind for f in candidate.faults]
        return 'sigkill' in kinds and 'torn_write' in kinds

    shrunk, runs = plangen.shrink(plan7, canned_oracle)
    sfp = plangen.plan_fingerprint(shrunk)
    if sfp != gs['fingerprint'] or \
            len(shrunk.faults) != gs['n_faults']:
        failures.append(
            f'shrinker drifted: {len(shrunk.faults)} fault(s) '
            f'fingerprint {sfp} != golden {gs["n_faults"]}/'
            f'{gs["fingerprint"]}')

    cluster_report = None
    if not args.no_cluster:
        smoke_args = argparse.Namespace(
            seed=7, procs=2, steps=12, faults=4, save_every=2,
            collective_timeout=5.0, barrier_timeout=10.0,
            watchdog='step=60,grace=2', deadline=180.0,
            max_restarts=6, jax_distributed=False)
        cluster_report, _ = run_soak(
            smoke_args, plan=FaultPlan.from_json(
                json.dumps(SMOKE_PLAN)))
        if not cluster_report['ok']:
            failures += [f'cluster smoke: {v}'
                         for v in cluster_report['violations']]
        injected_kinds = {e.get('fault')
                          for e in cluster_report['injected']}
        for kind in ('collective_hang', 'sigkill', 'sigterm',
                     'torn_write'):
            if kind not in injected_kinds:
                failures.append(
                    f'cluster smoke never injected {kind} '
                    f'(got {sorted(injected_kinds)})')

    doc = {'ok': not failures, 'failures': failures,
           'plan_fingerprint': fp, 'shrunk_fingerprint': sfp,
           'oracle_runs': runs}
    if cluster_report is not None:
        doc['cluster'] = {k: cluster_report.get(k) for k in
                          ('ok', 'violations', 'injected',
                           'incarnations', 'duration_s', 'rc',
                           'watchdog_exit_codes',
                           'preempt_exit_codes')}
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True, default=str))
    else:
        print('soak smoke:', 'ok' if doc['ok'] else 'FAILED')
        for msg in failures:
            print(f'  {msg}')
        if cluster_report is not None:
            print(f'  cluster spin: rc={cluster_report["rc"]} '
                  f'{len(cluster_report["injected"])} faults, '
                  f'incarnations={cluster_report["incarnations"]}, '
                  f'{cluster_report["duration_s"]}s')
    return 0 if doc['ok'] else 1


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == '--worker':
        sys.exit(worker_main())
    ap = argparse.ArgumentParser(
        prog='soak_run',
        description='Property-based multi-process chaos soaks over '
                    'invariants I1-I7, with failing-plan shrinking.')
    ap.add_argument('--procs', type=int, default=2)
    ap.add_argument('--seed', type=int, default=7)
    ap.add_argument('--steps', type=int, default=50)
    ap.add_argument('--faults', type=int, default=6,
                    help='plan size for the generator (default 6)')
    ap.add_argument('--save-every', type=int, default=2)
    ap.add_argument('--collective-timeout', type=float, default=15.0)
    ap.add_argument('--barrier-timeout', type=float, default=20.0)
    ap.add_argument('--watchdog', default='step=120,grace=2',
                    help="worker watchdog config (PADDLE_TPU_WATCHDOG "
                         "syntax; '0' disables)")
    ap.add_argument('--deadline', type=float, default=300.0,
                    help='I7 wall-clock budget per soak (seconds)')
    ap.add_argument('--max-restarts', type=int, default=6,
                    help='per-rank failure-restart budget (invariant '
                         'I5); abort cascades under compound plans '
                         'cost a restart per affected rank')
    ap.add_argument('--quant-wire', action='store_true',
                    help='quantized-wire coverage class: workers run '
                         'every host all-reduce as block-scaled int8 '
                         '+ scales inside the crc frame, so the '
                         'fault seams drive the quantized payload '
                         'path; the bit-exact final-state reference '
                         'replays the same quantizer')
    ap.add_argument('--supervisor', default=None,
                    help='arm the self-healing plan supervisor in '
                         'the workers (PADDLE_TPU_SUPERVISOR syntax, '
                         "e.g. '1' or 'cooldown=10,margin=0.2') and "
                         'add the supervisor-migration coverage '
                         'class to generated plans: one injected '
                         'drift on rank 0 plus a SIGKILL one step '
                         'later (mid-migration crash); the actuated '
                         'swap is a coordinated-reshape restart, '
                         'free of the max_restarts budget')
    ap.add_argument('--jax-distributed', action='store_true',
                    help='also jax.distributed-initialize the workers '
                         '(clean plans only: the coordination service '
                         'cannot re-admit a killed task)')
    ap.add_argument('--once', action='store_true',
                    help='skip the same-seed replay verification')
    ap.add_argument('--shrink', action='store_true',
                    help='shrink a failing plan to a minimal '
                         'reproducer (combine with --break)')
    ap.add_argument('--break', dest='break_invariant', default=None,
                    choices=['I6'],
                    help='deliberately break an invariant in the '
                         'worker (shrinker demo / self-test)')
    ap.add_argument('--max-shrink-runs', type=int, default=16)
    ap.add_argument('--emit-regression', default=None,
                    help='path for the generated pytest reproducer')
    ap.add_argument('--smoke', action='store_true',
                    help='CI gate: golden fixtures + one 2-process '
                         'cluster spin')
    ap.add_argument('--no-cluster', action='store_true',
                    help='with --smoke: fixtures only (no processes)')
    ap.add_argument('--json', action='store_true')
    args = ap.parse_args(argv)

    if args.smoke:
        return cmd_smoke(args)
    if args.shrink or args.break_invariant:
        return cmd_shrink(args)
    return cmd_soak(args)


if __name__ == '__main__':
    sys.exit(main())
