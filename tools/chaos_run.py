#!/usr/bin/env python
"""chaos_run — run a training workload under a seeded FaultPlan and
assert the resilience invariant set.

The executable half of paddle_tpu.resilience.chaos: a supervisor
(elastic.watch_local_trainers) drives a worker training loop while the
plan injects faults INSIDE it (torn manifests, dropped commits, EIO,
SIGKILL/SIGTERM at step N, NaN grads), then the run's checkpoints and
telemetry are checked against the invariants the resilience runtime
promises:

    I1  restore() only ever yields a committed, verifiable step
    I2  committed steps are monotonic (modulo explicit restores)
    I3  every restore landed on a committed step
    I4  preemptions exited PREEMPTED_EXIT_CODE (117)
    I5  restarts stayed within the failure budget
    +   the finished run's final state equals an uninterrupted run's
        (the workload is a pure function of the step index)

Usage:

    python tools/chaos_run.py                         # default plan
    python tools/chaos_run.py --plan plan.json        # your plan
    python tools/chaos_run.py --plan '{"seed":7,...}' # inline JSON
    python tools/chaos_run.py --smoke --json          # CI gate (bench)
    python tools/chaos_run.py --script train.py a b   # your script

With ``--script`` the plan is exported as PADDLE_TPU_CHAOS_PLAN and
the script is supervised as-is — it opts in by calling
``chaos.plan_from_env()`` + ``ChaosEngine.step()`` in its loop (see
the built-in worker at the bottom of this file for the pattern).
Exit code 0 iff every invariant held.
"""
import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

STEPS_ENV = 'PADDLE_TPU_CHAOS_STEPS'
DIR_ENV = 'PADDLE_TPU_CHAOS_DIR'

# the default plan: a hard kill mid-run, one torn manifest write, one
# dropped commit — the three crash shapes the commit protocol exists
# for.  Seeded so two runs inject the identical sequence.
DEFAULT_PLAN = {
    'seed': 7,
    'name': 'smoke',
    'faults': [
        {'kind': 'sigkill', 'at_step': 5},
        {'kind': 'torn_write', 'at_step': None, 'path': 'step_7'},
        {'kind': 'drop_commit', 'at_step': 9},
    ],
}


def _final_w(steps):
    """The workload's exact final state: w_i = 0.9 * w_{i-1} + i over
    float32 — pure in the step index, so ANY fault schedule that lets
    the run finish must reproduce it bit-for-bit."""
    import numpy as np
    w = np.arange(8.0, dtype='float32')
    for i in range(1, steps + 1):
        w = (w * np.float32(0.9)
             + np.float32(i) * np.ones(8, dtype='float32'))
    return w


def worker_main(args):
    """The supervised workload (internal --worker mode): deterministic
    toy training with a per-step sharded checkpoint, resumed from the
    latest committed step, with the FaultPlan's engine active."""
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import jax.numpy as jnp
    from paddle_tpu import telemetry
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.resilience import (
        install_shutdown, shutdown_requested, PREEMPTED_EXIT_CODE)
    from paddle_tpu.resilience.chaos import ChaosEngine, plan_from_env

    workdir = os.environ[DIR_ENV]
    steps = int(os.environ.get(STEPS_ENV, '12'))
    incarnation = int(os.environ.get('PADDLE_ELASTIC_RESTART_COUNT',
                                     '0'))
    preemptions = int(os.environ.get('PADDLE_ELASTIC_PREEMPT_COUNT',
                                     '0'))
    hb = os.path.join(workdir, 'heartbeat')
    telemetry.enable(os.path.join(workdir, 'telemetry'))
    plan = plan_from_env()
    if plan is not None and (incarnation or preemptions):
        # process-level faults fire once, in the FIRST incarnation —
        # a restarted worker re-reading the same plan must not
        # re-kill itself at the same step forever
        plan.faults = [f for f in plan.faults
                       if f.kind not in ('sigterm', 'sigkill')]
    engine = ChaosEngine(plan, heartbeat_file=hb) if plan else None
    if engine:
        engine.activate()
    install_shutdown()

    ckpt = os.path.join(workdir, 'ckpt')
    mgr = CheckpointManager(ckpt, keep=3, async_save=False)
    w = jnp.arange(8.0, dtype=jnp.float32)
    state = {'w': w, 'step': jnp.asarray(0)}
    restored, got = mgr.restore(state)
    start = 1
    if restored is not None:
        state = restored
        start = int(np.asarray(restored['step'])) + 1
    for i in range(start, steps + 1):
        if engine:
            engine.step(i)          # may SIGKILL/SIGTERM us right here
        state = {'w': state['w'] * jnp.float32(0.9)
                 + jnp.float32(i) * jnp.ones(8, jnp.float32),
                 'step': jnp.asarray(i)}
        mgr.save(state, i)
        with open(hb, 'a'):
            os.utime(hb, None)
        if shutdown_requested():
            mgr.wait()
            telemetry.dump_flight(os.path.join(
                workdir, f'flightrec-preempt-{i}.json'))
            sys.exit(PREEMPTED_EXIT_CODE)
    mgr.wait()
    with open(os.path.join(workdir, 'out.json'), 'w') as f:
        json.dump({'final_w': np.asarray(state['w']).tolist(),
                   'final_step': int(np.asarray(state['step'])),
                   'incarnation': incarnation,
                   'preemptions': preemptions}, f)
    return 0


def _load_events(workdir):
    """Every telemetry event of the run: streamed JSONL plus the event
    rings of any flight-recorder dumps (a SIGKILLed incarnation's last
    moments only survive in its pre-kill dump).  Shared with the
    multi-process ChaosCluster driver."""
    from paddle_tpu.resilience.chaos import load_run_events
    return load_run_events(workdir)


def supervise_run(plan, workdir, steps=12, max_restarts=3,
                  script=None, timeout=600):
    """Run the workload (or `script` argv) under `plan`; returns the
    report dict (ok, violations, injected, exit codes...)."""
    from paddle_tpu.distributed import elastic
    from paddle_tpu.resilience.chaos import check_invariants

    os.makedirs(workdir, exist_ok=True)
    env = dict(os.environ)
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = _REPO + os.pathsep + env.get('PYTHONPATH', '')
    env[DIR_ENV] = workdir
    env[STEPS_ENV] = str(steps)
    env['PADDLE_TPU_CHAOS_PLAN'] = plan.to_json()
    env['PADDLE_TPU_MIN_PREEMPT_UPTIME'] = '0'
    cmd = (list(script) if script
           else [sys.executable, os.path.abspath(__file__), '--worker'])

    events_seen = []
    exit_codes = {'preempt': [], 'exit': []}

    def on_event(kind, t):
        events_seen.append(kind)
        rc = t.proc.returncode if t.proc else None
        if kind in exit_codes and rc is not None:
            exit_codes[kind].append(rc)

    t0 = time.time()
    procs = elastic.start_local_trainers([cmd], envs=env)
    rc = elastic.watch_local_trainers(
        procs, max_restarts=max_restarts, poll=0.05,
        min_preempt_uptime=0.0, on_event=on_event,
        restart_backoff=0.2, restart_backoff_max=2.0)
    dur = time.time() - t0

    events = _load_events(workdir)
    injected = [e for e in events if e.get('kind') == 'fault_injected']
    violations = check_invariants(
        os.path.join(workdir, 'ckpt'), events=events,
        max_restarts=max_restarts, restarts=procs[0].restarts,
        preempt_codes=exit_codes['preempt'])
    if rc != 0:
        violations.append(f'run did not complete cleanly (rc={rc})')
    out_path = os.path.join(workdir, 'out.json')
    final = None
    if script is None:
        if os.path.exists(out_path):
            final = json.load(open(out_path))
            import numpy as np
            ref = _final_w(steps)
            if not np.allclose(final['final_w'], ref, rtol=0, atol=0):
                violations.append(
                    'final state differs from the uninterrupted '
                    'reference — a fault leaked into the arithmetic')
        else:
            violations.append('worker never wrote out.json')
    return {
        'ok': not violations,
        'violations': violations,
        'plan': json.loads(plan.to_json()),
        'steps': steps,
        'injected': [{k: e.get(k) for k in
                      ('fault', 'step', 'path', 'seq', 'errno')
                      if e.get(k) is not None} for e in injected],
        'incarnations': 1 + procs[0].restarts + procs[0].preemptions,
        'failure_restarts': procs[0].restarts,
        'preemptions': procs[0].preemptions,
        'preempt_exit_codes': exit_codes['preempt'],
        'supervisor_events': events_seen,
        'duration_s': round(dur, 2),
        'final': final,
    }


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == '--worker':
        sys.exit(worker_main(argv[1:]))
    ap = argparse.ArgumentParser(
        prog='chaos_run',
        description='Run a training workload under a seeded FaultPlan '
                    'and assert the resilience invariants.')
    ap.add_argument('--plan', default=None,
                    help='FaultPlan JSON (inline or a file path); '
                         'default: the built-in kill+torn-write plan')
    ap.add_argument('--steps', type=int, default=None,
                    help='training steps (default 12; 10 in --smoke)')
    ap.add_argument('--max-restarts', type=int, default=3)
    ap.add_argument('--dir', default=None,
                    help='workdir (default: a fresh temp dir)')
    ap.add_argument('--smoke', action='store_true',
                    help='CI gate mode: default plan, fewer steps')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable report on stdout')
    ap.add_argument('--script', nargs=argparse.REMAINDER, default=None,
                    help='run this argv as the worker instead of the '
                         'built-in workload (plan ships via '
                         'PADDLE_TPU_CHAOS_PLAN)')
    args = ap.parse_args(argv)

    from paddle_tpu.resilience.chaos import FaultPlan
    if args.plan and not args.smoke:
        text = args.plan
        if os.path.exists(text):
            text = open(text).read()
        plan = FaultPlan.from_json(text)
    else:
        # --smoke is the CI gate: always the built-in plan (a custom
        # --plan is ignored so the gate's coverage can't be narrowed
        # by accident) and a shorter run
        plan = FaultPlan.from_json(json.dumps(DEFAULT_PLAN))
    steps = args.steps if args.steps is not None else \
        (10 if args.smoke else 12)
    workdir = args.dir
    if workdir is None:
        import tempfile
        workdir = tempfile.mkdtemp(prefix='chaos_run_')
    report = supervise_run(plan, workdir, steps=steps,
                           max_restarts=args.max_restarts,
                           script=args.script)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(f'chaos_run: plan={plan.name or "custom"} '
              f'seed={plan.seed} steps={steps} '
              f'workdir={workdir}')
        for e in report['injected']:
            print(f'  injected: {e}')
        print(f'  incarnations={report["incarnations"]} '
              f'(failure restarts {report["failure_restarts"]}, '
              f'preemptions {report["preemptions"]}) '
              f'in {report["duration_s"]}s')
        if report['ok']:
            print('  all resilience invariants held')
        else:
            for v in report['violations']:
                print(f'  VIOLATION: {v}')
    return 0 if report['ok'] else 1


if __name__ == '__main__':
    sys.exit(main())
