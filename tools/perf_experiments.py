#!/usr/bin/env python
"""ResNet-50 perf experiment matrix (PERF.md follow-ups).

The step is HBM-bound; each variant tests one bytes-reduction lever:
  base      — bench.py config (batch 256, bf16 AMP O2)
  remat     — strategy.recompute: trade recompute FLOPs for residuals
  bf16in    — feed the images as bf16 (halves the input slab)
  b512      — batch 512 (amortize fixed traffic; may OOM)
  s2d       — MLPerf-TPU space-to-depth stem (4x4/s1 conv on the
              block-2 s2d input; exact-function re-lay of the 7x7/s2
              stem — parity locked in test_resnet_s2d_stem_matches_
              standard; this measures whether it is FASTER)
Run on the real chip: python tools/perf_experiments.py
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def run(tag, batch=256, image=224, recompute=False, bf16_in=False,
        s2d=False, iters=30, warmup=5):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models.resnet import ResNet, BottleneckBlock
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed import env as dist_env

    dist_env.set_mesh(None)
    paddle.seed(0)
    net = ResNet(BottleneckBlock, 50, num_classes=1000,
                 data_format='NHWC', stem_space_to_depth=s2d)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    ce = nn.CrossEntropyLoss()
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    strategy.recompute = recompute
    trainer = ParallelTrainer(net, opt, lambda out, y: ce(out, y),
                              strategy=strategy)
    rs = np.random.RandomState(0)
    x = rs.randn(batch, image, image, 3)
    x = jax.device_put(x.astype('bfloat16' if bf16_in else 'float32'))
    y = jax.device_put(rs.randint(0, 1000, size=(batch, 1))
                       .astype('int64'))
    try:
        loss = None
        for _ in range(warmup):
            loss = trainer.step(x, y)
        jax.block_until_ready(loss)
        t0 = time.time()
        for _ in range(iters):
            loss = trainer.step(x, y)
        lv = float(np.asarray(loss))
        dt = (time.time() - t0) / iters
        print(f'{tag:8s} {dt * 1000:7.1f} ms/step '
              f'{batch / dt:8.0f} imgs/s  loss={lv:.3f}', flush=True)
        return batch / dt
    except Exception as e:
        print(f'{tag:8s} FAILED: {type(e).__name__}: {e}', flush=True)
        return None


def main():
    import jax
    print('device:', jax.devices()[0], flush=True)
    results = {}
    results['base'] = run('base')
    results['remat'] = run('remat', recompute=True)
    results['bf16in'] = run('bf16in', bf16_in=True)
    results['b512'] = run('b512', batch=512)
    results['b512rm'] = run('b512rm', batch=512, recompute=True)
    results['s2d'] = run('s2d', s2d=True)
    results['s2d_bf16'] = run('s2d_bf16', s2d=True, bf16_in=True)
    best = max((v, k) for k, v in results.items() if v)
    print(f'best: {best[1]} at {best[0]:.0f} imgs/s')


if __name__ == '__main__':
    main()
