#!/usr/bin/env python
"""Profile the ResNet-50 bench step on the real TPU chip.

Dumps: compiled cost analysis (flops), optimized-HLO op census (via
the shared ``profiler.op_summary`` / ``analysis.hlo`` parser — the
ad-hoc regex census this script used to carry is gone), and timed
variants (fwd-only, fwd+bwd, full step) to locate where step time
goes.  Findings feed bench.py / PERF.md (VERDICT round-1 item 3).

``--emit-telemetry`` additionally captures an on-device trace window
around the timed full-step loop through the shared capture/parse API
(``telemetry.capture``): the run leaves telemetry JSONL + a
``profile_capture`` event (device-compute vs collective breakdown,
census-matched ``collective_observed`` on multi-device runs) in
``--out``, joinable by tools/run_report.py and fittable by
tools/calibrate_costmodel.py.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--batch', type=int, default=256)
    p.add_argument('--image', type=int, default=224)
    p.add_argument('--iters', type=int, default=20)
    p.add_argument('--emit-telemetry', action='store_true',
                   help='capture a trace window around the timed loop '
                        'and stream telemetry JSONL to --out')
    p.add_argument('--out', default=os.path.join(
        'tools', 'chip_out', 'profile_resnet'),
        help='telemetry/trace output dir for --emit-telemetry')
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.vision.models.resnet import ResNet, BottleneckBlock
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet

    log(f'device: {jax.devices()[0]}')
    if args.emit_telemetry:
        telemetry.enable(args.out)
    paddle.seed(0)
    net = ResNet(BottleneckBlock, 50, num_classes=1000, data_format='NHWC')
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    ce = nn.CrossEntropyLoss()
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    trainer = ParallelTrainer(net, opt, lambda out, y: ce(out, y),
                              strategy=strategy)

    rs = np.random.RandomState(0)
    x = jax.device_put(rs.randn(args.batch, args.image, args.image, 3)
                       .astype('float32'))
    y = jax.device_put(rs.randint(0, 1000, size=(args.batch, 1))
                       .astype('int64'))

    # one step to build + place state
    loss = trainer.step(x, y)
    jax.block_until_ready(loss)

    # per-op census + module cost totals through the ONE shared
    # lowering (trainer.compiled_text memo feeds op_summary, the
    # collective census and memory_usage alike)
    try:
        trainer.op_summary(x, y, top=40, stream=sys.stderr)
    except Exception as e:
        log('op_summary failed:', repr(e))
    try:
        txt = trainer.compiled_text()
        log('--- conv lines (first 10) ---')
        shown = 0
        for line in txt.splitlines():
            if ' convolution(' in line and shown < 10:
                log(line.strip()[:200])
                shown += 1
    except Exception as e:
        log('hlo text unavailable:', repr(e))

    # timed: full step — NEVER traced: in-window tracing adds
    # per-step overhead (PERF.md) and this number is the headline
    t0 = time.time()
    for _ in range(args.iters):
        loss = trainer.step(x, y)
    jax.block_until_ready(loss)
    full = (time.time() - t0) / args.iters
    log(f'full step: {full * 1000:.2f} ms '
        f'({args.batch / full:.0f} imgs/s)')

    if args.emit_telemetry:
        # a SEPARATE short traced window, after the headline loop
        n_trace = min(args.iters, 4)
        mesh_shape = (dict(trainer.mesh.shape)
                      if trainer.mesh is not None else None)
        with telemetry.capture(
                os.path.join(args.out, 'trace'), name='resnet',
                hlo_text_fn=trainer.compiled_text,
                mesh_shape=mesh_shape, steps=n_trace) as cap:
            for _ in range(n_trace):
                loss = trainer.step(x, y)
            cap.sync = loss
        win = cap.windows[-1] if cap.windows else {}
        log(f'trace window ({n_trace} steps): '
            f'{win.get("device_us_per_step", 0):.0f} us/step device, '
            f'{win.get("collective_us_per_step", 0):.0f} us '
            'collectives '
            f'({len(cap.observed)} collective_observed)')

    # fwd-only (same AMP path), jitted separately
    from paddle_tpu.jit import functional_call
    from paddle_tpu import amp as amp_mod

    params, buffers = net.functional_state()

    def fwd(params, x):
        with amp_mod.auto_cast(level='O2'):
            out, _ = functional_call(net, params, buffers, (x,),
                                     training=True,
                                     key=jax.random.PRNGKey(0))
        return out.astype(jnp.float32).mean()

    jf = jax.jit(fwd)
    jf(params, x).block_until_ready()
    t0 = time.time()
    for _ in range(args.iters):
        r = jf(params, x)
    r.block_until_ready()
    fwd_t = (time.time() - t0) / args.iters
    log(f'fwd-only: {fwd_t * 1000:.2f} ms')

    # fwd+bwd (no optimizer)
    jg = jax.jit(jax.grad(fwd))
    jg(params, x)
    jax.block_until_ready(jg(params, x))
    t0 = time.time()
    for _ in range(args.iters):
        g = jg(params, x)
    jax.block_until_ready(g)
    bwd_t = (time.time() - t0) / args.iters
    log(f'fwd+bwd: {bwd_t * 1000:.2f} ms')
    log(f'optimizer+overhead: {(full - bwd_t) * 1000:.2f} ms')
    if args.emit_telemetry:
        telemetry.disable()
        log(f'telemetry JSONL + trace artifacts: {args.out}')


if __name__ == '__main__':
    main()
