#!/usr/bin/env python
"""Profile the ResNet-50 bench step on the real TPU chip.

Dumps: compiled cost analysis (flops), optimized-HLO op census
(conv dtypes, transposes, fusions, all casts), and timed variants
(fwd-only, fwd+bwd, full step) to locate where step time goes.
Findings feed bench.py / PERF.md (VERDICT round-1 item 3).
"""
import argparse
import collections
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def census(hlo_text):
    """Count ops by (opcode, dtype) in optimized HLO text."""
    counts = collections.Counter()
    for line in hlo_text.splitlines():
        m = re.match(r'\s*(?:ROOT )?[%\w.-]+ = (\w+)\[([\d,]*)\][^ ]* (\w+)\(',
                     line)
        if m:
            dtype, shape, opcode = m.group(1), m.group(2), m.group(3)
            counts[(opcode, dtype)] += 1
    return counts


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--batch', type=int, default=256)
    p.add_argument('--image', type=int, default=224)
    p.add_argument('--iters', type=int, default=20)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models.resnet import ResNet, BottleneckBlock
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet

    log(f'device: {jax.devices()[0]}')
    paddle.seed(0)
    net = ResNet(BottleneckBlock, 50, num_classes=1000, data_format='NHWC')
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    ce = nn.CrossEntropyLoss()
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    trainer = ParallelTrainer(net, opt, lambda out, y: ce(out, y),
                              strategy=strategy)

    rs = np.random.RandomState(0)
    x = jax.device_put(rs.randn(args.batch, args.image, args.image, 3)
                       .astype('float32'))
    y = jax.device_put(rs.randint(0, 1000, size=(args.batch, 1))
                       .astype('int64'))

    # one step to build + place state
    loss = trainer.step(x, y)
    jax.block_until_ready(loss)

    compiled = None
    try:
        # trainer caches the jitted fn; re-lower for analysis
        fn = trainer._compiled
        lowered = fn.lower(trainer.params, trainer.buffers,
                           trainer.opt_state, jnp.asarray(1),
                           jnp.asarray(0, jnp.uint32), x, y)
        compiled = lowered.compile()
    except Exception as e:
        log('lower/compile for analysis failed:', repr(e))

    if compiled is not None:
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            log('cost_analysis flops:', ca.get('flops'))
            log('cost_analysis bytes accessed:', ca.get('bytes accessed'))
        except Exception as e:
            log('cost_analysis failed:', repr(e))
        try:
            txt = compiled.as_text()
            c = census(txt)
            log('--- optimized HLO op census (top 40) ---')
            for (opcode, dtype), n in c.most_common(40):
                log(f'{opcode:24s} {dtype:8s} {n}')
            convs = [(k, v) for k, v in c.items() if k[0] == 'convolution']
            log('--- convolutions by dtype ---', convs)
            # biggest fusions / convs with shapes
            log('--- conv lines (first 10) ---')
            shown = 0
            for line in txt.splitlines():
                if ' convolution(' in line and shown < 10:
                    log(line.strip()[:200])
                    shown += 1
        except Exception as e:
            log('hlo census failed:', repr(e))

    # timed: full step
    t0 = time.time()
    for _ in range(args.iters):
        loss = trainer.step(x, y)
    jax.block_until_ready(loss)
    full = (time.time() - t0) / args.iters
    log(f'full step: {full * 1000:.2f} ms '
        f'({args.batch / full:.0f} imgs/s)')

    # fwd-only (same AMP path), jitted separately
    from paddle_tpu.jit import functional_call
    from paddle_tpu import amp as amp_mod

    params, buffers = net.functional_state()

    def fwd(params, x):
        with amp_mod.auto_cast(level='O2'):
            out, _ = functional_call(net, params, buffers, (x,),
                                     training=True,
                                     key=jax.random.PRNGKey(0))
        return out.astype(jnp.float32).mean()

    jf = jax.jit(fwd)
    jf(params, x).block_until_ready()
    t0 = time.time()
    for _ in range(args.iters):
        r = jf(params, x)
    r.block_until_ready()
    fwd_t = (time.time() - t0) / args.iters
    log(f'fwd-only: {fwd_t * 1000:.2f} ms')

    # fwd+bwd (no optimizer)
    jg = jax.jit(jax.grad(fwd))
    jg(params, x)
    jax.block_until_ready(jg(params, x))
    t0 = time.time()
    for _ in range(args.iters):
        g = jg(params, x)
    jax.block_until_ready(g)
    bwd_t = (time.time() - t0) / args.iters
    log(f'fwd+bwd: {bwd_t * 1000:.2f} ms')
    log(f'optimizer+overhead: {(full - bwd_t) * 1000:.2f} ms')


if __name__ == '__main__':
    main()
