#!/usr/bin/env python
"""Chip A/B: bf16 matmul vs dynamic int8 matmul at GPT decode shapes.

Decode is weight-bandwidth-bound (every step streams all weights for a
[B, 1, H] activation), so int8 weights (half the HBM bytes of bf16,
native MXU int8 multiply on v5e) should approach 2x on the matmul-
dominated portion.  This measures the raw op; model integration
follows only if the chip confirms the win.

    python tools/bench_int8_matmul.py [--iters 30]
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._env import setup_jax_cache
setup_jax_cache()

SHAPES = [  # (B, H, O): lm head, MLP up, MLP down, qkv at gpt2-small
    (8, 768, 50304),
    (8, 768, 3072),
    (8, 3072, 768),
    (8, 768, 2304),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--iters', type=int, default=30)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.int8_matmul import (quantize_weight_int8,
                                            dynamic_int8_matmul)
    print(f'device: {jax.devices()[0]}', file=sys.stderr)
    rs = np.random.RandomState(0)
    rows = {}
    for B, H, O in SHAPES:
        x = jnp.asarray(rs.randn(B, H), jnp.bfloat16)
        w = jnp.asarray(rs.randn(H, O) / np.sqrt(H), jnp.float32)
        w_bf = w.astype(jnp.bfloat16)
        w_q, w_s = quantize_weight_int8(w)

        def chain(fn, x):
            # in-graph chain with a data dependency defeats tunnel
            # dispatch noise (PERF.md methodology); fold the output
            # back to the input width via a cheap slice-sum
            def body(c, _):
                y = fn(c)
                return (c + y[:, :H].astype(c.dtype)
                        if O >= H else c + jnp.pad(y, ((0, 0), (0, H - O))).astype(c.dtype)), None
            out, _ = jax.lax.scan(body, x, None, length=args.iters)
            return out

        f_bf = jax.jit(lambda x: chain(lambda c: c @ w_bf, x))
        f_i8 = jax.jit(lambda x: chain(
            lambda c: dynamic_int8_matmul(c, w_q, w_s), x))
        out = {}
        for name, f in (('bf16', f_bf), ('int8', f_i8)):
            float(np.asarray(f(x)).ravel()[0])     # compile+warm
            t0 = time.perf_counter()
            float(np.asarray(f(x)).ravel()[0])
            out[name] = (time.perf_counter() - t0) * 1e3 / args.iters
        rows[f'{B}x{H}x{O}'] = out
        print(f'[{B}x{H}x{O}] bf16 {out["bf16"]:7.3f} ms  '
              f'int8 {out["int8"]:7.3f} ms  '
              f'({out["bf16"] / out["int8"]:.2f}x)', file=sys.stderr)
    import json
    print(json.dumps(rows))


if __name__ == '__main__':
    main()
