#!/usr/bin/env python
"""Headline benchmark: ResNet-50 bf16(AMP) training throughput on one
TPU chip — imgs/sec/chip (SURVEY.md §3 item 2).

Baseline constant: the reference's V100-class ResNet-50 AMP number is
~900 imgs/s/chip (no published figure ships in BASELINE.json, see
SURVEY.md §3); vs_baseline = value / 900.

Prints ONE JSON line to stdout; progress goes to stderr.
"""
import argparse
import json
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 900.0


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--smoke', action='store_true',
                   help='tiny shapes, few iters (CI sanity)')
    p.add_argument('--batch', type=int, default=256)
    p.add_argument('--image', type=int, default=224)
    p.add_argument('--iters', type=int, default=30)
    p.add_argument('--warmup', type=int, default=5)
    args = p.parse_args()
    if args.smoke:
        args.batch, args.image, args.iters, args.warmup = 32, 64, 4, 2

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models.resnet import ResNet, BottleneckBlock
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet

    log(f'device: {jax.devices()[0]}  batch={args.batch} '
        f'image={args.image}')

    paddle.seed(0)
    net = ResNet(BottleneckBlock, 50, num_classes=1000,
                 data_format='NHWC')
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    ce = nn.CrossEntropyLoss()

    strategy = fleet.DistributedStrategy()
    strategy.amp = True                       # bf16 compute (TPU AMP)
    strategy.amp_configs['use_pure_fp16'] = True   # O2: pure bf16

    trainer = ParallelTrainer(net, opt, lambda out, y: ce(out, y),
                              strategy=strategy)

    rs = np.random.RandomState(0)
    # place the batch in HBM once — the bench measures compute, not the
    # host link (real input pipelines double-buffer via the DataLoader)
    x = jax.device_put(
        rs.randn(args.batch, args.image, args.image, 3).astype('float32'))
    y = jax.device_put(
        rs.randint(0, 1000, size=(args.batch, 1)).astype('int64'))

    t0 = time.time()
    loss = None
    for i in range(args.warmup):
        loss = trainer.step(x, y)
    jax.block_until_ready(loss)
    log(f'warmup ({args.warmup} steps incl. compile): '
        f'{time.time() - t0:.1f}s  loss={float(np.asarray(loss)):.4f}')

    t0 = time.time()
    for i in range(args.iters):
        loss = trainer.step(x, y)
    jax.block_until_ready(loss)
    dt = time.time() - t0

    imgs_per_sec = args.batch * args.iters / dt
    log(f'{args.iters} steps in {dt:.2f}s  '
        f'({dt / args.iters * 1000:.1f} ms/step)  '
        f'final loss={float(np.asarray(loss)):.4f}')

    print(json.dumps({
        'metric': 'resnet50_bf16_train_throughput',
        'value': round(imgs_per_sec, 2),
        'unit': 'imgs/sec/chip',
        'vs_baseline': round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 4),
    }))


if __name__ == '__main__':
    main()
