#!/usr/bin/env python
"""SURVEY.md §3 benchmark suite on one TPU chip.

Configs (SURVEY §3):
  1. LeNet MNIST dygraph        — correctness anchor (imgs/sec).
  2. ResNet-50 bf16(AMP) train  — HEADLINE imgs/sec/chip.
  3. BERT-base pretrain bf16    — tokens/sec/chip.
  4. GPT-2 small T=1024 train   — tokens/sec/chip (single-chip face of
     the GPT config; the hybrid multichip path is
     __graft_entry__.dryrun_multichip).
  5. Wide&Deep sparse           — examples/sec/chip.

Baseline constants (BASELINE.json ships no published numbers; these are
documented V100-class reference points, vs_baseline = value/baseline):
  ResNet-50 AMP   ~900    imgs/s/GPU   (reference's headline config)
  BERT-base s128  ~50_000 tokens/s/GPU (~390 seq/s fp16)
  Wide&Deep       ~200_000 examples/s  (GPU PS-mode)

Prints ONE JSON line to stdout: the headline ResNet metric, with the
other configs nested under "extras". Progress goes to stderr.
Run a single config with --config
{lenet,resnet,bert,gpt,widedeep,longctx,gptgen} (or 'all').
"""
import argparse
import contextlib
import json
import os
import sys
import time

import numpy as np

BASELINES = {
    'resnet': 900.0,        # imgs/s
    'bert': 50_000.0,       # tokens/s
    'widedeep': 200_000.0,  # examples/s
    'lenet': 10_000.0,      # imgs/s (anchor only)
    'gpt': 20_000.0,        # tokens/s (V100-class GPT-2 small AMP)
    'gptgen': 2_000.0,      # decoded tokens/s (V100-class KV-cache
                            # batch-8 GPT-2 small generation)
    'longctx': 5_000.0,     # tokens/s (V100-class GPT-2 small T=4096:
                            # activation memory forces micro-batching)
    'serve': 4_000.0,       # decoded tokens/s (V100-class vLLM-style
                            # continuous batching, GPT-2 small,
                            # batch-64 mixed-length Poisson load)
}


CHIP_OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'tools', 'chip_out')
# committed, per-config chip-verified numbers (tools/chip_session.py
# commits this file after every bench step) — the stale-merge source
# when the tunnel is dead at driver time
CHIP_RESULTS = os.path.join(CHIP_OUT, 'bench_results.json')
GPTGEN_FALLBACK_FLAG = os.path.join(CHIP_OUT, 'gptgen_fallback.flag')


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _load_chip_results():
    try:
        with open(CHIP_RESULTS) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def _record_chip_result(name, res):
    """Persist a chip-verified per-config number (merged, timestamped)
    so a later dead-tunnel driver run can still surface it as stale
    evidence.  Only real-TPU, non-smoke numbers qualify — round 4 lost
    a whole session's measurements to a CPU smoke run overwriting the
    partial artifact."""
    if res.get('value') is None or res.get('platform') != 'tpu':
        return
    os.makedirs(CHIP_OUT, exist_ok=True)
    merged = _load_chip_results()
    merged[name] = dict(res, measured_at=time.strftime(
        '%Y-%m-%dT%H:%M:%SZ', time.gmtime()))
    tmp = CHIP_RESULTS + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(merged, f, indent=1, sort_keys=True)
    os.replace(tmp, CHIP_RESULTS)


def _time_steps(step, iters, *args):
    """Run `step` iters times, force a host sync, return seconds."""
    import jax
    t0 = time.time()
    out = None
    for _ in range(iters):
        out = step(*args)
    jax.block_until_ready(out)
    # belt & braces: block_until_ready + an actual host readback
    float(np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])
    return time.time() - t0


def bench_resnet(smoke):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models.resnet import ResNet, BottleneckBlock
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet

    batch, image, iters, warmup = (32, 64, 4, 2) if smoke else \
        (256, 224, 30, 5)
    paddle.seed(0)
    net = ResNet(BottleneckBlock, 50, num_classes=1000,
                 data_format='NHWC')
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    ce = nn.CrossEntropyLoss()
    strategy = fleet.DistributedStrategy()
    strategy.amp = True                        # bf16 compute (TPU AMP)
    strategy.amp_configs['use_pure_fp16'] = True   # O2: pure bf16
    trainer = ParallelTrainer(net, opt, lambda out, y: ce(out, y),
                              strategy=strategy)
    rs = np.random.RandomState(0)
    # batch lives in HBM: the bench measures compute, not the host link
    # (real input pipelines double-buffer via the DataLoader).
    # bf16 images: the step is HBM-bound (PERF.md) and the input slab is
    # 154 MB/step at f32 — halving it is a measured ~1.5% step win; the
    # first conv runs bf16 under AMP O2 anyway so numerics are unchanged
    x = jax.device_put(
        rs.randn(batch, image, image, 3).astype('float32')
        .astype('bfloat16'))
    y = jax.device_put(
        rs.randint(0, 1000, size=(batch, 1)).astype('int64'))
    t0 = time.time()
    loss = None
    for _ in range(warmup):
        loss = trainer.step(x, y)
    jax.block_until_ready(loss)
    log(f'resnet warmup ({warmup} steps incl. compile): '
        f'{time.time() - t0:.1f}s loss={float(np.asarray(loss)):.4f}')
    dt = _time_steps(trainer.step, iters, x, y)
    v = batch * iters / dt
    log(f'resnet50: {iters} steps in {dt:.2f}s '
        f'({dt / iters * 1000:.1f} ms/step, {v:.0f} imgs/s)')
    return v


def bench_bert(smoke):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn  # noqa: F401  (keeps import order uniform)
    from paddle_tpu.models.bert import bert_base, bert_tiny
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet

    batch, seq, iters, warmup = (4, 64, 3, 2) if smoke else \
        (64, 128, 20, 4)
    paddle.seed(0)
    # fused_head: the tied-decoder matmul fuses into the MLM loss
    # (ops/fused_ce.py) — no [B·T, V] logits tensor
    model = bert_tiny(fused_head=True) if smoke else \
        bert_base(max_seq_len=seq, dropout=0.0, fused_head=True,
                  fused_head_chunks=8)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    trainer = ParallelTrainer(model, opt,
                              lambda out, y: model.loss(out, y),
                              strategy=strategy)
    rs = np.random.RandomState(0)
    V = model.config.vocab_size
    ids = jax.device_put(
        rs.randint(0, V, size=(batch, seq)).astype('int64'))
    # MLM labels: predict 15% of positions, ignore the rest (-100)
    lbl = np.where(rs.rand(batch, seq) < 0.15,
                   rs.randint(0, V, size=(batch, seq)), -100)
    lbl = jax.device_put(lbl.astype('int64'))
    t0 = time.time()
    loss = None
    for _ in range(warmup):
        loss = trainer.step(ids, lbl)
    jax.block_until_ready(loss)
    log(f'bert warmup ({warmup} steps incl. compile): '
        f'{time.time() - t0:.1f}s loss={float(np.asarray(loss)):.4f}')
    dt = _time_steps(trainer.step, iters, ids, lbl)
    v = batch * seq * iters / dt
    log(f'bert-base: {iters} steps in {dt:.2f}s '
        f'({dt / iters * 1000:.1f} ms/step, {v:.0f} tokens/s)')
    return v


def _bench_gpt_train(smoke, *, smoke_shape, full_shape, label):
    """Shared GPT-2 train-bench harness (gpt @T=1024, longctx @T=4096):
    fused CE head, flash attention on the T^2 term, bf16 AMP O2."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt_small, gpt_tiny
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import fleet

    batch, seq, iters, warmup = smoke_shape if smoke else full_shape
    paddle.seed(0)
    # fused_head: the LM-head matmul fuses into the loss (ops/
    # fused_ce.py) — no f32 [B*T, V] logits tensor, the top HBM
    # consumer of the unfused step
    model = gpt_tiny(fused_head=True, max_seq_len=seq) if smoke else \
        gpt_small(max_seq_len=seq, dropout=0.0, fused_head=True,
                  fused_head_chunks=8)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    trainer = ParallelTrainer(model, opt,
                              lambda out, y: model.loss(out, y),
                              strategy=strategy)
    rs = np.random.RandomState(0)
    V = model.config.vocab_size
    ids = jax.device_put(
        rs.randint(0, V, size=(batch, seq)).astype('int64'))
    t0 = time.time()
    loss = None
    for _ in range(warmup):
        loss = trainer.step(ids, ids)
    jax.block_until_ready(loss)
    log(f'{label} warmup ({warmup} steps incl. compile): '
        f'{time.time() - t0:.1f}s loss={float(np.asarray(loss)):.4f}')
    dt = _time_steps(trainer.step, iters, ids, ids)
    v = batch * seq * iters / dt
    log(f'{label} T={seq}: {iters} steps in {dt:.2f}s '
        f'({dt / iters * 1000:.1f} ms/step, {v:.0f} tokens/s)')
    return v


def bench_gpt(smoke):
    """GPT-2 small causal-LM train at T=1024 — the single-chip face of
    SURVEY §3 config 4 (the hybrid multichip path is
    dryrun_multichip); the fused CE head is the bench default."""
    return _bench_gpt_train(smoke, smoke_shape=(2, 128, 3, 2),
                            full_shape=(8, 1024, 15, 3),
                            label='gpt2-small')


def bench_longctx(smoke):
    """GPT-2 small at T=4096 on ONE chip — the long-context face of
    the brief: flash attention carries the 16x-larger T^2 term in
    O(block) memory.  (Beyond-one-chip sequences ride the sp ring;
    see dryrun.)"""
    return _bench_gpt_train(smoke, smoke_shape=(1, 256, 2, 2),
                            full_shape=(2, 4096, 10, 3),
                            label='gpt2-small-longctx')


def bench_widedeep(smoke):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.widedeep import WideDeep
    from paddle_tpu.parallel import ParallelTrainer

    from paddle_tpu.distributed import fleet

    batch, iters, warmup = (256, 3, 2) if smoke else (16384, 30, 5)
    fields = [100_000] * 26          # criteo-like: 26 sparse fields
    dense_dim = 13
    paddle.seed(0)
    model = WideDeep(fields, dense_dim=dense_dim, embed_dim=16,
                     hidden=(400, 400, 400))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    bce = nn.BCEWithLogitsLoss()
    # bf16 AMP on the MLP towers: measured +58% step win (PERF.md);
    # CTR training at 16k batch is standard for this model class
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.amp_configs['use_pure_fp16'] = True
    trainer = ParallelTrainer(model, opt,
                              lambda out, y: bce(out, y), n_inputs=2,
                              strategy=strategy)
    rs = np.random.RandomState(0)
    ids = jax.device_put(np.stack(
        [rs.randint(0, f, size=batch) for f in fields],
        axis=1).astype('int64'))
    dense = jax.device_put(rs.rand(batch, dense_dim).astype('float32'))
    y = jax.device_put(
        rs.randint(0, 2, size=(batch, 1)).astype('float32'))
    t0 = time.time()
    loss = None
    for _ in range(warmup):
        loss = trainer.step(ids, dense, y)
    jax.block_until_ready(loss)
    log(f'widedeep warmup ({warmup} steps incl. compile): '
        f'{time.time() - t0:.1f}s loss={float(np.asarray(loss)):.4f}')
    dt = _time_steps(trainer.step, iters, ids, dense, y)
    v = batch * iters / dt
    log(f'wide&deep: {iters} steps in {dt:.2f}s '
        f'({dt / iters * 1000:.1f} ms/step, {v:.0f} examples/s)')
    return v


def bench_gptgen(smoke):
    """Incremental decoding throughput on the KV-cache generate path:
    whole prefill+scan decode is ONE compiled XLA module
    (models/gpt.py::generate), so per-token cost is O(T) attention —
    reference decode goes through fluid's host-side beam loop."""
    import numpy as np  # noqa: F811
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt_small, gpt_tiny

    bench_gptgen.last_note = None
    bench_gptgen.nonstandard_shape = False
    fallback = os.path.exists(GPTGEN_FALLBACK_FLAG)
    if smoke:
        batch, prompt, new, iters = (2, 8, 8, 2)
    elif fallback:
        # a previous session recorded a mid-compile timeout: halve the
        # decode module (shape drives compile time) so this session
        # gets a number instead of another wedge
        batch, prompt, new, iters = (4, 64, 64, 5)
        bench_gptgen.last_note = (
            f'fallback shape b{batch} p{prompt} n{new} '
            '(previous session timed out mid-compile)')
        bench_gptgen.nonstandard_shape = True
        log(f'gptgen: {bench_gptgen.last_note}')
    else:
        batch, prompt, new, iters = (8, 128, 128, 5)
    paddle.seed(0)
    model = gpt_tiny() if smoke else gpt_small(max_seq_len=prompt + new,
                                               dropout=0.0)
    model.eval()
    rs = np.random.RandomState(0)
    V = model.config.vocab_size
    ids = rs.randint(0, V, size=(batch, prompt)).astype('int64')
    t0 = time.time()
    out = model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                         temperature=0)
    np.asarray(out.value)
    log(f'gptgen warmup (incl. compile): {time.time() - t0:.1f}s')
    marker = os.environ.get('BENCH_COMPILE_MARKER')
    if marker:      # tell the no-kill parent the compile is behind us
        open(marker, 'w').close()
    t0 = time.time()
    for i in range(iters):
        out = model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                             temperature=0, seed=i)
        np.asarray(out.value)   # force readback
    dt = time.time() - t0
    v = batch * new * iters / dt
    log(f'gpt-generate: {iters} x {new} tokens in {dt:.2f}s '
        f'({v:.0f} tokens/s decoded)')
    if fallback and not smoke:
        # a completed fallback run retires the flag: the orphaned /
        # post-compile full-shape module has had a session to land in
        # the persistent XLA cache, so the NEXT session retries full
        # shape (and re-arms on another timeout)
        try:
            os.remove(GPTGEN_FALLBACK_FLAG)
            log('gptgen: fallback flag cleared — next session retries '
                'the full shape')
        except OSError:
            pass
    return v


def _serve_setup(smoke):
    """Shared model + engine config + request set for the serve bench
    and the --serve-smoke gate: tiny model on CPU smoke, gpt-small on
    chip runs; batch 64 continuous batching either way."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import gpt_small, gpt_tiny
    from paddle_tpu.serving import ServeConfig, poisson_requests

    paddle.seed(0)
    if smoke:
        # hidden 256: big enough that batch-64 decode genuinely reuses
        # weights per step (the continuous-batching premise) while the
        # ~10 bucket modules still compile in well under a minute
        model = gpt_tiny(hidden_size=256, num_heads=4, num_layers=4,
                         max_seq_len=64)
        cfg = ServeConfig(block_size=8, max_slots=64, decode_span=8,
                          prompt_buckets=(8, 16),
                          batch_buckets=(8, 64), prefill_batch=8,
                          max_model_len=48, temperature=0.0)
        n, rate = 96, 2000.0
        prompt_lens, new_tokens = (5, 7, 8, 12, 16), (16, 24)
    else:
        model = gpt_small(max_seq_len=256, dropout=0.0)
        cfg = ServeConfig(block_size=16, max_slots=64, decode_span=8,
                          prompt_buckets=(32, 64),
                          batch_buckets=(8, 64), max_model_len=160,
                          temperature=0.0)
        n, rate = 128, 100.0
        prompt_lens, new_tokens = (24, 32, 48, 64), (32, 64)
    model.eval()

    def load(seed):
        return poisson_requests(
            n, rate_rps=rate, prompt_lens=prompt_lens,
            new_tokens=new_tokens, vocab_size=model.config.vocab_size,
            seed=seed, deadline_s=600.0)

    return model, cfg, load


def bench_serve(smoke):
    """Continuous-batching serving throughput (paddle_tpu/serving):
    batch-64 paged-KV decode under seeded Poisson load with mixed
    prompt/output lengths — decoded tokens/sec/chip plus p99 TTFT,
    the ROADMAP item-1 target metrics."""
    import jax
    from paddle_tpu.serving import ServingEngine

    model, cfg, load = _serve_setup(smoke)
    eng = ServingEngine(model, cfg)
    t0 = time.time()
    eng.warmup()                        # every declared bucket module
    eng.run(load(seed=3))               # then a shakeout load
    log(f'serve warmup (incl. compile): {time.time() - t0:.1f}s '
        f'({eng.compile_count} modules)')
    marker = os.environ.get('BENCH_COMPILE_MARKER')
    if marker:
        open(marker, 'w').close()
    rep = eng.run(load(seed=7))
    chips = jax.device_count()
    v = (rep['tokens_per_s'] or 0.0) / max(1, chips)
    bench_serve.last_note = (
        f"p99 TTFT {rep['ttft_p99_s']:.3f}s, "
        f"{rep['interventions']} interventions, "
        f"batch<= {cfg.max_slots}" if rep['ttft_p99_s'] else None)
    log(f"serve: {rep['decoded_tokens']} tokens in "
        f"{rep['wall_s']:.2f}s ({v:.0f} tokens/s/chip), "
        f"p99 TTFT {rep['ttft_p99_s']}")
    if rep['audit']:
        raise RuntimeError(f'serve invariants violated: {rep["audit"]}')
    return v


def bench_lenet(smoke):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.parallel import ParallelTrainer

    batch, iters, warmup = (64, 4, 2) if smoke else (256, 50, 5)
    paddle.seed(0)
    net = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    ce = nn.CrossEntropyLoss()
    trainer = ParallelTrainer(net, opt, lambda out, y: ce(out, y))
    rs = np.random.RandomState(0)
    x = jax.device_put(rs.randn(batch, 1, 28, 28).astype('float32'))
    y = jax.device_put(
        rs.randint(0, 10, size=(batch, 1)).astype('int64'))
    loss = None
    for _ in range(warmup):
        loss = trainer.step(x, y)
    jax.block_until_ready(loss)
    l0 = float(np.asarray(loss))
    dt = _time_steps(trainer.step, iters, x, y)
    loss = trainer.step(x, y)
    l1 = float(np.asarray(loss))
    assert np.isfinite(l1) and l1 < l0 * 1.5, (l0, l1)  # sanity anchor
    v = batch * iters / dt
    log(f'lenet: {iters} steps in {dt:.2f}s ({v:.0f} imgs/s) '
        f'loss {l0:.3f}->{l1:.3f}')
    return v


CONFIGS = {
    'lenet': bench_lenet,
    'resnet': bench_resnet,
    'bert': bench_bert,
    'gpt': bench_gpt,
    'widedeep': bench_widedeep,
    'longctx': bench_longctx,
    'serve': bench_serve,
    # gptgen runs LAST: it is the only config that has ever wedged the
    # dev tunnel mid-run (r4: 900s timeout, tunnel dead afterwards) —
    # a repeat must not cost the other configs their numbers.
    'gptgen': bench_gptgen,
}

# Per-config timeout scale.  Killing a child mid-compile is what WEDGES
# the tunnel (round-2: 5h outage), so the configs whose remote compile
# is slow get a generous window instead of a kill: gptgen's whole
# prefill+decode scan is one big XLA module; serve compiles one module
# per declared bucket.
TIMEOUT_SCALE = {'gptgen': 3, 'longctx': 2, 'serve': 2}

METRIC_NAMES = {
    'resnet': 'resnet50_bf16_train_throughput',
    'bert': 'bert_base_bf16_pretrain_throughput',
    'gpt': 'gpt2_small_bf16_train_throughput',
    'gptgen': 'gpt2_small_kvcache_decode_throughput',
    'longctx': 'gpt2_small_t4096_train_throughput',
    'widedeep': 'widedeep_sparse_train_throughput',
    'lenet': 'lenet_train_throughput',
    'serve': 'gpt_serve_continuous_batching_decode_throughput',
}

UNITS = {
    'lenet': 'imgs/sec/chip',
    'resnet': 'imgs/sec/chip',
    'bert': 'tokens/sec/chip',
    'gpt': 'tokens/sec/chip',
    'gptgen': 'decoded tokens/sec/chip',
    'widedeep': 'examples/sec/chip',
    'longctx': 'tokens/sec/chip',
    'serve': 'decoded tokens/sec/chip',
}


def _run_one(name, smoke):
    """Run one config in-process; returns its result dict."""
    import jax
    from paddle_tpu.distributed import env as dist_env
    dist_env.set_mesh(None)
    try:
        v = CONFIGS[name](smoke)
        res = {'value': round(v, 2), 'unit': UNITS[name],
               'vs_baseline': round(v / BASELINES[name], 4),
               'platform': jax.default_backend()}
        note = getattr(CONFIGS[name], 'last_note', None)
        if note:
            res['note'] = note
        if getattr(CONFIGS[name], 'nonstandard_shape', False):
            # e.g. the gptgen halved-shape fallback: the baseline
            # constant is calibrated for the full shape, so a ratio
            # would report a phantom regression
            res['vs_baseline'] = None
        return res
    except Exception as e:  # one config failing must not hide the rest
        log(f'{name} FAILED: {e!r}')
        return {'value': None, 'unit': UNITS[name],
                'error': repr(e)[:200]}


def _run_isolated(name, smoke, timeout_s):
    """Run one config in a SUBPROCESS with a hard timeout: a wedged
    accelerator tunnel (or a pathological compile) in one config must
    not take down the whole artifact."""
    import subprocess
    cmd = [sys.executable, os.path.abspath(__file__), '--config', name,
           '--single-json']
    if smoke:
        cmd.append('--smoke')
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired as exc:
        # the child's progress log says where it hung (compile vs iters)
        tail = (exc.stderr or '')
        if isinstance(tail, bytes):
            tail = tail.decode('utf-8', 'replace')
        log(f'{name} TIMED OUT after {timeout_s}s; child stderr tail: '
            f'{tail[-400:]}')
        return {'value': None, 'unit': UNITS[name],
                'error': f'timeout after {timeout_s}s',
                'stderr_tail': tail[-400:]}
    parsed = _last_json_dict(proc.stdout)
    if parsed is not None:
        return parsed
    log(f'{name} produced no JSON (rc={proc.returncode}): '
        f'{proc.stderr[-300:]}')
    return {'value': None, 'unit': UNITS[name],
            'error': f'no output (rc={proc.returncode})'}


# configs whose child must never be killed mid-compile: gptgen's whole
# prefill+decode scan is one huge XLA module whose remote compile hit
# ~900s in round 4, and killing a python mid-TPU-compile wedges the
# shared tunnel for hours (round-2: 5h outage, round-4: two sessions)
NO_KILL = {'gptgen'}


def _arm_gptgen_fallback(reason):
    os.makedirs(CHIP_OUT, exist_ok=True)
    with open(GPTGEN_FALLBACK_FLAG, 'w') as f:
        json.dump({'at': time.strftime('%Y-%m-%dT%H:%M:%SZ',
                                       time.gmtime()),
                   'reason': reason}, f)
    log(f'gptgen fallback armed: {reason}')


def _last_json_dict(text):
    """Last JSON-dict line of a child's stdout, or None."""
    for line in reversed(text.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):   # stray numeric lines don't count
            return parsed
    return None


def _run_no_kill(name, smoke, timeout_s):
    """Like _run_isolated, but safe for tunnel-wedging compiles:
    - the child signals 'compile done' via a marker file; past the
      timeout we only kill it AFTER that marker exists (killing during
      execution is safe; killing during compile wedges the tunnel);
    - a child still compiling at 2x the budget is ORPHANED, not killed
      — it finishes the compile eventually and warms the persistent
      XLA cache, so the next session's attempt is fast;
    - either timeout path arms the halved-shape fallback flag so the
      next attempt compiles a much smaller module."""
    import subprocess
    import tempfile
    # real runs leave their scratch in the committed evidence dir;
    # smoke runs (CI) must not litter it
    scratch = tempfile.mkdtemp(prefix='bench_nokill_') if smoke \
        else CHIP_OUT
    os.makedirs(scratch, exist_ok=True)
    marker = os.path.join(scratch, f'{name}_compile_done.marker')
    if os.path.exists(marker):
        os.remove(marker)
    cmd = [sys.executable, os.path.abspath(__file__), '--config', name,
           '--single-json']
    if smoke:
        cmd.append('--smoke')
    out_p = os.path.join(scratch, f'{name}_child.out')
    err_p = os.path.join(scratch, f'{name}_child.err')
    env = dict(os.environ, BENCH_COMPILE_MARKER=marker)
    with open(out_p, 'w') as so, open(err_p, 'w') as se:
        proc = subprocess.Popen(cmd, stdout=so, stderr=se, env=env,
                                start_new_session=True)
    def _cleanup_scratch():
        if smoke:
            import shutil
            shutil.rmtree(scratch, ignore_errors=True)

    deadline = time.time() + timeout_s
    hard_deadline = deadline + timeout_s
    while proc.poll() is None:
        time.sleep(5)
        now = time.time()
        if proc.poll() is not None:
            break   # finished during the sleep — its result counts
        if now > deadline and os.path.exists(marker):
            proc.kill()
            proc.wait()
            if not smoke:   # a CPU smoke hiccup must not degrade the
                            # next REAL session to the halved shape
                _arm_gptgen_fallback(
                    f'post-compile timeout after {timeout_s}s')
            _cleanup_scratch()
            return {'value': None, 'unit': UNITS[name],
                    'error': f'timeout after {timeout_s}s '
                             '(compile had finished; child killed)'}
        if now > hard_deadline:
            if not smoke:
                _arm_gptgen_fallback(
                    f'compile still running at {2 * timeout_s}s')
            # orphan keeps writing into scratch: do NOT clean it here
            return {'value': None, 'unit': UNITS[name],
                    'error': f'compile exceeded {2 * timeout_s}s; '
                             'child orphaned (not killed — a '
                             'mid-compile kill wedges the tunnel) to '
                             'finish warming the XLA cache',
                    'orphan_pid': proc.pid}
    try:
        with open(out_p) as f:
            stdout = f.read()
    except OSError:
        stdout = ''
    parsed = _last_json_dict(stdout)
    try:
        with open(err_p) as f:
            err_tail = f.read()[-300:]
    except OSError:
        err_tail = ''
    _cleanup_scratch()
    if parsed is not None:
        return parsed
    log(f'{name} produced no JSON (rc={proc.returncode}): {err_tail}')
    return {'value': None, 'unit': UNITS[name],
            'error': f'no output (rc={proc.returncode})'}


# device memory_stats rows from the most recent successful preflight
# probe (TPU/GPU backends; [] on CPU which exposes none) — read at
# artifact-assembly time so every chip artifact records how much HBM
# the pool offered BEFORE any config ran
_preflight_memstats = None


def _device_preflight_once(timeout_s):
    """Run one tiny jitted op in a subprocess: (True, None) iff the
    device stack (incl. a possibly-wedged dev tunnel) answers within
    timeout_s, else (False, reason) — the reason (timeout vs crash,
    with rc + stderr tail) lands in the bench artifact so a failed
    chip round is diagnosable after the fact (BENCH rounds r02-r05
    all failed preflight with NOTHING captured).  Executed in a child
    so a hang cannot wedge US.  A passing probe also captures each
    device's ``memory_stats()`` (in-use/peak/limit) into the
    artifact's ``device_mem`` — the live-truth baseline the memory
    observatory's per-run numbers are read against."""
    import subprocess
    global _preflight_memstats
    code = ('import json, jax, jax.numpy as jnp, numpy as np\n'
            'v = float(np.asarray(jax.jit(lambda a: a.sum())'
            '(jnp.ones((8, 8)))))\n'
            'rows = []\n'
            'for d in jax.local_devices():\n'
            '    st = d.memory_stats()\n'
            '    if st:\n'
            '        rows.append({"device": str(d.id),\n'
            '                     "bytes_in_use":'
            ' st.get("bytes_in_use"),\n'
            '                     "peak_bytes_in_use":'
            ' st.get("peak_bytes_in_use"),\n'
            '                     "bytes_limit":'
            ' st.get("bytes_limit")})\n'
            'print("PREFLIGHT_OK", v, json.dumps(rows))\n')
    try:
        proc = subprocess.run([sys.executable, '-c', code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f'device preflight attempt timed out after {timeout_s}s')
        return False, (f'timeout after {timeout_s:.0f}s (tiny jitted '
                       'op never answered — wedged tunnel?)')
    if 'PREFLIGHT_OK' in proc.stdout:
        for line in proc.stdout.splitlines():
            if line.startswith('PREFLIGHT_OK'):
                try:
                    _preflight_memstats = json.loads(
                        line.split(' ', 2)[2])
                except (IndexError, ValueError):
                    pass
                break
        return True, None
    reason = (f'rc={proc.returncode}: '
              f'{(proc.stderr or proc.stdout)[-300:].strip()}')
    log(f'device preflight failed ({reason})')
    return False, reason


def _classify_preflight_reason(reason):
    """Map a captured preflight failure reason onto its retry class.
    'timeout' — the tiny op never answered (wedged tunnel): recovers
    in minutes, worth the longest wait.  'device_unavailable' —
    backend init / device discovery failed loudly: the pool usually
    returns within a minute.  'crash' — the probe process died some
    other way: only transient infra makes a retry worthwhile, so it
    gets the shortest one."""
    r = (reason or '').lower()
    if 'timeout' in r:
        return 'timeout'
    if any(s in r for s in ('unable to initialize backend',
                            'no devices', 'device', 'unavailable',
                            'failed to connect', 'connection')):
        return 'device_unavailable'
    return 'crash'


_PREFLIGHT_RETRY_WAIT_S = {'timeout': 240, 'device_unavailable': 60,
                           'crash': 20}


def _device_preflight(total_budget_s=600):
    """Preflight with one bounded retry PER FAILURE-REASON CLASS: the
    dev tunnel recovers from transient wedges in minutes (round-2
    lesson: a single 180s attempt nulled the whole artifact), but the
    old fixed 0/1/2/4-minute ladder retried a hard crash exactly like
    a wedge — burning four minutes of budget on a failure mode where
    waiting never helps.  Each captured failure reason is classified
    (timeout / device_unavailable / crash) and each CLASS gets one
    retry with its own backoff; a failure mode that repeats after its
    retry gives up immediately, while a mode that MORPHS (timeout ->
    crash) earns the new class's single retry.  Returns
    (ok, attempts) — attempts is the per-try diagnosis list
    (reason + reason_class) that rides into the artifact when every
    try failed."""
    deadline = time.time() + total_budget_s
    attempts = []
    retried = set()
    i = 0
    while True:
        remaining = deadline - time.time()
        if remaining <= 10:
            break
        attempt_s = min(120, max(30, remaining))
        ok, reason = _device_preflight_once(attempt_s)
        if ok:
            if i:
                log('preflight recovered after retry')
            return True, attempts
        cls = _classify_preflight_reason(reason)
        attempts.append({'attempt': i, 'timeout_s': round(attempt_s),
                         'reason': reason, 'reason_class': cls})
        if cls in retried:
            log(f'preflight giving up: {cls} failure repeated after '
                'its retry')
            break
        retried.add(cls)
        wait = _PREFLIGHT_RETRY_WAIT_S.get(cls, 60)
        i += 1
        remaining = deadline - time.time()
        log(f'preflight retry {i} ({cls}): waiting {wait}s for '
            f'recovery ({remaining:.0f}s of budget left)')
        time.sleep(min(wait, max(0, remaining - 60)))
    return False, attempts


def _write_partial(results, smoke=False):
    """Checkpoint the artifact-so-far next to this script.  Smoke runs
    (CI) must NOT overwrite it — round 4 lost a chip session's partial
    numbers to exactly that."""
    if smoke:
        return
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            'BENCH_partial.json')
        with open(path, 'w') as f:
            json.dump(results, f, indent=1)
    except OSError as e:
        log(f'could not write partial artifact: {e}')


def _chaos_preflight(timeout_s=420):
    """--chaos-smoke gate: tools/soak_run.py --smoke on CPU BEFORE any
    chip time is spent — (1) the golden plan-generator and
    shrunk-plan fixtures (property-based chaos machinery cannot drift
    silently), then (2) ONE 2-process ChaosCluster spin of the
    built-in smoke plan: a hung collective (watchdog timeout ->
    coordinated abort -> elastic restart), a SIGKILLed worker (crash
    recovery from the two-phase committed step), a SIGTERM preemption
    (exit 117), and a torn manifest write — the coverage the two old
    single-process chaos_run driver cases provided, now across real
    process boundaries, gated on invariants I1-I7 + bit-exact final
    state on every rank.

    Returns (ok, summary_dict).  Chaos-infra failures (timeout, crash
    of the driver itself) never block the bench — evidence beats a
    dead gate — but invariant VIOLATIONS always do."""
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(repo, 'tools', 'soak_run.py'),
           '--smoke', '--json']
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = json.loads(proc.stdout)
    except Exception as e:
        log(f'chaos preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    cluster = doc.get('cluster') or {}
    summary = {'ok': doc.get('ok'),
               'failures': doc.get('failures', [])[:10],
               'injected': cluster.get('injected', []),
               'incarnations': cluster.get('incarnations'),
               'watchdog_exit_codes':
                   cluster.get('watchdog_exit_codes'),
               'duration_s': cluster.get('duration_s')}
    log(f'chaos preflight: ok={doc.get("ok")} '
        f'({len(cluster.get("injected", []))} faults injected across '
        f'2 procs, incarnations={cluster.get("incarnations")})')
    return bool(doc.get('ok')), summary


def _supervisor_smoke_child():
    """--supervisor-smoke child (forced 8-device CPU mesh): the
    self-healing actuator's acceptance evidence in one process —

    - a dp=8 trainer with the supervisor armed, running with an
      artificial per-step slowdown while on the incumbent mesh (the
      degradation the injected drift reports), receives ONE synthetic
      ``drift_detected`` edge: exactly one remediation must actuate
      (replan with drift-adjusted calibration -> background precompile
      -> boundary swap), the mesh must actually change, steps/sec must
      recover once the swap lands (the slowdown stops with the
      incumbent mesh), and sustained drift inside the cooldown must
      NOT actuate again;
    - a clean run (supervisor armed, no drift) must actuate ZERO
      times.

    Emits one JSON line the parent asserts on."""
    import time as _time
    import paddle_tpu as paddle
    from paddle_tpu import nn, distributed as dist, telemetry
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.telemetry import get_recorder

    events = []
    get_recorder().subscribe(lambda r: events.append(dict(r)))

    def make_trainer():
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                            nn.Linear(256, 64))
        opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                        parameters=net.parameters())
        return ParallelTrainer(
            net, opt, lambda o, y: ((o - y) ** 2).mean(),
            supervisor={'debounce_s': 0.05, 'cooldown_s': 120.0,
                        'margin': 0.0})

    rs = np.random.RandomState(1)
    X = rs.randn(16, 64).astype('float32')
    Y = rs.randn(16, 64).astype('float32')
    out = {}

    # -- run A: injected drift, degraded incumbent -----------------------
    dist.init_parallel_env(axes={'dp': 8})
    tr = make_trainer()
    incumbent = dict(tr.mesh.shape)
    slow_s = 0.05           # the degradation drift is reporting

    def timed_steps(n):
        t0 = _time.perf_counter()
        for _ in range(n):
            tr.step(X, Y)
            if dict(tr.mesh.shape) == incumbent:
                _time.sleep(slow_s)
        return n / (_time.perf_counter() - t0)

    timed_steps(3)                              # warmup + compile
    out['pre_sps'] = round(timed_steps(6), 2)
    telemetry.event('drift_detected', cause='us_ratio',
                    op='all-reduce', instr='bench-smoke',
                    us_ratio=50.0, band=4.0, windows=8)
    deadline = _time.time() + 60
    while _time.time() < deadline:
        if tr._supervisor is not None and tr._supervisor.incidents:
            break
        _time.sleep(0.05)
    timed_steps(2)                              # boundary: apply swap
    out['mesh_before'] = incumbent
    out['mesh_after'] = dict(tr.mesh.shape)
    # sustained drift inside the cooldown: must not actuate again
    for _ in range(3):
        telemetry.event('drift_detected', cause='us_ratio',
                        op='all-reduce', instr='bench-smoke',
                        us_ratio=50.0, band=4.0, windows=8)
        _time.sleep(0.1)
    timed_steps(2)                              # post-swap recompile
    out['post_sps'] = round(timed_steps(6), 2)
    out['losses_finite'] = bool(np.isfinite(
        float(np.asarray(tr.step(X, Y)))))
    tr.stop_supervisor()
    out['swaps'] = sum(1 for e in events if e['kind'] == 'plan_swap')
    out['outcomes'] = [e.get('outcome') for e in events
                       if e['kind'] == 'remediation']
    out['recovered'] = out['post_sps'] > out['pre_sps'] * 1.2

    # -- run B: clean — zero actuations ----------------------------------
    events.clear()
    from paddle_tpu.distributed import env as dist_env
    dist_env.set_mesh(None)
    dist.init_parallel_env(axes={'dp': 8})
    tr2 = make_trainer()
    for _ in range(8):
        tr2.step(X, Y)
    tr2.stop_supervisor()
    out['clean_swaps'] = sum(1 for e in events
                             if e['kind'] in ('plan_swap',
                                              'remediation'))
    out['clean_incidents'] = len(tr2._supervisor.incidents
                                 if tr2._supervisor else [])
    print(json.dumps(out))


def _supervisor_preflight(timeout_s=900):
    """--supervisor-smoke gate: the self-healing runtime must earn
    chip time — injected drift on a dp=8 CPU-mesh trainer must
    produce EXACTLY one plan migration (mesh actually changes,
    steps/sec recovers, sustained drift suppressed by the cooldown),
    and a clean run with the supervisor armed must actuate zero
    times.

    Returns (ok, summary).  Infra failures (timeout, crash of the
    child) never block the bench — evidence beats a dead gate — but a
    missing/double actuation, an unchanged mesh, unrecovered
    throughput, or a clean-run actuation always does."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['XLA_FLAGS'] = ' '.join(
        [t for t in env.get('XLA_FLAGS', '').split()
         if not t.startswith('--xla_force_host_platform_device_count')]
        + ['--xla_force_host_platform_device_count=8'])
    env['PADDLE_TPU_SUPERVISOR'] = '0'      # the child arms explicitly
    cmd = [sys.executable, os.path.abspath(__file__),
           '--supervisor-smoke-child']
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'supervisor preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'supervisor preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    if doc.get('swaps') != 1:
        failures.append(f'expected exactly 1 plan_swap under '
                        f'sustained drift, got {doc.get("swaps")} '
                        f'(outcomes {doc.get("outcomes")})')
    if doc.get('mesh_after') == doc.get('mesh_before'):
        failures.append('mesh did not change across the swap '
                        f'({doc.get("mesh_before")})')
    if not doc.get('recovered'):
        failures.append(f'throughput did not recover after the swap '
                        f'(pre {doc.get("pre_sps")} -> post '
                        f'{doc.get("post_sps")} steps/s)')
    if not doc.get('losses_finite'):
        failures.append('post-swap loss went non-finite')
    if doc.get('clean_swaps'):
        failures.append(f'clean run actuated '
                        f'{doc.get("clean_swaps")} time(s)')
    summary = dict(doc, failures=failures)
    ok = not failures
    log(f'supervisor preflight: {"ok" if ok else "FAIL"} '
        f'(swaps={doc.get("swaps")}, '
        f'{doc.get("mesh_before")} -> {doc.get("mesh_after")}, '
        f'{doc.get("pre_sps")} -> {doc.get("post_sps")} steps/s, '
        f'clean_swaps={doc.get("clean_swaps")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _frontdoor_smoke_child():
    """--frontdoor-smoke-child: the serving front door's acceptance
    evidence against a REAL 2-replica fleet (subprocess workers
    behind serving/router.py), emitted as one JSON line.

    Four drills over one tiny config:

    - overload: a seeded Poisson burst far above pool+queue capacity
      must come back with TYPED rejections only — never an OOM, a
      wedged stream, or a silently lost rid — while every admitted
      request still finishes;
    - clean twin: the same request shapes, gently paced, must shed
      NOTHING and every stream must be bit-exact vs a fresh
      single-engine run of the same rid (per-request positional key
      discipline);
    - replica_kill: a seeded FaultPlan SIGKILLs the serving replica
      mid-stream (ServingFaultInjector's fleet seam); every in-flight
      rid must land terminal with >=1 successful retry on the
      survivor, streams still bit-exact, and the warm spare must be
      promoted to backfill the dead replica;
    - drain: a forced slo_breach latch on one replica must drain it
      (fleet_event, typed 503s for new work) with ZERO dropped
      in-flight tokens, and the fleet keeps serving through the
      other replica.
    """
    import random
    import signal as _signal
    import tempfile
    import threading
    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(repo, 'tools'))
    import serve_fleet
    from paddle_tpu.resilience.chaos import (
        Fault, FaultPlan, ServingFaultInjector)
    from paddle_tpu.serving import Request, RejectReason
    from paddle_tpu.serving.router import FleetFrontend

    doc = {'model': 'tiny',
           'model_kwargs': {'num_layers': 2, 'num_heads': 2,
                            'hidden_size': 32, 'vocab_size': 128,
                            'max_seq_len': 128},
           'block_size': 8, 'max_slots': 4, 'decode_span': 4,
           'num_blocks': 64, 'temperature': 0.7, 'top_k': 8,
           'seed': 13}
    workdir = tempfile.mkdtemp(prefix='frontdoor_smoke_')
    config_path = os.path.join(workdir, 'serve.json')
    with open(config_path, 'w') as f:
        json.dump(doc, f)

    rng = random.Random(20)
    prompts = {}

    def req_shape(rid):
        if rid not in prompts:
            prompts[rid] = ([rng.randrange(1, 120)
                             for _ in range(rng.randrange(4, 9))],
                            rng.randrange(6, 10))
        return prompts[rid]

    def run_many(router, rids, pace_s=0.0, on_token=None):
        results, threads = {}, []

        def one(rid):
            prompt, n = req_shape(rid)
            try:
                results[rid] = router.generate(
                    prompt, n, rid,
                    on_token=(None if on_token is None else
                              (lambda i, t, _r=rid:
                               on_token(_r, i, t))))
            except Exception as e:       # a crash IS the finding
                results[rid] = {'state': 'crashed',
                                'reason': repr(e)[:120]}
        for rid in rids:
            t = threading.Thread(target=one, args=(rid,),
                                 daemon=True)
            t.start()
            threads.append(t)
            if pace_s:
                time.sleep(pace_s)
            else:
                time.sleep(rng.expovariate(1 / 0.002))
        for t in threads:
            t.join(timeout=120)
        return results

    def shed_total(router):
        n = 0
        for rep in router.replicas + router.spares:
            if not rep.alive():
                continue
            try:
                st = rep.status(timeout_s=2.0)
            except OSError:
                continue
            n += sum((st.get('shed_counts') or {}).values())
        return n

    def single_engine_tokens(rids):
        eng = serve_fleet.build_engine(doc)
        out = {}
        for rid in rids:
            prompt, n = req_shape(rid)
            r = Request(rid, prompt, max_new_tokens=n)
            eng.submit(r)
            eng.run()
            out[rid] = [int(t) for t in r.tokens]
        return out

    router = serve_fleet.launch_fleet(config_path, replicas=2,
                                      spares=1, workdir=workdir)
    door = FleetFrontend(router).start()
    summary = {'workdir': workdir}
    try:
        # -- drill 1: Poisson overload --------------------------------
        over_rids = [f'ov-{i}' for i in range(24)]
        res = run_many(router, over_rids)
        states = {}
        for r in res.values():
            states[r['state']] = states.get(r['state'], 0) + 1
        typed = all(r.get('reason') in RejectReason.ALL
                    for r in res.values()
                    if r['state'] == 'rejected')
        summary['overload'] = {
            'total': len(over_rids), 'states': states,
            'sheds': shed_total(router), 'typed': typed,
            'invariants': router.check_invariants(),
            'replicas_alive': sum(r.alive()
                                  for r in router.replicas)}

        # -- drill 2: clean twin, bit-exact vs single engine ----------
        shed0 = shed_total(router)
        clean_rids = [f'cl-{i}' for i in range(4)]
        res = run_many(router, clean_rids, pace_s=0.4)
        want = single_engine_tokens(clean_rids)
        summary['clean'] = {
            'finished': sum(r['state'] == 'finished'
                            for r in res.values()),
            'total': len(clean_rids),
            'sheds': shed_total(router) - shed0,
            'bitexact': all(res[rid].get('tokens') == want[rid]
                            for rid in clean_rids
                            if res[rid]['state'] == 'finished'),
            'invariants': router.check_invariants()}

        # -- drill 3: seeded replica_kill mid-stream ------------------
        plan = FaultPlan(seed=0, faults=[
            Fault('replica_kill', after_tokens=3, count=1)])
        inj = ServingFaultInjector(plan)
        kill_lock = threading.Lock()

        def tap(rid, i, tok):
            with kill_lock:
                fired = inj.fleet_faults(rid, i + 1)
            for _f in fired:
                entry = router.ledger.get(rid)
                victim = router.replica(entry['replicas'][-1])
                if victim is not None:
                    victim.kill(_signal.SIGKILL)

        kill_rids = [f'ki-{i}' for i in range(3)]
        res = run_many(router, kill_rids, pace_s=0.05, on_token=tap)
        want = single_engine_tokens(kill_rids)
        summary['kill'] = {
            'injected': list(inj.injected),
            'finished': sum(r['state'] == 'finished'
                            for r in res.values()),
            'total': len(kill_rids),
            'retried': sum(r.get('retried', 0) for r in res.values()),
            'bitexact': all(res[rid].get('tokens') == want[rid]
                            for rid in kill_rids
                            if res[rid]['state'] == 'finished'),
            'promoted': sum(1 for e in router.events
                            if e['action'] == 'promote'),
            'invariants': router.check_invariants()}

        # -- drill 4: forced-latch drain, zero dropped in-flight ------
        draining = [r for r in router.dispatchable()]
        target = draining[0] if draining else None
        drain_res = {}
        if target is not None:
            t = threading.Thread(
                target=lambda: drain_res.update(one=router.generate(
                    *req_shape('dr-0'), 'dr-0')), daemon=True)
            # pin dispatch: every other replica momentarily excluded
            # is overkill for a smoke — just start the stream, then
            # latch the alert on WHICHEVER replica took it
            t.start()
            while 'dr-0' not in router.ledger or \
                    not router.ledger['dr-0']['replicas']:
                time.sleep(0.01)
            owner = router.replica(
                router.ledger['dr-0']['replicas'][-1])
            owner.post_json('/admin/alert/slo_breach')
            router.health_tick()        # must drain the owner
            t.join(timeout=120)
            entry = router.ledger['dr-0']
            want = single_engine_tokens(['dr-0'])['dr-0']
            summary['drain'] = {
                'owner': owner.name,
                'drained': owner.draining,
                'state': entry['state'],
                'bitexact': entry['tokens'] == want,
                'still_serving': bool(router.dispatchable()),
                'drain_events': sum(1 for e in router.events
                                    if e['action'] == 'drain'),
                'invariants': router.check_invariants()}
        summary['fleet_actions'] = sorted(
            {e['action'] for e in router.events})
        summary['ok'] = True
    finally:
        try:
            door.stop()
            router.stop()
        except Exception:
            pass
    print(json.dumps(summary))


def _frontdoor_preflight(timeout_s=900):
    """--frontdoor-smoke gate: the serving front door must earn chip
    time — overload sheds TYPED (never OOM / silent loss), a clean
    twin sheds nothing and is bit-exact vs single-engine, a
    mid-stream replica SIGKILL leaves every in-flight rid terminal
    with >=1 successful bit-exact retry plus a promoted warm spare,
    and a forced-latch drain drops zero in-flight tokens.

    Returns (ok, summary).  Infra failures (timeout, dead child)
    never block the bench — evidence beats a dead gate — but any
    violated front-door invariant always does."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    cmd = [sys.executable, os.path.abspath(__file__),
           '--frontdoor-smoke-child']
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'frontdoor preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'frontdoor preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    over = doc.get('overload') or {}
    if not over.get('sheds'):
        failures.append('overload burst shed nothing — admission '
                        'control never engaged')
    if not over.get('typed'):
        failures.append('overload produced an UNTYPED rejection')
    if over.get('states', {}).get('crashed') \
            or over.get('states', {}).get('failed'):
        failures.append(f'overload lost requests untyped: '
                        f'{over.get("states")}')
    if over.get('replicas_alive', 0) < 2:
        failures.append('a replica died under pure overload (OOM?)')
    clean = doc.get('clean') or {}
    if clean.get('sheds'):
        failures.append(f'clean twin shed {clean["sheds"]} '
                        'request(s)')
    if clean.get('finished') != clean.get('total'):
        failures.append(f'clean twin: {clean.get("finished")} of '
                        f'{clean.get("total")} finished')
    if not clean.get('bitexact'):
        failures.append('clean-twin streams not bit-exact vs '
                        'single-engine')
    kill = doc.get('kill') or {}
    if kill.get('finished') != kill.get('total'):
        failures.append(f'replica_kill: {kill.get("finished")} of '
                        f'{kill.get("total")} in-flight reached '
                        'finished')
    if not kill.get('retried'):
        failures.append('replica_kill: no in-flight request was '
                        'retried on a survivor')
    if not kill.get('bitexact'):
        failures.append('replica_kill: a resumed stream diverged '
                        'from single-engine')
    if not kill.get('promoted'):
        failures.append('replica_kill: warm spare never promoted')
    drain = doc.get('drain') or {}
    if not drain.get('drained'):
        failures.append('forced slo_breach latch did not drain the '
                        'owning replica')
    if drain.get('state') != 'finished' or not drain.get('bitexact'):
        failures.append('drain dropped or corrupted the in-flight '
                        'stream')
    if not drain.get('still_serving'):
        failures.append('fleet stopped serving after the drain')
    for phase in ('overload', 'clean', 'kill', 'drain'):
        probs = (doc.get(phase) or {}).get('invariants')
        if probs:
            failures.append(f'{phase}: router invariants violated: '
                            f'{probs[:3]}')
    summary = dict(doc, failures=failures)
    summary.pop('workdir', None)
    ok = not failures
    log(f'frontdoor preflight: {"ok" if ok else "FAIL"} '
        f'(overload {over.get("states")}, sheds={over.get("sheds")}, '
        f'kill retried={kill.get("retried")} '
        f'bitexact={kill.get("bitexact")}, '
        f'drain={drain.get("state")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _threads_smoke_child():
    """--threads-smoke child (forced 8-device CPU mesh): the runtime
    lock checker's acceptance evidence in one process —

    - ARMED window (analysis.lockcheck.install): a dp=8 trainer runs
      real steps and the serving engine completes a smoke load while
      every paddle_tpu-constructed lock is instrumented; the checker
      must record zero lock-order cycles and zero unguarded accesses,
      and must neither deadlock nor crash either workload;
    - UNARMED re-run of the identical trainer: losses must match the
      armed run bit-exactly (observation must not perturb training).

    Emits one JSON line the parent asserts on."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, distributed as dist
    from paddle_tpu.analysis import lockcheck
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.serving import ServingEngine

    rs = np.random.RandomState(1)
    X = rs.randn(16, 64).astype('float32')
    Y = rs.randn(16, 64).astype('float32')

    def run_trainer(steps=6):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                            nn.Linear(256, 64))
        opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                        parameters=net.parameters())
        from paddle_tpu.parallel import ParallelTrainer
        tr = ParallelTrainer(net, opt,
                             lambda o, y: ((o - y) ** 2).mean())
        return [float(np.asarray(tr.step(X, Y)))
                for _ in range(steps)]

    out = {'checker_error': None}
    try:
        with lockcheck.install() as chk:
            dist.init_parallel_env(axes={'dp': 8})
            out['armed_losses'] = run_trainer()
            model, cfg, load = _serve_setup(smoke=True)
            eng = ServingEngine(model, cfg)
            eng.warmup()
            rep = eng.run(load(seed=3))
            out['serve_tokens'] = rep['decoded_tokens']
            out['serve_audit'] = rep['audit']
            lrep = chk.report()
            out['locks'] = chk.locks_created
            out['edges'] = lrep.extras['lockcheck']['edges']
            out['cycles'] = len(
                [f for f in lrep if f.rule == 'lock-order-cycle'])
            out['violations'] = len(
                [f for f in lrep if f.rule == 'unguarded-access'])
            out['findings'] = [f.message[:160] for f in lrep]
    except Exception as e:          # checker or guarded run crashed
        out['checker_error'] = repr(e)[:300]
    else:
        dist_env.set_mesh(None)
        dist.init_parallel_env(axes={'dp': 8})
        out['unarmed_losses'] = run_trainer()
        out['bit_exact'] = (out['armed_losses']
                            == out['unarmed_losses'])
    print(json.dumps(out))


def _threads_preflight(timeout_s=900):
    """--threads-smoke gate: the concurrency posture must hold before
    chip time — (a) the static sweep (tpu_lint --threads) over all of
    paddle_tpu/ must report zero HIGH findings, and (b) a dp=8
    trainer plus a serving-engine smoke must complete with the
    runtime lock checker armed: zero lock-order cycles, zero
    unguarded accesses, zero checker crashes, and armed-vs-unarmed
    losses bit-exact (observation never perturbs training).

    Returns (ok, summary).  Infra failures (timeout, child crash)
    never block the bench — evidence beats a dead gate — but a HIGH
    lint finding, a cycle, a violation, or a loss mismatch always
    does."""
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['XLA_FLAGS'] = ' '.join(
        [t for t in env.get('XLA_FLAGS', '').split()
         if not t.startswith('--xla_force_host_platform_device_count')]
        + ['--xla_force_host_platform_device_count=8'])
    env['PADDLE_TPU_LOCKCHECK'] = '0'       # the child arms explicitly
    failures = []
    summary = {}
    # -- (a) static sweep: zero HIGH across the package ------------------
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, 'tools', 'tpu_lint.py'),
             'paddle_tpu/', '--threads', '--json', '--fail-on',
             'never'],
            capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=repo)
        # tpu_lint --json pretty-prints one multi-line document (not
        # the one-line-JSON child protocol _last_json_dict parses)
        doc = json.loads(proc.stdout)
    except Exception as e:
        log(f'threads lint sweep skipped ({e!r})')
        doc = None
    if doc is not None:
        summary['lint'] = {'counts': doc.get('counts'),
                           'files': (doc.get('extras', {})
                                     .get('threads', {}).get('files'))}
        high = (doc.get('counts') or {}).get('high', 0)
        if high:
            rules = sorted({f.get('rule') for f in doc.get('findings',
                                                           ())
                            if f.get('severity') == 'high'})
            failures.append(f'{high} HIGH concurrency finding(s) in '
                            f'paddle_tpu/ ({", ".join(rules)})')
    # -- (b) armed runtime smoke -----------------------------------------
    cmd = [sys.executable, os.path.abspath(__file__),
           '--threads-smoke-child']
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'threads smoke skipped ({e!r})')
        doc = {'error': repr(e)[:200]}
    if doc is None:
        log(f'threads smoke skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        doc = {'error': f'no output (rc={proc.returncode})'}
    summary['smoke'] = {k: doc.get(k) for k in
                        ('locks', 'edges', 'cycles', 'violations',
                         'serve_tokens', 'bit_exact', 'checker_error',
                         'error', 'findings')}
    if doc.get('checker_error'):
        failures.append('armed run crashed: '
                        + str(doc['checker_error']))
    if doc.get('cycles'):
        failures.append(f'{doc["cycles"]} lock-order cycle(s) under '
                        'the armed trainer+engine run')
    if doc.get('violations'):
        failures.append(f'{doc["violations"]} unguarded cross-thread '
                        'access(es) under the armed run')
    if 'bit_exact' in doc and not doc.get('bit_exact'):
        failures.append('armed vs unarmed trainer losses diverged '
                        '(observation perturbed training)')
    if doc.get('serve_audit'):
        failures.append(f'serve invariants violated under the armed '
                        f'engine: {doc["serve_audit"]}')
    summary['failures'] = failures
    ok = not failures
    sm = summary.get('smoke', {})
    log(f'threads preflight: {"ok" if ok else "FAIL"} '
        f'(high={((summary.get("lint") or {}).get("counts") or {}).get("high")}, '
        f'locks={sm.get("locks")}, edges={sm.get("edges")}, '
        f'cycles={sm.get("cycles")}, violations={sm.get("violations")}, '
        f'bit_exact={sm.get("bit_exact")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _spmd_smoke_child():
    """--spmd-smoke child: the SPMD-contract runtime evidence —

    (a) INJECTED: a 2-proc ChaosCluster with a rank-gated skipped
        collective (``collective_skip`` on rank 1): the merged run
        telemetry must contain a ``collective_mismatch`` event that
        names the exact seeded call site (the soak worker's allreduce
        line) no later than the first generic ``timeout`` event, with
        invariants I1-I7 and bit-exact finals intact;
    (b) UNINJECTED twin (same cluster shape, empty plan): zero
        ``collective_mismatch`` events;
    (c) a ledger-ON trainer loop under a device->host transfer guard
        (the ledger must add no syncs), bit-exact with equal compile
        counts vs a ledger-OFF run.

    Emits one JSON line the parent asserts on."""
    import tempfile
    import contextlib
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.resilience.chaos import (
        ChaosCluster, FaultPlan, load_run_events)

    out = {}
    repo = os.path.dirname(os.path.abspath(__file__))
    # the seeded call site: the soak worker's per-step allreduce
    site_line = None
    with open(os.path.join(repo, 'tools', 'soak_run.py')) as f:
        for no, line in enumerate(f, 1):
            if "transport.allreduce(w, 'mean'" in line:
                site_line = no
                break
    out['seeded_site'] = (f'soak_run.py:{site_line}'
                         if site_line else None)

    def _spin(faults, tag):
        plan = FaultPlan(seed=11, name=f'spmd-smoke-{tag}',
                         faults=faults)
        cluster = ChaosCluster(
            procs=2, plan=plan, steps=10, save_every=2,
            collective_timeout_s=8.0, watchdog='step=60,grace=2',
            deadline_s=150.0)
        rep = cluster.run()
        events = load_run_events(cluster.workdir)
        return rep, events

    # -- (a) injected skip ----------------------------------------------
    try:
        rep, events = _spin(
            [{'kind': 'collective_skip', 'at_step': 5, 'rank': 1,
              'count': 1}], 'injected')
        mm = [e for e in events
              if e.get('kind') == 'collective_mismatch']
        to = [e for e in events if e.get('kind') == 'timeout']
        out['injected_ok'] = rep.get('ok')
        out['injected_rc'] = rep.get('rc')
        out['violations'] = (rep.get('violations') or [])[:4]
        out['skip_injected'] = any(
            e.get('fault') == 'collective_skip'
            for e in rep.get('injected', ()))
        out['mismatch_events'] = len(mm)
        out['timeout_events'] = len(to)
        sites = [s for e in mm
                 for s in (e.get('sites') or {}).values()]
        out['mismatch_sites'] = sorted(set(sites))[:4]
        out['site_attributed'] = bool(
            out['seeded_site'] and out['seeded_site'] in sites)
        if mm and to:
            out['mismatch_before_timeout'] = (
                min(e.get('ts') or 0 for e in mm)
                <= min(e.get('ts') or 0 for e in to))
    except Exception as e:
        out['injected_error'] = repr(e)[:300]

    # -- (b) uninjected twin --------------------------------------------
    try:
        rep, events = _spin([], 'twin')
        out['twin_ok'] = rep.get('ok')
        out['twin_mismatch_events'] = len(
            [e for e in events
             if e.get('kind') == 'collective_mismatch'])
    except Exception as e:
        out['twin_error'] = repr(e)[:300]

    # -- (c) ledger-on trainer: sync-free, bit-exact, equal compiles ----
    rs = np.random.RandomState(0)
    X = rs.randn(8, 16).astype('float32')
    Y = rs.randn(8, 4).astype('float32')

    def _losses(ledger_on):
        from paddle_tpu.distributed.collective import reset_ledgers
        os.environ['PADDLE_TPU_COLLECTIVE_LEDGER'] = \
            '1' if ledger_on else '0'
        reset_ledgers()
        telemetry.reset()
        telemetry.enable(None, flush_interval=4)
        try:
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                                nn.Linear(32, 4))
            opt = paddle.optimizer.Momentum(
                learning_rate=0.01, parameters=net.parameters())
            from paddle_tpu.parallel import ParallelTrainer
            tr = ParallelTrainer(net, opt,
                                 lambda o, y: ((o - y) ** 2).mean())
            tr.step(X, Y)           # compile outside the guard
            guard = (jax.transfer_guard_device_to_host('disallow')
                     if ledger_on else contextlib.nullcontext())
            losses = []
            with guard:
                for _ in range(6):
                    losses.append(tr.step(X, Y))
            compiles = len(telemetry.events('compile'))
            return [float(np.asarray(l)) for l in losses], compiles
        finally:
            telemetry.disable()
            telemetry.reset()
            os.environ.pop('PADDLE_TPU_COLLECTIVE_LEDGER', None)

    try:
        on_losses, on_compiles = _losses(True)
        out['sync_free_ok'] = True
        off_losses, off_compiles = _losses(False)
        out['bit_exact'] = on_losses == off_losses
        out['equal_compiles'] = on_compiles == off_compiles
    except Exception as e:
        out['sync_free_ok'] = False
        out['sync_free_error'] = repr(e)[:300]
    print(json.dumps(out))


def _spmd_preflight(timeout_s=900):
    """--spmd-smoke gate: the SPMD contract must hold before chip
    time — (a) the static sweep (tpu_lint --spmd) over paddle_tpu/ +
    tools/ must report zero HIGH findings, and (b) the armed runtime
    smoke: an injected rank-gated skipped collective in a 2-proc
    ChaosCluster must be attributed (``collective_mismatch`` naming
    the seeded call site, no later than the generic timeout) with
    I1-I7 intact, the uninjected twin must emit zero mismatch events,
    and the ledger-ON trainer loop must be sync-free and bit-exact
    with equal compiles vs ledger-OFF.

    Returns (ok, summary).  Infra failures (timeout, child crash)
    never block the bench — evidence beats a dead gate — but a HIGH
    lint finding, a missed/ghost attribution, a broken invariant, or
    a perturbed trainer always does."""
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['XLA_FLAGS'] = ' '.join(
        [t for t in env.get('XLA_FLAGS', '').split()
         if not t.startswith('--xla_force_host_platform_device_count')]
        + ['--xla_force_host_platform_device_count=8'])
    failures = []
    summary = {}
    # -- (a) static sweep: zero HIGH across package + tools --------------
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, 'tools', 'tpu_lint.py'),
             'paddle_tpu/', 'tools/', '--spmd', '--json', '--fail-on',
             'never'],
            capture_output=True, text=True, timeout=timeout_s,
            env=env, cwd=repo)
        doc = json.loads(proc.stdout)
    except Exception as e:
        log(f'spmd lint sweep skipped ({e!r})')
        doc = None
    if doc is not None:
        summary['lint'] = {'counts': doc.get('counts'),
                           'files': (doc.get('extras', {})
                                     .get('spmd', {}).get('files'))}
        high = (doc.get('counts') or {}).get('high', 0)
        if high:
            rules = sorted({f.get('rule') for f in doc.get('findings',
                                                           ())
                            if f.get('severity') == 'high'})
            failures.append(f'{high} HIGH SPMD finding(s) in '
                            f'paddle_tpu/ + tools/ '
                            f'({", ".join(rules)})')
    # -- (b) armed runtime smoke -----------------------------------------
    cmd = [sys.executable, os.path.abspath(__file__),
           '--spmd-smoke-child']
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'spmd smoke skipped ({e!r})')
        doc = {'error': repr(e)[:200]}
    if doc is None:
        log(f'spmd smoke skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        doc = {'error': f'no output (rc={proc.returncode})'}
    summary['smoke'] = {k: doc.get(k) for k in
                        ('seeded_site', 'injected_ok', 'skip_injected',
                         'mismatch_events', 'timeout_events',
                         'mismatch_sites', 'site_attributed',
                         'mismatch_before_timeout', 'twin_ok',
                         'twin_mismatch_events', 'sync_free_ok',
                         'bit_exact', 'equal_compiles',
                         'injected_error', 'twin_error',
                         'sync_free_error', 'error')}
    if 'error' not in doc:
        if doc.get('injected_error'):
            failures.append('injected cluster spin crashed: '
                            + str(doc['injected_error']))
        else:
            if doc.get('injected_ok') is False:
                failures.append('invariants I1-I7 / finals broke '
                                'under the injected skip: '
                                f'{doc.get("violations")}')
            if doc.get('skip_injected') and not doc.get(
                    'site_attributed'):
                failures.append(
                    'collective_mismatch missed the seeded call site '
                    f'(wanted {doc.get("seeded_site")}, saw '
                    f'{doc.get("mismatch_sites")})')
            if doc.get('mismatch_events') and doc.get(
                    'timeout_events') and not doc.get(
                    'mismatch_before_timeout'):
                failures.append('attribution arrived AFTER the '
                                'generic watchdog timeout')
        if doc.get('twin_error'):
            failures.append('uninjected twin spin crashed: '
                            + str(doc['twin_error']))
        elif doc.get('twin_mismatch_events'):
            failures.append(
                f'{doc["twin_mismatch_events"]} ghost '
                'collective_mismatch event(s) in the clean twin run')
        if doc.get('sync_free_ok') is False:
            failures.append('ledger-ON trainer loop synced '
                            'device->host: '
                            + str(doc.get('sync_free_error')))
        if 'bit_exact' in doc and not doc.get('bit_exact'):
            failures.append('ledger-ON vs ledger-OFF trainer losses '
                            'diverged (recording perturbed training)')
        if 'equal_compiles' in doc and not doc.get('equal_compiles'):
            failures.append('ledger-ON vs ledger-OFF compile counts '
                            'differ (recording perturbed tracing)')
    summary['failures'] = failures
    ok = not failures
    sm = summary.get('smoke', {})
    log(f'spmd preflight: {"ok" if ok else "FAIL"} '
        f'(high={((summary.get("lint") or {}).get("counts") or {}).get("high")}, '
        f'mismatch={sm.get("mismatch_events")}, '
        f'site={sm.get("site_attributed")}, '
        f'twin={sm.get("twin_mismatch_events")}, '
        f'bit_exact={sm.get("bit_exact")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _plan_preflight(timeout_s=600):
    """--plan-smoke gate: run the auto-sharding planner
    (tools/tpu_lint.py --plan) over the built-in gpt/widedeep/lenet
    suite on a virtual dp=8 CPU mesh and compare each target's
    top-ranked plan against the committed goldens
    (tools/plan_goldens.json).  A diff means the cost model or the
    planner's scoring regressed — the same posture as the HLO
    self-lint gate pinning rule behavior.

    Returns (ok, summary_dict).  Planner-infra failures (timeout,
    crash, plan_error) never block the bench — evidence beats a dead
    gate — but a golden MISMATCH always does."""
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    golden_path = os.path.join(repo, 'tools', 'plan_goldens.json')
    try:
        with open(golden_path) as f:
            goldens = json.load(f)
    except (OSError, ValueError) as e:
        log(f'plan preflight skipped (no goldens: {e!r})')
        return True, {'error': repr(e)[:200]}
    chips = int(goldens.get('chips', 8))
    cmd = [sys.executable, os.path.join(repo, 'tools', 'tpu_lint.py'),
           '--plan', '--chips', str(chips), '--json']
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env['XLA_FLAGS'] = ' '.join(
        t for t in env.get('XLA_FLAGS', '').split()
        if not t.startswith('--xla_force_host_platform_device_count'))
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = json.loads(proc.stdout)
    except Exception as e:
        log(f'plan preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc.get('plan_error'):
        log(f'plan preflight skipped (plan_error: '
            f'{doc["plan_error"][:120]})')
        return True, {'error': doc['plan_error'][:200]}
    mismatches = {}
    winners = {}
    for target, want in (goldens.get('winners') or {}).items():
        res = (doc.get('plan') or {}).get(target)
        got = (res or {}).get('winner')
        winners[target] = None if got is None else {
            'mesh': got['mesh'], 'assignment': got['assignment'],
            'fallback': got.get('fallback')}
        if got is None:
            mismatches[target] = {'want': want, 'got': None}
            continue
        got_mesh = {a: s for a, s in got['mesh'].items() if s > 1}
        want_mesh = {a: s for a, s in (want.get('mesh') or {}).items()
                     if s > 1}
        if got_mesh != want_mesh \
                or got['assignment'] != want.get('assignment') \
                or got.get('fallback') != want.get('fallback'):
            mismatches[target] = {'want': want, 'got': winners[target]}
    summary = {'winners': winners, 'mismatches': mismatches,
               'chips': chips}
    log(f'plan preflight: {len(winners)} targets, '
        f'{len(mismatches)} golden mismatches')
    return not mismatches, summary


def _cache_smoke_child(telemetry_dir, smoke):
    """--cache-smoke child: run the lenet trainer + gpt generate cold
    paths once each, reporting time-to-first-step and the compile
    cache's per-target deserialize counts as one JSON line.  The
    parent runs this twice against one cache dir: the second (warm)
    process must deserialize instead of recompiling."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.core import compile_cache as cc

    telemetry.enable(telemetry_dir)
    out = {'cache_enabled': cc.enabled(), 'cache_dir': cc.cache_dir()}

    def delta(before):
        now = cc.stats()
        return {k: now.get(k, 0) - before.get(k, 0)
                for k in ('deserialize_exec', 'serialize_exec',
                          'hit_exec', 'miss_exec')}

    # -- lenet trainer step --------------------------------------------------
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.parallel import ParallelTrainer
    batch = 64 if smoke else 256
    paddle.seed(0)
    net = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    ce = nn.CrossEntropyLoss()
    trainer = ParallelTrainer(net, opt, lambda o, y: ce(o, y))
    rs = np.random.RandomState(0)
    x = rs.randn(batch, 1, 28, 28).astype('float32')
    y = rs.randint(0, 10, size=(batch, 1)).astype('int64')
    before = cc.stats()
    t0 = time.perf_counter()
    loss = trainer.step(x, y)
    jax.block_until_ready(loss)
    out['lenet'] = dict(delta(before),
                        ttfs_s=round(time.perf_counter() - t0, 4),
                        loss=float(np.asarray(loss)))

    # -- gpt generate (kv-cache decode module) -------------------------------
    from paddle_tpu.models.gpt import gpt_small, gpt_tiny
    if smoke:
        b, prompt, new = 2, 8, 8
        model = gpt_tiny()
    else:
        b, prompt, new = 8, 128, 128
        model = gpt_small(max_seq_len=prompt + new, dropout=0.0)
    paddle.seed(0)
    model.eval()
    ids = np.random.RandomState(0).randint(
        0, model.config.vocab_size, (b, prompt)).astype('int64')
    before = cc.stats()
    t0 = time.perf_counter()
    gen = model.generate(paddle.to_tensor(ids), max_new_tokens=new,
                         temperature=0)
    np.asarray(gen.value)
    out['gpt'] = dict(delta(before),
                      ttfs_s=round(time.perf_counter() - t0, 4),
                      tokens=np.asarray(gen.value)[0, -4:].tolist())
    out['stats'] = cc.stats()
    telemetry.disable()
    print(json.dumps(out))


def _cache_preflight(smoke, timeout_s=900):
    """--cache-smoke gate: two COLD PROCESSES share one fresh compile
    cache — the first populates (serialize), the second must record
    >=1 exec-tier deserialize hit per target (lenet trainer step + gpt
    generate) and a lower time-to-first-step, proving every restart /
    cold-start path skips trace+lower.  The warm run's telemetry is
    joined through run_report so the artifact carries the hit rate.

    Returns (ok, summary).  Infra failures (timeout, crash) never
    block the bench — evidence beats a dead gate — but a missing hit
    or a slower warm start always does."""
    import subprocess
    import tempfile
    workdir = tempfile.mkdtemp(prefix='bench_cache_')
    cache = os.path.join(workdir, 'cache')
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PADDLE_TPU_COMPILE_CACHE=cache)
    env.pop('PALLAS_AXON_POOL_IPS', None)
    runs = {}
    for phase in ('cold', 'warm'):
        tel = os.path.join(workdir, f'tel_{phase}')
        cmd = [sys.executable, os.path.abspath(__file__),
               '--cache-smoke-child', '--telemetry-dir', tel]
        if smoke:
            cmd.append('--smoke')
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout_s, env=env)
            doc = _last_json_dict(proc.stdout)
        except Exception as e:
            log(f'cache preflight skipped ({e!r})')
            return True, {'error': repr(e)[:200]}
        if doc is None:
            log(f'cache preflight skipped (no child output, '
                f'rc={proc.returncode}): {proc.stderr[-300:]}')
            return True, {'error': f'no output (rc={proc.returncode})'}
        runs[phase] = doc
    failures = []
    per_target = {}
    tot_cold = tot_warm = 0.0
    for tgt in ('lenet', 'gpt'):
        cold = runs['cold'].get(tgt, {})
        warm = runs['warm'].get(tgt, {})
        des = warm.get('deserialize_exec', 0)
        per_target[tgt] = {
            'cold_ttfs_s': cold.get('ttfs_s'),
            'warm_ttfs_s': warm.get('ttfs_s'),
            'warm_deserialize_hits': des,
        }
        if des < 1:
            failures.append(f'{tgt}: warm run recorded no exec-tier '
                            'deserialize hit')
        tot_cold += cold.get('ttfs_s') or 0.0
        tot_warm += warm.get('ttfs_s') or float('inf')
    # deserialized executables must reproduce the cold numerics
    # exactly — a fingerprint collision handing back the WRONG module
    # would otherwise pass on hit count + speed alone
    if runs['cold'].get('lenet', {}).get('loss') != \
            runs['warm'].get('lenet', {}).get('loss'):
        failures.append(
            f'lenet: warm loss {runs["warm"].get("lenet", {}).get("loss")} '
            f'!= cold {runs["cold"].get("lenet", {}).get("loss")}')
    if runs['cold'].get('gpt', {}).get('tokens') != \
            runs['warm'].get('gpt', {}).get('tokens'):
        failures.append(
            f'gpt: warm tokens {runs["warm"].get("gpt", {}).get("tokens")} '
            f'!= cold {runs["cold"].get("gpt", {}).get("tokens")}')
    if not tot_warm < tot_cold:
        # total, not per-target: CPU smoke compile times compress the
        # per-target margins into the noise floor, but the warm run
        # must still win overall or the cache isn't saving anything
        failures.append(
            f'warm time-to-first-step total {tot_warm:.3f}s not lower '
            f'than cold {tot_cold:.3f}s')
    hit_rate = None
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), 'tools'))
        import run_report as _rr
        jsonls, flights = _rr.discover(
            [os.path.join(workdir, 'tel_warm')])
        events, sources, skew = _rr.load_events(jsonls, flights)
        hit_rate = (_rr.analyze(events, sources, skew)
                    .get('compile_cache'))
    except Exception as e:
        log(f'cache preflight: run_report join failed ({e!r})')
    summary = {'targets': per_target, 'failures': failures,
               'warm_run_report': hit_rate,
               'cache_dir': cache}
    ok = not failures
    log(f'cache preflight: {"ok" if ok else "FAIL"} '
        + ' '.join(f'{t}={d["warm_deserialize_hits"]}hit '
                   f'{d["cold_ttfs_s"]}s->{d["warm_ttfs_s"]}s'
                   for t, d in per_target.items()))
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _profile_smoke_child(telemetry_dir):
    """--profile-smoke child (forced 8-device CPU mesh): capture one
    sampled profiler window on (a) lenet through hapi
    ``fit(profile=…)`` and (b) the dp=8 CPU-mesh ParallelTrainer, then
    prove steps OUTSIDE a window add no host sync (device→host
    transfer guard, the PR-3 proof) with a profiler attached.  Emits
    one JSON line the parent asserts on."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.vision.models import LeNet

    telemetry.enable(telemetry_dir)
    out = {}
    rs = np.random.RandomState(0)

    # (a) lenet via hapi fit(profile=): one window, breakdown gauges
    paddle.seed(0)
    model = paddle.hapi.Model(LeNet())
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
    x = rs.randn(8, 1, 28, 28).astype('float32')
    y = rs.randint(0, 10, size=(8, 1)).astype('int64')
    model.fit([(x, y)] * 6, epochs=1, verbose=0,
              profile={'every': 100, 'steps': 2, 'start': 2,
                       'dir': telemetry_dir})
    caps = telemetry.events('profile_capture')
    out['lenet_windows'] = len(caps)
    out['lenet_errors'] = [c.get('error') for c in caps
                           if c.get('error')]

    # (b) dp=8 mesh trainer: census-matched collective_observed
    prev = dist_env.get_mesh()
    mesh = dist_env.build_mesh({'dp': 8})
    dist_env.set_mesh(mesh)
    try:
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                            nn.Linear(64, 8))
        topt = paddle.optimizer.Adam(learning_rate=1e-3,
                                     parameters=net.parameters())
        mse = nn.MSELoss()
        tr = ParallelTrainer(
            net, topt, lambda o, t: mse(o, t), mesh=mesh,
            profile={'every': 100, 'steps': 2, 'start': 2,
                     'dir': telemetry_dir})
        tx = rs.randn(16, 32).astype('float32')
        ty = rs.randn(16, 8).astype('float32')
        for _ in range(5):
            loss = tr.step(tx, ty)
        jax.block_until_ready(loss)
        tr.finish_profile(sync=loss)
        out['collective_observed'] = len(
            telemetry.events('collective_observed'))

        # (c) sync-free proof: a trainer with a profiler ATTACHED but
        # no window in range must add zero device→host transfers per
        # step (the telemetry-overhead A/B of the sampled design).
        # Fresh net+optimizer: tr donated the first pair's opt state.
        paddle.seed(0)
        net2 = nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                             nn.Linear(64, 8))
        topt2 = paddle.optimizer.Adam(learning_rate=1e-3,
                                      parameters=net2.parameters())
        tr2 = ParallelTrainer(
            net2, topt2, lambda o, t: mse(o, t), mesh=mesh,
            donate=False,
            profile={'every': 1000, 'steps': 1, 'start': 900,
                     'dir': telemetry_dir})
        tr2.step(tx, ty)    # compile + census outside the guard
        try:
            with jax.transfer_guard_device_to_host('disallow'):
                for _ in range(4):
                    tr2.step(tx, ty)
            out['sync_free_ok'] = True
        except Exception as e:
            out['sync_free_ok'] = False
            out['sync_free_error'] = repr(e)[:300]
    finally:
        dist_env.set_mesh(prev)
        telemetry.disable()
    print(json.dumps(out))


def _profile_preflight(timeout_s=600):
    """--profile-smoke gate: the self-profiling runtime must (1) close
    a capture window on both loop integrations (hapi fit + the dp=8
    CPU-mesh ParallelTrainer), (2) land >=1 census-matched
    ``collective_observed`` event — the calibration fitter's input —
    and (3) keep non-profiled steps sync-free under a transfer guard.

    Returns (ok, summary).  Infra failures (timeout, crash) never
    block the bench — evidence beats a dead gate — but a windowless
    run, zero observed collectives, or an added host sync always do."""
    import subprocess
    import tempfile
    workdir = tempfile.mkdtemp(prefix='bench_profile_')
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['XLA_FLAGS'] = ' '.join(
        [t for t in env.get('XLA_FLAGS', '').split()
         if not t.startswith('--xla_force_host_platform_device_count')]
        + ['--xla_force_host_platform_device_count=8'])
    cmd = [sys.executable, os.path.abspath(__file__),
           '--profile-smoke-child', '--telemetry-dir', workdir]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'profile preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'profile preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    if not doc.get('lenet_windows'):
        failures.append('lenet fit(profile=) closed no capture window')
    if doc.get('lenet_errors'):
        failures.append(f'lenet window errors: {doc["lenet_errors"]}')
    if (doc.get('collective_observed') or 0) < 1:
        failures.append('dp=8 trainer produced no collective_observed '
                        'event (the calibration fit has no input)')
    if not doc.get('sync_free_ok'):
        failures.append('non-profiled steps synced the host with a '
                        'profiler attached: '
                        + str(doc.get('sync_free_error')))
    summary = dict(doc, failures=failures)
    ok = not failures
    log(f'profile preflight: {"ok" if ok else "FAIL"} '
        f'(windows={doc.get("lenet_windows")}, '
        f'observed={doc.get("collective_observed")}, '
        f'sync_free={doc.get("sync_free_ok")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _fused_smoke_child(smoke):
    """--fused-smoke child: steps/sec-vs-K sweep (K in {1, 8, 32}) of
    the fused train loop (core.scan_loop) on the lenet and widedeep
    bench model classes, plus a K=1-vs-unfused bit-exactness probe.
    K=1 runs through the SAME fused machinery (a length-1 scan), so
    the sweep isolates exactly what fusion buys: dispatch count.
    Emits one JSON line the parent asserts on."""
    import time as _time
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.models.widedeep import WideDeep

    out = {'sweep': {}}
    rs = np.random.RandomState(0)

    def sweep(name, make, stack, total, reps=1):
        res = {}
        for K in (1, 8, 32):
            trainer = make(K)
            chunk = stack(K)
            loss = trainer.step_fused(*chunk)   # compile + 1st chunk
            jax.block_until_ready(loss)
            n_chunks = max(2, total // K)
            best = 0.0
            for _ in range(reps):   # best-of: a loaded box adds
                t0 = _time.perf_counter()   # noise, never speed
                for _ in range(n_chunks):
                    loss = trainer.step_fused(*chunk)
                jax.block_until_ready(loss)
                dt = _time.perf_counter() - t0
                best = max(best, n_chunks * K / dt)
            res[str(K)] = round(best, 2)
            log(f'fused {name} K={K}: {res[str(K)]} steps/s '
                f'(best of {reps} x {n_chunks} chunks)')
        out['sweep'][name] = res
        return res

    # -- lenet (the gated config: small model, dispatch-bound).  The
    # high-QPS posture is SMALL per-step work — batch 4 keeps the
    # conv cheap enough that dispatch (what fusion removes) is a
    # measurable share of the step on CPU, mirroring the real-chip
    # regime where a lenet step is microseconds of MXU time.
    batch = 4
    x = rs.randn(batch, 1, 28, 28).astype('float32')
    y = rs.randint(0, 10, size=(batch, 1)).astype('int64')

    def make_lenet(K):
        paddle.seed(0)
        net = LeNet()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        ce = nn.CrossEntropyLoss()
        return ParallelTrainer(net, opt, lambda o, t: ce(o, t),
                               fused_steps=K)

    def stack_lenet(K):
        return (np.broadcast_to(x, (K,) + x.shape).copy(),
                np.broadcast_to(y, (K,) + y.shape).copy())

    lres = sweep('lenet', make_lenet, stack_lenet,
                 total=128 if smoke else 256, reps=3)
    out['lenet_uplift_k32'] = round(lres['32'] / lres['1'], 3)

    # K=1 fused vs today's per-step loop.  A dense model must be
    # BIT-exact (the scan changes nothing but dispatch count); the
    # conv model is allclose-gated — XLA reassociates the conv grad
    # inside a scan body, a ~1 ULP/step drift (see MIGRATION.md).
    def make_mlp(K):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                            nn.Linear(64, 10))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        ce = nn.CrossEntropyLoss()
        return ParallelTrainer(net, opt, lambda o, t: ce(o, t),
                               fused_steps=K)
    mx = rs.randn(batch, 32).astype('float32')
    t_a = make_mlp(0)
    l_a = [np.asarray(t_a.step(mx, y)) for _ in range(3)]
    t_b = make_mlp(1)
    l_b = [np.asarray(t_b.step_fused(mx[None], y[None]))[0]
           for _ in range(3)]
    out['mlp_k1_bitexact'] = bool(
        np.array_equal(np.asarray(l_a), np.asarray(l_b)))
    t_c = make_lenet(0)
    c_a = [np.asarray(t_c.step(x, y)) for _ in range(3)]
    t_d = make_lenet(1)
    c_b = [np.asarray(t_d.step_fused(x[None], y[None]))[0]
           for _ in range(3)]
    out['lenet_k1_allclose'] = bool(np.allclose(
        np.asarray(c_a), np.asarray(c_b), rtol=1e-5, atol=1e-6))
    out['lenet_k1_max_reldiff'] = float(np.max(
        np.abs(np.asarray(c_a) - np.asarray(c_b))
        / np.maximum(np.abs(np.asarray(c_a)), 1e-9)))

    # -- widedeep-class (recorded, not gated: bigger per-step work) --
    fields = [100_000] * 26
    dense_dim = 13
    wbatch = 256
    ids = np.stack([rs.randint(0, f, size=wbatch) for f in fields],
                   axis=1).astype('int64')
    dense = rs.rand(wbatch, dense_dim).astype('float32')
    wy = rs.randint(0, 2, size=(wbatch, 1)).astype('float32')

    def make_wd(K):
        paddle.seed(0)
        model = WideDeep(fields, dense_dim=dense_dim, embed_dim=16,
                         hidden=(400, 400, 400))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        bce = nn.BCEWithLogitsLoss()
        return ParallelTrainer(model, opt,
                               lambda o, t: bce(o, t), n_inputs=2,
                               fused_steps=K)

    def stack_wd(K):
        return tuple(np.broadcast_to(a, (K,) + a.shape).copy()
                     for a in (ids, dense, wy))

    wres = sweep('widedeep', make_wd, stack_wd,
                 total=16 if smoke else 32)
    out['widedeep_uplift_k32'] = round(wres['32'] / wres['1'], 3)
    print(json.dumps(out))


def _serve_smoke_child(smoke):
    """--serve-smoke child: one engine, warmup load then measured
    load, vs a sequential batch-1 generate baseline on the SAME
    request set.  Emits one JSON line with the gate evidence:

    - engine_tps vs seq_tps (continuous batching must win),
    - zero post-warmup compiles (engine module count AND persistent
      compile-cache stats — a fresh cache dir is armed for this
      process so every serialize is visible),
    - scheduler invariants (all requests complete, none starved past
      its deadline budget, no leaked/aliased KV blocks),
    - paged decode bit-exact vs dense-cache generate (greedy).
    """
    import tempfile
    import numpy as np  # noqa: F811
    del smoke       # the gate always runs the CPU smoke scale
    # a fresh cache makes 'zero post-warmup compiles' measurable via
    # compile_cache.stats(): warmup serializes every module, the
    # measured run must add none
    os.environ['PADDLE_TPU_COMPILE_CACHE'] = tempfile.mkdtemp(
        prefix='bench_serve_cc_')
    import paddle_tpu as paddle
    from paddle_tpu.core import compile_cache as CC
    from paddle_tpu.serving import ServingEngine

    model, cfg, load = _serve_setup(smoke=True)
    eng = ServingEngine(model, cfg)
    t0 = time.time()
    eng.warmup()                            # every declared module
    eng.run(load(seed=3))                   # shakeout under load
    warm_s = time.time() - t0
    compiles0 = eng.compile_count
    stats0 = CC.stats()
    rep = eng.run(load(seed=7))
    compiles_after = eng.compile_count - compiles0
    stats1 = CC.stats()
    cache_new = {k: stats1.get(k, 0) - stats0.get(k, 0)
                 for k in ('serialize_exec', 'miss_exec')
                 if stats1.get(k, 0) != stats0.get(k, 0)}

    # sequential batch-1 baseline + bit-exactness on the same set
    reqs = load(seed=7)
    fin = {r.rid: r for r in eng.scheduler.finished}
    refs = {}
    for r in reqs:                          # warm generate's buckets
        refs[r.rid] = np.asarray(model.generate(
            paddle.to_tensor(r.prompt[None, :]), r.max_new_tokens,
            temperature=0).value)[0, r.prompt.size:].tolist()
    t0 = time.time()
    total = 0
    for r in reqs:
        out = model.generate(paddle.to_tensor(r.prompt[None, :]),
                             r.max_new_tokens, temperature=0)
        np.asarray(out.value)
        total += r.max_new_tokens
    seq_wall = time.time() - t0
    seq_tps = total / seq_wall
    exact = all(fin[r.rid].tokens == refs[r.rid] for r in reqs
                if r.rid in fin)

    recs = rep['requests']
    starved = [r for r in recs if r['reason'] == 'deadline']
    incomplete = [r for r in recs if r['state'] not in ('done',)
                  or r['reason'] not in ('eos', 'max_tokens')]
    missing = [r.rid for r in reqs if r.rid not in fin]
    print(json.dumps({
        'engine_tps': rep['tokens_per_s'],
        'seq_tps': seq_tps,
        'speedup': (rep['tokens_per_s'] or 0) / seq_tps,
        'p99_ttft_s': rep['ttft_p99_s'],
        'p50_ttft_s': rep['ttft_p50_s'],
        'tpot_mean_s': rep['tpot_mean_s'],
        'warmup_s': round(warm_s, 2),
        'compiles_after_warmup': compiles_after,
        'cache_activity_after_warmup': cache_new,
        'modules': eng.stats()['modules'],
        'exact_vs_generate': bool(exact),
        'batch': cfg.max_slots,
        'requests': len(reqs),
        'decoded_tokens': rep['decoded_tokens'],
        'interventions': rep['interventions'],
        'starved': [r['rid'] for r in starved],
        'incomplete': [r['rid'] for r in incomplete] + missing,
        'audit': rep['audit'],
        'counters': rep['counters'],
    }))


def _serve_preflight(smoke, timeout_s=900):
    """--serve-smoke gate (the ISSUE-12 acceptance bar): under
    sustained synthetic Poisson load at batch 64 on the CPU smoke,
    continuous batching must sustain STRICTLY higher decoded
    tokens/sec than sequential batch-1 generate on the same request
    set, with zero post-warmup compiles, intact scheduler/allocator
    invariants, and paged-attention output bit-exact vs the dense
    reference.  Returns (ok, summary); infra failures never block —
    evidence beats a dead gate — but a violated bar always does."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    cmd = [sys.executable, os.path.abspath(__file__),
           '--serve-smoke-child'] + (['--smoke'] if smoke else [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'serve preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'serve preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    if not doc.get('exact_vs_generate'):
        failures.append('paged decode drifted from dense-cache '
                        'generate (bit-exactness broken)')
    speedup = doc.get('speedup') or 0
    if speedup <= 1.0:
        failures.append('continuous batching did not beat sequential '
                        f'batch-1 generate (x{speedup:.2f})')
    if doc.get('compiles_after_warmup'):
        failures.append(f'{doc["compiles_after_warmup"]} module '
                        'compile(s) AFTER warmup (bucket set leak)')
    if doc.get('cache_activity_after_warmup'):
        failures.append('compile-cache misses/serializes after warmup:'
                        f' {doc["cache_activity_after_warmup"]}')
    if doc.get('starved'):
        failures.append(f'requests starved past their deadline '
                        f'budget: {doc["starved"][:5]}')
    if doc.get('incomplete'):
        failures.append(f'admitted requests neither completed nor '
                        f'cleanly evicted: {doc["incomplete"][:5]}')
    if doc.get('audit'):
        failures.append(f'allocator/scheduler invariants violated: '
                        f'{doc["audit"][:3]}')
    summary = dict(doc, failures=failures)
    ok = not failures
    log(f'serve preflight: {"ok" if ok else "FAIL"} '
        f'(engine x{speedup:.2f} vs sequential, '
        f'p99 TTFT {doc.get("p99_ttft_s")}, '
        f'exact={doc.get("exact_vs_generate")}, '
        f'post-warmup compiles={doc.get("compiles_after_warmup")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _obs_smoke_child(smoke):
    """--obs-smoke child: one serving engine with the live
    observability plane ON (`serve_metrics_port=0` — ephemeral
    127.0.0.1 port), short Poisson load, a scraper thread hitting
    /metrics + /status.json every 200ms THROUGHOUT the measured run.
    Emits one JSON line with the gate evidence:

    - mid-run scrapes carry populated TTFT/TPOT percentiles and the
      KV-occupancy gauge (the live plane actually aggregates),
    - zero post-warmup compiles with the scraper attached (scraping
      cannot perturb the compiled surface),
    - a NON-serving trainer loop with the LiveAggregator installed
      stays sync-free under a device->host transfer guard (the live
      plane is free to leave on everywhere).
    """
    import tempfile
    import threading
    import urllib.request
    import numpy as np  # noqa: F811
    del smoke       # the gate always runs the CPU smoke scale
    os.environ['PADDLE_TPU_COMPILE_CACHE'] = tempfile.mkdtemp(
        prefix='bench_obs_cc_')
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.serving import ServingEngine

    out = {}
    model, cfg, load = _serve_setup(smoke=True)
    eng = ServingEngine(model, cfg, serve_metrics_port=0)
    url = eng.metrics_server.url
    eng.warmup()                    # builds every module, marks steady
    compiles0 = eng.compile_count

    scrapes = {'status': [], 'metrics': [], 'errors': []}
    stop = threading.Event()

    def scraper():
        while not stop.wait(0.2):
            try:
                scrapes['metrics'].append(urllib.request.urlopen(
                    url + '/metrics', timeout=5).read().decode())
                scrapes['status'].append(json.loads(
                    urllib.request.urlopen(
                        url + '/status.json', timeout=5).read()))
            except Exception as e:
                scrapes['errors'].append(repr(e)[:200])

    th = threading.Thread(target=scraper, daemon=True)
    th.start()
    rep = eng.run(load(seed=11))
    stop.set()
    th.join(timeout=10)
    status = json.loads(urllib.request.urlopen(
        url + '/status.json', timeout=5).read())
    metrics = urllib.request.urlopen(
        url + '/metrics', timeout=5).read().decode()
    eng.close()
    all_status = scrapes['status'] + [status]
    populated = [s for s in all_status
                 if s['serving']['ttft_ms'].get('count')
                 and s['serving']['tpot_ms'].get('count')
                 and 'kv_occupancy' in s['serving']['gauges']]
    out['scrapes'] = len(scrapes['status'])
    out['scrape_errors'] = scrapes['errors'][:5]
    out['populated_scrapes'] = len(populated)
    out['ttft_p99_ms'] = status['serving']['ttft_ms'].get('p99')
    out['tpot_p50_ms'] = status['serving']['tpot_ms'].get('p50')
    out['tokens_per_s'] = rep['tokens_per_s']
    out['metrics_has_ttft'] = 'paddle_tpu_serve_ttft_ms' in metrics
    out['metrics_has_occupancy'] = \
        'paddle_tpu_serve_kv_occupancy' in metrics
    out['compiles_after_warmup'] = eng.compile_count - compiles0
    out['post_steady_compiles'] = status['compiles']['after_steady']
    out['alerts'] = [a.get('kind') for a in status['alerts']]

    # (c) a non-serving trainer loop with live.py enabled stays
    # sync-free: the aggregator consumes only buffered flushes, so a
    # transfer guard over the hot loop must not trip
    from paddle_tpu.telemetry import LiveAggregator
    agg = LiveAggregator().install()
    telemetry.enable(None)
    try:
        paddle.seed(0)
        m2 = paddle.hapi.Model(nn.Sequential(
            nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4)))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m2.parameters())
        m2.prepare(optimizer=opt, loss=nn.MSELoss())
        m2._check_finite_steps = False      # NanGuard(enable=False)
        rs = np.random.RandomState(0)
        x = rs.randn(8, 16).astype('float32')
        y = rs.randn(8, 4).astype('float32')
        m2.train_batch(x, y)        # compile outside the guard
        acc = telemetry.step_accumulator('obsguard')
        try:
            with jax.transfer_guard_device_to_host('disallow'):
                for i in range(8):
                    t0 = time.perf_counter()
                    loss, _ = m2.train_batch(x, y)
                    acc.observe(step=i,
                                step_time_s=time.perf_counter() - t0,
                                loss=loss)
            out['sync_free_ok'] = True
        except Exception as e:
            out['sync_free_ok'] = False
            out['sync_free_error'] = repr(e)[:300]
        acc.flush()                 # the one sync, at the boundary
        out['live_saw_steps'] = bool(
            agg.step_ms.get('obsguard')
            and agg.step_ms['obsguard'].percentiles())
    finally:
        agg.uninstall()
        telemetry.disable()
    print(json.dumps(out))


def _obs_preflight(smoke, timeout_s=900):
    """--obs-smoke gate (the ISSUE-13 acceptance bar): with the live
    metrics endpoint up and scraped every 200ms through a Poisson
    serving run, (a) mid-run scrapes must carry populated TTFT/TPOT
    percentiles and the occupancy gauge, (b) the engine must compile
    NOTHING after warmup (a scraper cannot perturb the compiled
    surface), and (c) a non-serving trainer loop with the live
    aggregator installed must stay sync-free under a transfer guard.
    Returns (ok, summary); infra failures never block — evidence
    beats a dead gate — but a violated bar always does."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    cmd = [sys.executable, os.path.abspath(__file__),
           '--obs-smoke-child'] + (['--smoke'] if smoke else [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'obs preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'obs preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    if not doc.get('populated_scrapes'):
        failures.append('no mid-run scrape carried populated '
                        'TTFT/TPOT percentiles + occupancy gauge')
    if not doc.get('metrics_has_ttft') \
            or not doc.get('metrics_has_occupancy'):
        failures.append('/metrics missing the TTFT or occupancy '
                        'families')
    if doc.get('compiles_after_warmup'):
        failures.append(f'{doc["compiles_after_warmup"]} compile(s) '
                        'after warmup with the scraper attached')
    if not doc.get('sync_free_ok'):
        failures.append('trainer loop with LiveAggregator installed '
                        'synced the host: '
                        + str(doc.get('sync_free_error')))
    if not doc.get('live_saw_steps'):
        failures.append('the aggregator never aggregated the trainer '
                        "loop's steps flushes (live plane blind to "
                        'training)')
    summary = dict(doc, failures=failures)
    ok = not failures
    log(f'obs preflight: {"ok" if ok else "FAIL"} '
        f'({doc.get("populated_scrapes")}/{doc.get("scrapes")} '
        f'populated scrapes, p99 TTFT {doc.get("ttft_p99_ms")}ms, '
        f'post-warmup compiles={doc.get("compiles_after_warmup")}, '
        f'sync_free={doc.get("sync_free_ok")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _mem_smoke_child(smoke):
    """--mem-smoke child: the memory observatory end to end on the
    dp=8 CPU mesh, armed.  Emits one JSON line with the gate
    evidence:

    - every compiled module produced a ``memory_compiled`` event
      (the trainer's free ``compiled_text()`` path AND the armed hapi
      ``train_batch`` path),
    - ``run_report --json`` carries a populated three-way memory
      table (per-module predicted/compiled rows + live sampler),
    - a seeded near-budget injection fires EXACTLY ONE
      ``memory_pressure`` edge -> one supervisor re-plan whose
      ``hbm_budget_gb`` is TIGHTER than the breached budget,
    - the armed sampler adds zero device->host syncs (census ticks
      taken INSIDE a transfer guard around the hot loop).
    """
    import tempfile
    import numpy as np  # noqa: F811
    del smoke       # the gate always runs the CPU smoke scale
    # armed BEFORE paddle imports consult the env; huge interval so
    # every tick below is an explicit, deterministic sample_once()
    os.environ['PADDLE_TPU_MEMSTATS'] = 'interval=3600'
    os.environ['PADDLE_TPU_COMPILE_CACHE'] = '0'
    import jax
    from jax.sharding import Mesh
    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.telemetry import LiveAggregator
    from paddle_tpu.telemetry import memory as mem
    from paddle_tpu.telemetry.monitors import MemoryMonitor
    from paddle_tpu.parallel import ParallelTrainer
    from paddle_tpu.resilience.supervisor import (
        PlanSupervisor, SupervisorConfig)

    out = {}
    tmpdir = tempfile.mkdtemp(prefix='bench_mem_')
    telemetry.enable(tmpdir)

    # -- (a) compiled truth at both extraction tiers ------------------
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                        nn.Linear(32, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ('dp',))
    tr = ParallelTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
    rs = np.random.RandomState(0)
    x = rs.randn(16, 16).astype('float32')
    y = rs.randn(16, 4).astype('float32')
    tr.step(x, y)                   # armed extraction at first compile
    tr.compiled_text()              # the free trainer-hlo path
    paddle.seed(1)
    m2 = paddle.hapi.Model(nn.Linear(8, 2))
    opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=m2.network.parameters())
    m2.prepare(optimizer=opt2, loss=nn.MSELoss())
    m2.train_batch(rs.randn(4, 8).astype('float32'),
                   rs.randn(4, 2).astype('float32'))
    noted = sorted({e['name']
                    for e in telemetry.events('memory_compiled')})
    out['memory_compiled_modules'] = noted
    out['all_modules_extracted'] = (
        'ParallelTrainer.step' in noted
        and 'Model.train_batch' in noted)

    # -- (d) the armed sampler adds zero syncs ------------------------
    sampler = mem.ensure_sampler()
    out['sampler_armed'] = sampler is not None
    try:
        with jax.transfer_guard_device_to_host('disallow'):
            for _ in range(8):
                tr.step(x, y)
                s = (sampler or mem.MemorySampler()).sample_once()
        out['sync_free_ok'] = True
        out['sampler_source'] = (s or {}).get('source')
    except Exception as e:
        out['sync_free_ok'] = False
        out['sync_free_error'] = repr(e)[:300]

    # -- (c) seeded near-budget injection -> exactly-once pressure
    #        -> one tightened supervisor re-plan --------------------
    class _Host:
        """Five-method host whose replan records the tightened
        budget; the swap is a no-op plan echo."""

        class _Plan:
            mesh_axes = {'dp': 8}
            assignment = 'replicated'
            score_us = 50.0

        def __init__(self):
            self.replans = []

        def calibration(self):
            return None

        def healthy_devices(self, incident):
            return list(range(8))

        def replan(self, devices, calibration, hbm_budget_gb=None):
            self.replans.append(hbm_budget_gb)

            class R:
                winner = self._Plan()
                candidates = [winner]
                fallbacks = []
            return R()

        def incumbent(self):
            return None, None

        def precompile(self, plan, devices):
            pass

        def request_swap(self, plan, devices, incident):
            return True

    agg = LiveAggregator().install()
    host = _Host()
    sup = PlanSupervisor(host, SupervisorConfig(
        debounce_s=0.01, cooldown_s=0.0, margin=0.1)).start()
    try:
        census = mem.live_arrays_bytes() or 0
        # near-budget: the census sits just UNDER the watermark, so
        # the next (seeded, fixed-size) allocation crosses it
        budget = int((census + (4 << 20)) / 0.9)
        agg.attach_monitor(MemoryMonitor(budget_bytes=budget))
        probe = mem.MemorySampler(mem.MemConfig(
            budget_gb=budget / float(1 << 30)))
        probe.sample_once()             # below watermark: no edge
        ballast = jax.numpy.ones((budget // 4, 2), jax.numpy.float32)
        ballast.block_until_ready()     # ~2x the 4 MiB headroom
        probe.sample_once()             # crosses: THE edge
        probe.sample_once()             # latched: must not re-fire
        deadline = time.time() + 10
        while time.time() < deadline and not sup.incidents:
            time.sleep(0.05)
        del ballast
        pressures = telemetry.events('memory_pressure')
        out['pressure_events'] = len(pressures)
        out['budget_gb'] = round(budget / float(1 << 30), 4)
        out['replans'] = len(host.replans)
        out['tightened_gb'] = (None if not host.replans
                               else host.replans[0])
        out['budget_tightened'] = bool(
            host.replans and host.replans[0] is not None
            and host.replans[0] < budget / float(1 << 30))
        out['supervisor_outcomes'] = [
            i.get('outcome') for i in sup.incidents]
    finally:
        sup.stop()
        agg.uninstall()
        mem.stop_sampler()

    # -- (b) the run_report three-way table ---------------------------
    telemetry.disable()
    import subprocess
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      'tools', 'run_report.py'), tmpdir, '--json'],
        capture_output=True, text=True, timeout=120)
    try:
        rep = json.loads(proc.stdout)
    except ValueError:
        rep = {}
    memsec = rep.get('memory') or {}
    mods = memsec.get('modules') or {}
    out['report_memory_modules'] = len(mods)
    out['report_three_way'] = bool(
        mods
        and all(r.get('predicted_peak_bytes') is not None
                and r.get('compiled_peak_bytes') is not None
                for r in mods.values())
        and (memsec.get('live') or {}).get('device_bytes') is not None)
    out['report_ratio_mean'] = memsec.get('ratio_mean')
    out['report_pressure_events'] = memsec.get('pressure_events')
    print(json.dumps(out))


def _mem_preflight(smoke, timeout_s=900):
    """--mem-smoke gate (the ISSUE-18 acceptance bar): on a dp=8 CPU
    mesh with PADDLE_TPU_MEMSTATS armed, (a) every compiled module
    must produce a ``memory_compiled`` event, (b) ``run_report
    --json`` must carry a populated three-way memory table, (c) a
    seeded near-budget injection must fire EXACTLY ONE
    ``memory_pressure`` and drive one supervisor re-plan with a
    TIGHTENED ``hbm_budget_gb``, and (d) the armed sampler must add
    zero device->host syncs under a transfer guard.  Returns
    (ok, summary); infra failures never block — evidence beats a
    dead gate — but a violated bar always does."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['XLA_FLAGS'] = ' '.join(
        [t for t in env.get('XLA_FLAGS', '').split()
         if not t.startswith('--xla_force_host_platform_device_count')]
        + ['--xla_force_host_platform_device_count=8'])
    env.pop('PADDLE_TPU_MEMSTATS', None)    # the child arms explicitly
    cmd = [sys.executable, os.path.abspath(__file__),
           '--mem-smoke-child'] + (['--smoke'] if smoke else [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'mem preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'mem preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    if not doc.get('all_modules_extracted'):
        failures.append('a compiled module produced no '
                        'memory_compiled event (got: '
                        f'{doc.get("memory_compiled_modules")})')
    if not doc.get('report_three_way'):
        failures.append('run_report --json memory table unpopulated '
                        '(modules='
                        f'{doc.get("report_memory_modules")})')
    if doc.get('pressure_events') != 1:
        failures.append(f'near-budget injection fired '
                        f'{doc.get("pressure_events")} '
                        'memory_pressure event(s), want exactly 1')
    if doc.get('replans') != 1 or not doc.get('budget_tightened'):
        failures.append('supervisor re-plan missing or budget not '
                        f'tightened (replans={doc.get("replans")}, '
                        f'hint={doc.get("tightened_gb")} vs breached '
                        f'{doc.get("budget_gb")} GiB)')
    if not doc.get('sync_free_ok'):
        failures.append('armed sampler synced the host under the '
                        'transfer guard: '
                        + str(doc.get('sync_free_error')))
    summary = dict(doc, failures=failures)
    ok = not failures
    log(f'mem preflight: {"ok" if ok else "FAIL"} '
        f'(modules={doc.get("memory_compiled_modules")}, '
        f'ratio_mean={doc.get("report_ratio_mean")}, '
        f'pressure={doc.get("pressure_events")}, '
        f'tightened={doc.get("tightened_gb")}, '
        f'sync_free={doc.get("sync_free_ok")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _cluster_obs_smoke_child(smoke):
    """--cluster-obs-smoke child: the training-cluster observability
    plane under chaos (the ISSUE-15 acceptance bar), in one process:

    (a) a 2-proc ChaosCluster with rank 1 throttled (``slow_rank``)
        then SIGKILLed, cluster stats armed — rank 0's aggregator
        serves /cluster/status.json on an ephemeral port while the
        parent thread scrapes every 200ms.  Mid-run scrapes must
        ATTRIBUTE the straggler to rank 1 with populated skew, and
        the kill must DEGRADE the view (rank 1 stale-marked, server
        still answering) rather than crash the plane or the job
        (rc=0, invariants I1-I7 + bit-exact finals still gate).
    (b) scraping changes nothing: a hapi trainer loop runs twice on
        identical seeds/data — publisher ON (under a device->host
        transfer guard: the publisher must add no syncs) vs
        publisher OFF — and must produce bit-identical losses with
        equal compile counts.

    Emits one JSON line with the gate evidence."""
    import tempfile
    import threading
    import urllib.request
    import numpy as np  # noqa: F811
    del smoke       # the gate always runs the CPU smoke scale
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import nn, telemetry
    from paddle_tpu.resilience.chaos import ChaosCluster, FaultPlan

    out = {}

    # -- (a) chaos-validated live cluster view ---------------------------
    plan = FaultPlan(seed=7, name='cluster-obs-smoke', faults=(
        [{'kind': 'slow_rank', 'at_step': s, 'rank': 1,
          'delay_s': 0.35} for s in range(3, 10)]
        + [{'kind': 'sigkill', 'at_step': 14, 'rank': 1}]))
    cluster = ChaosCluster(
        procs=2, plan=plan, steps=20, save_every=2,
        collective_timeout_s=20.0, watchdog='step=60,grace=2',
        deadline_s=180.0, cluster_stats=True,
        # hold the killed rank down for ~4s: the stale threshold is
        # 1.5s, so the degraded (stale-marked) view is observable by
        # the 200ms scraper for a couple of seconds before the
        # elastic respawn re-publishes
        restart_backoff=4.0, restart_backoff_max=5.0,
        extra_env={'PADDLE_TPU_SOAK_FLUSH': '2',
                   'PADDLE_TPU_SOAK_STALE_AFTER': '1.5'})
    result = {}

    def _run():
        result['report'] = cluster.run()

    th = threading.Thread(target=_run, daemon=True)
    th.start()
    snaps, scrape_errors = [], 0
    t0 = time.time()
    while th.is_alive() and time.time() - t0 < 170:
        try:
            with open(cluster.cluster_port_file) as f:
                port = json.load(f)['port']
            doc = json.loads(urllib.request.urlopen(
                f'http://127.0.0.1:{port}/cluster/status.json',
                timeout=2).read())
            snaps.append(doc)
        except Exception:
            scrape_errors += 1
        time.sleep(0.2)
    th.join(timeout=30)
    rep = result.get('report') or {}
    out['cluster_rc'] = rep.get('rc')
    out['cluster_ok'] = rep.get('ok')
    out['violations'] = (rep.get('violations') or [])[:4]
    out['scrapes'] = len(snaps)
    out['scrape_errors'] = scrape_errors
    blamed = [s for s in snaps
              if (s.get('straggler') or {}).get('rank') is not None]
    attributed = [s for s in blamed
                  if s['straggler']['rank'] == 1
                  and (s['straggler'].get('skew') or 0) > 1.0]
    out['straggler_scrapes'] = len(attributed)
    # attributions naming any OTHER rank: transient windows may blame
    # a waiter briefly, but the correct attribution must dominate
    out['wrong_rank_scrapes'] = len(blamed) - len(
        [s for s in blamed if s['straggler']['rank'] == 1])
    if attributed:
        out['straggler_example'] = attributed[0]['straggler']
        out['critical_path_example'] = \
            attributed[0].get('critical_path')
    # any scrape that saw rank 1 stale/missing while the server still
    # answered = the degraded-not-crashed contract (the SIGKILL window
    # before the elastic respawn re-publishes)
    degraded = [s for s in snaps
                if s.get('degraded')
                and ((s.get('ranks') or {}).get('1', {}).get('stale')
                     or 1 in (s.get('missing') or []))]
    out['degraded_scrapes'] = len(degraded)
    out['kill_injected'] = any(
        e.get('fault') == 'sigkill' for e in rep.get('injected', ()))

    # -- (b) scrape-changes-nothing + sync-free publisher ----------------
    from paddle_tpu.distributed.collective import (
        FileKVStore, HostCollectives)
    from paddle_tpu.telemetry.cluster import ClusterPublisher

    def _losses(with_publisher):
        telemetry.reset()
        telemetry.enable(None, flush_interval=4)
        pub = None
        if with_publisher:
            kv = FileKVStore(tempfile.mkdtemp(prefix='cobs_kv_'))
            pub = ClusterPublisher(
                transport=HostCollectives(client=kv, rank=0, world=1),
                interval_s=0.0).install()
        try:
            paddle.seed(0)
            model = paddle.hapi.Model(nn.Sequential(
                nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4)))
            opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=model.parameters())
            model.prepare(optimizer=opt, loss=nn.MSELoss())
            model._check_finite_steps = False
            rs = np.random.RandomState(0)
            x = rs.randn(8, 16).astype('float32')
            y = rs.randn(8, 4).astype('float32')
            model.train_batch(x, y)     # compile outside the guard
            acc = telemetry.step_accumulator('cobsguard')
            losses = []
            guard = (jax.transfer_guard_device_to_host('disallow')
                     if with_publisher else contextlib.nullcontext())
            with guard:
                for i in range(8):
                    t0 = time.perf_counter()
                    loss, _ = model.train_batch(x, y)
                    acc.observe(step=i,
                                step_time_s=time.perf_counter() - t0,
                                loss=loss)
                    losses.append(loss)
            acc.flush()                 # the one sync, at the boundary
            frames = pub.published if pub is not None else None
            compiles = len(telemetry.events('compile'))
            return ([float(np.asarray(l)) for l in losses],
                    compiles, frames)
        finally:
            if pub is not None:
                pub.uninstall()
            telemetry.disable()
            telemetry.reset()

    try:
        on_losses, on_compiles, frames = _losses(True)
        out['sync_free_ok'] = True
        out['frames_published'] = frames
    except Exception as e:
        out['sync_free_ok'] = False
        out['sync_free_error'] = repr(e)[:300]
        on_losses, on_compiles = None, None
    if on_losses is not None:
        off_losses, off_compiles, _ = _losses(False)
        out['bitexact'] = on_losses == off_losses
        out['equal_compiles'] = on_compiles == off_compiles
    print(json.dumps(out))


def _cluster_obs_preflight(smoke, timeout_s=900):
    """--cluster-obs-smoke gate (the ISSUE-15 acceptance bar): a
    2-proc ChaosCluster with a throttled rank must be live-attributable
    (mid-run /cluster/status.json scrape names the correct straggler
    with populated skew), a SIGKILLed rank must degrade the view
    (stale-marked) rather than crash the plane or the job, and a
    publisher-enabled trainer loop must stay sync-free and bit-exact
    with equal compile counts.  Infra failures never block — evidence
    beats a dead gate — but a violated bar always does."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    cmd = [sys.executable, os.path.abspath(__file__),
           '--cluster-obs-smoke-child'] + (['--smoke'] if smoke else [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'cluster-obs preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'cluster-obs preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    if doc.get('cluster_rc') != 0 or not doc.get('cluster_ok'):
        failures.append(
            'the chaos run itself failed under the observability '
            f'plane (rc={doc.get("cluster_rc")}, violations='
            f'{doc.get("violations")}) — the plane must never cost '
            'the job')
    if not doc.get('straggler_scrapes'):
        failures.append('no mid-run scrape attributed the throttled '
                        'rank 1 as straggler with populated skew')
    elif (doc.get('wrong_rank_scrapes') or 0) \
            > doc['straggler_scrapes']:
        failures.append(
            f'wrong-rank attributions ({doc["wrong_rank_scrapes"]}) '
            f'outnumber correct ones ({doc["straggler_scrapes"]})')
    if not doc.get('degraded_scrapes'):
        failures.append('SIGKILL of rank 1 never surfaced as a '
                        'degraded (stale-marked) view — either the '
                        'plane crashed or staleness is broken')
    if not doc.get('kill_injected'):
        failures.append('the sigkill fault never fired (gate '
                        'evidence incomplete)')
    if not doc.get('sync_free_ok'):
        failures.append('publisher-enabled trainer loop synced the '
                        'host: ' + str(doc.get('sync_free_error')))
    if doc.get('bitexact') is False:
        failures.append('publisher-enabled trainer losses drifted '
                        'bitwise from the publisher-off run')
    if doc.get('equal_compiles') is False:
        failures.append('publisher changed the compile count')
    summary = dict(doc, failures=failures)
    ok = not failures
    log(f'cluster-obs preflight: {"ok" if ok else "FAIL"} '
        f'({doc.get("straggler_scrapes")}/{doc.get("scrapes")} '
        f'attributed scrapes, degraded={doc.get("degraded_scrapes")}, '
        f'rc={doc.get("cluster_rc")}, '
        f'sync_free={doc.get("sync_free_ok")}, '
        f'bitexact={doc.get("bitexact")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _fused_preflight(smoke, timeout_s=900):
    """--fused-smoke gate: the fused K-step loop must (1) be bit-exact
    with the per-step loop at K=1 and (2) show a steps/sec uplift at
    K=32 vs K=1 on the lenet config — the whole point of whole-loop
    compilation is dispatch amortization on small models, and a
    regression here means the scan is paying more than it saves.

    Returns (ok, summary).  Infra failures (timeout, crash) never
    block the bench — evidence beats a dead gate — but a K=1 numeric
    drift or a missing uplift always does."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    cmd = [sys.executable, os.path.abspath(__file__),
           '--fused-smoke-child'] + (['--smoke'] if smoke else [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'fused preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'fused preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    if not doc.get('mlp_k1_bitexact'):
        failures.append('fused K=1 losses drifted bitwise from the '
                        'per-step loop on the dense model')
    if not doc.get('lenet_k1_allclose'):
        failures.append('fused K=1 lenet losses drifted beyond conv '
                        'reassociation tolerance (max rel diff '
                        f'{doc.get("lenet_k1_max_reldiff")})')
    uplift = doc.get('lenet_uplift_k32') or 0
    if uplift <= 1.0:
        failures.append(f'no steps/sec uplift at K=32 vs K=1 on '
                        f'lenet (x{uplift})')
    summary = dict(doc, failures=failures)
    ok = not failures
    log(f'fused preflight: {"ok" if ok else "FAIL"} '
        f'(lenet x{doc.get("lenet_uplift_k32")}, '
        f'widedeep x{doc.get("widedeep_uplift_k32")}, '
        f'k1_bitexact={doc.get("mlp_k1_bitexact")}, '
        f'lenet_allclose={doc.get("lenet_k1_allclose")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _quant_smoke_child(telemetry_dir, smoke):
    """--quant-smoke child (forced 8-device CPU mesh): the quantized
    wire's acceptance evidence in one process —

    - lenet trained quantized-wire vs full-width on identical data/rng
      (tools/quant_accuracy.compare): final-loss delta gate + per-op
      censuses with wire_dtype tags,
    - the quantized trainer runs with a profile window so
      census-joined ``collective_observed`` events (s8-tagged) land in
      telemetry for the parent's run_report join,
    - zero post-warmup compiles (compile events after step 1),
    - corrupt-after-crc rejection: a quantized HostCollectives payload
      byte-flipped by the chaos seam AFTER the crc header must raise
      CollectivePayloadError on the receiving rank.

    Emits one JSON line the parent asserts on."""
    import tempfile
    import threading
    del smoke       # the gate always runs the CPU smoke scale
    from paddle_tpu import telemetry
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'tools'))
    import quant_accuracy as _qa

    telemetry.enable(telemetry_dir)
    out = {}
    try:
        row = _qa.compare(
            'lenet', {'block': 256, 'min_bytes': 0}, steps=25,
            profile={'every': 100, 'steps': 2, 'start': 2,
                     'dir': telemetry_dir})
        out.update(row)
        out['observed_rows'] = len(
            telemetry.events('collective_observed'))
        out['observed_s8'] = sum(
            1 for e in telemetry.events('collective_observed')
            if e.get('wire_dtype') == 's8')

        # corrupt-after-crc on the QUANTIZED host wire: two ranks over
        # one FileKVStore, the chaos collective_corrupt seam flips a
        # payload byte after the header on rank 0 — rank 1 must reject
        from paddle_tpu.distributed.collective import (
            FileKVStore, HostCollectives, CollectivePayloadError)
        from paddle_tpu.resilience.chaos import ChaosEngine, FaultPlan
        kv = FileKVStore(tempfile.mkdtemp(prefix='quant_corrupt_'))
        t0 = HostCollectives(client=kv, rank=0, world=2, timeout_s=15,
                             quant='int8', quant_min_bytes=0)
        t1 = HostCollectives(client=kv, rank=1, world=2, timeout_s=15,
                             quant='int8', quant_min_bytes=0)
        eng = ChaosEngine(FaultPlan(seed=0, faults=[
            {'kind': 'collective_corrupt', 'at_step': 1, 'rank': 0}]),
            rank=0).activate()
        try:
            eng.step(1)
            arr = np.arange(1024, dtype='float32')

            def rank0():
                try:
                    t0.allreduce(arr, 'mean', tag='corrupt1')
                except Exception:
                    pass
            th = threading.Thread(target=rank0)
            th.start()
            try:
                t1.allreduce(arr, 'mean', tag='corrupt1')
                out['corrupt_rejected'] = False
            except CollectivePayloadError:
                out['corrupt_rejected'] = True
            th.join()
        finally:
            eng.deactivate()
    finally:
        telemetry.disable()
    print(json.dumps(out))


def _quant_preflight(smoke, timeout_s=900):
    """--quant-smoke gate (the ISSUE-14 acceptance bar): quantized-
    wire lenet must converge within the gated loss delta of full
    width, the run_report join must show wire_dtype-tagged predicted
    bytes >=2x below the full-width baseline with observed_us
    populated from the profile window, the quantized trainer must
    compile nothing after warmup, and a quantized payload corrupted
    after its crc header must be rejected under chaos.  Returns
    (ok, summary); infra failures never block — evidence beats a dead
    gate — but a violated bar always does."""
    import subprocess
    import tempfile
    workdir = tempfile.mkdtemp(prefix='bench_quant_')
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['XLA_FLAGS'] = ' '.join(
        [t for t in env.get('XLA_FLAGS', '').split()
         if not t.startswith('--xla_force_host_platform_device_count')]
        + ['--xla_force_host_platform_device_count=8'])
    cmd = [sys.executable, os.path.abspath(__file__),
           '--quant-smoke-child', '--telemetry-dir', workdir] \
        + (['--smoke'] if smoke else [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = _last_json_dict(proc.stdout)
    except Exception as e:
        log(f'quant preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    if doc is None:
        log(f'quant preflight skipped (no child output, '
            f'rc={proc.returncode}): {proc.stderr[-300:]}')
        return True, {'error': f'no output (rc={proc.returncode})'}
    failures = []
    delta_rel = doc.get('loss_delta_rel')
    if delta_rel is None or delta_rel > 0.10:
        # explicit None check: a PERFECT run reports exactly 0.0,
        # which a falsy-or default would misread as missing
        failures.append(
            'quantized-wire lenet drifted '
            + ('(no measurement)' if delta_rel is None
               else f'{delta_rel * 100:.1f}% of the full-width loss '
                    'progress (gate 10%)'))
    if (doc.get('wire_reduction') or 0) < 2.0:
        failures.append(
            f'predicted wire reduction x{doc.get("wire_reduction")} '
            'below the x2 bar')
    s8 = [op for op, r in (doc.get('census_quant') or {}).items()
          if r.get('wire_dtype') == 's8']
    if not s8:
        failures.append('no s8-tagged collective in the quantized '
                        "trainer's census (wire never quantized)")
    if doc.get('compile_events_quant') not in (None, 1):
        failures.append(
            f'{doc.get("compile_events_quant")} compile events across '
            'the quantized run (expected exactly the warmup compile)')
    if not doc.get('corrupt_rejected'):
        failures.append('a quantized payload corrupted after the crc '
                        'header was ACCEPTED by a receiver')
    # the run_report join: predicted-vs-observed with the wire_dtype
    # dimension populated (observed_us from the child's profile window)
    rr = None
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), 'tools'))
        import run_report as _rr
        jsonls, flights = _rr.discover([workdir])
        events, sources, skew = _rr.load_events(jsonls, flights)
        rep = _rr.analyze(events, sources, skew)
        cmp_rows = rep.get('collectives_cmp') or {}
        rr = {op: {'wire_dtype': r.get('wire_dtype'),
                   'predicted_wire_bytes': r.get('predicted_wire_bytes'),
                   'observed_us': r.get('observed_us')}
              for op, r in cmp_rows.items()}
        tagged = [op for op, r in cmp_rows.items()
                  if r.get('wire_dtype') == 's8']
        if not tagged:
            failures.append('run_report collectives_cmp carries no '
                            's8-tagged row')
        observed = [op for op in tagged
                    if cmp_rows[op].get('observed_us')]
        if not observed:
            failures.append('no s8-tagged row has observed_us '
                            'populated (profile join failed)')
    except Exception as e:
        log(f'quant preflight: run_report join failed ({e!r})')
        failures.append(f'run_report join failed: {e!r}')
    summary = dict(doc, failures=failures, run_report=rr)
    summary.pop('losses', None)
    ok = not failures
    log(f'quant preflight: {"ok" if ok else "FAIL"} '
        f'(loss delta {(doc.get("loss_delta_rel") or 0) * 100:.2f}%, '
        f'wire x{doc.get("wire_reduction")}, '
        f'observed_s8={doc.get("observed_s8")}, '
        f'corrupt_rejected={doc.get("corrupt_rejected")})')
    for f in failures:
        log(f'  {f}')
    return ok, summary


def _lint_preflight(timeout_s=300, smoke=False):
    """tpu_lint gate before burning chip time: a HIGH-severity finding
    in examples/ or paddle_tpu/models/ means some bench config would
    run a known-degraded step (host sync / retrace hazard) — fail the
    bench up front and put the findings in the artifact instead of
    discovering it in the throughput numbers.

    The gate includes the lowered-HLO SPMD audit (--hlo under a forced
    8-device CPU mesh): the model suite is lowered through the
    partitioner and replicated-giant-hlo / collective-cost /
    resharding / peak-memory run BEFORE any chip session — a
    replicated giant or an OOM-bound peak shows up here, not in a
    wedged tunnel.  The subprocess isolates the forced virtual mesh
    from this process's real-device jax.

    Returns (ok, summary_dict).  Lint-infra failures (timeout, crash)
    never block the bench: evidence beats a dead gate."""
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(repo, 'tools', 'tpu_lint.py'),
           os.path.join(repo, 'examples'),
           os.path.join(repo, 'paddle_tpu', 'models'),
           '--hlo', '--mesh', 'dp=8',
           '--json', '--fail-on', 'never']
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # a pre-existing forced device count (e.g. a 4-device virtual-mesh
    # launcher env) would beat tpu_lint's own =8 and break the dp=8
    # lower — strip it so the subprocess forces exactly what it needs
    env['XLA_FLAGS'] = ' '.join(
        t for t in env.get('XLA_FLAGS', '').split()
        if not t.startswith('--xla_force_host_platform_device_count'))
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
        doc = json.loads(proc.stdout)
    except Exception as e:
        log(f'lint preflight skipped ({e!r})')
        return True, {'error': repr(e)[:200]}
    counts = doc.get('counts', {})
    high = [f for f in doc.get('findings', [])
            if f.get('severity') == 'high']
    # findings become lint_finding telemetry events, made DURABLE via
    # a flight dump into the committed evidence dir (chip_session's
    # collect_flightrecs archives flightrec-*.json; an in-memory ring
    # alone would die with this process)
    if doc.get('findings') and not smoke:
        try:
            from paddle_tpu import telemetry
            for f in doc['findings']:
                telemetry.event('lint_finding', rule=f.get('rule'),
                                severity=f.get('severity'),
                                file=f.get('file'), line=f.get('line'),
                                origin=f.get('origin'),
                                name='bench-preflight')
            telemetry.dump_flight(os.path.join(
                CHIP_OUT, 'flightrec-bench-preflight.json'))
        except Exception:
            pass
    summary = {'counts': counts, 'high': high[:10]}
    hlo = doc.get('hlo') or {}
    if hlo:
        # per-target headline numbers for the artifact: predicted
        # collective wire traffic + peak HBM of each lowered step
        summary['hlo'] = {
            t: {'counts': r.get('counts'),
                'peak_bytes': (r.get('extras') or {}).get('peak_bytes'),
                'collective_wire_bytes': (r.get('extras') or {}).get(
                    'collective_wire_bytes')}
            for t, r in hlo.items()}
    log(f'lint preflight: {counts}')
    return not high, summary


def main():
    from tools._env import setup_jax_cache
    setup_jax_cache()
    p = argparse.ArgumentParser()
    p.add_argument('--smoke', action='store_true',
                   help='tiny shapes, few iters (CI sanity)')
    p.add_argument('--config', choices=list(CONFIGS) + ['all'],
                   default='all')
    p.add_argument('--single-json', action='store_true',
                   help='(internal) emit one config result as raw JSON')
    p.add_argument('--timeout', type=int, default=900,
                   help='per-config subprocess timeout in seconds '
                        '(slow-compile configs scale it by '
                        'TIMEOUT_SCALE, e.g. gptgen x3)')
    p.add_argument('--no-lint', action='store_true',
                   help='skip the tpu_lint preflight gate')
    p.add_argument('--chaos-smoke', action='store_true',
                   help='run a short seeded fault-injection plan '
                        '(tools/chaos_run.py) and gate on the '
                        'resilience invariants before benching')
    p.add_argument('--plan-smoke', action='store_true',
                   help='run the auto-sharding planner over the '
                        'built-in suite on a virtual dp=8 CPU mesh '
                        'and gate on the committed golden plans '
                        '(tools/plan_goldens.json)')
    p.add_argument('--cache-smoke', action='store_true',
                   help='two cold processes against one fresh compile '
                        'cache: the second must deserialize (>=1 '
                        'exec-tier hit per target) and start faster — '
                        'gates the persistent-compile-cache warm path')
    p.add_argument('--cache-smoke-child', action='store_true',
                   help='(internal) run one cold-path pass for '
                        '--cache-smoke and emit its JSON')
    p.add_argument('--profile-smoke', action='store_true',
                   help='capture one sampled profiler window on lenet '
                        '+ the dp=8 CPU-mesh trainer: >=1 '
                        'collective_observed event must land and '
                        'non-profiled steps must stay sync-free — '
                        'gates the self-profiling runtime')
    p.add_argument('--profile-smoke-child', action='store_true',
                   help='(internal) run the profile-smoke captures '
                        'and emit their JSON')
    p.add_argument('--serve-smoke', action='store_true',
                   help='preflight gate: continuous-batching serving '
                        '(paddle_tpu/serving) under synthetic Poisson '
                        'load at batch 64 on CPU must beat sequential '
                        'batch-1 generate on the same request set, '
                        'with zero post-warmup compiles, intact '
                        'scheduler/KV-block invariants and paged '
                        'decode bit-exact vs the dense reference')
    p.add_argument('--serve-smoke-child', action='store_true',
                   help='(internal) run the serve-smoke measurement '
                        'and emit its JSON')
    p.add_argument('--obs-smoke', action='store_true',
                   help='preflight gate: live observability plane — '
                        'a serving run with the HTTP status server '
                        'on, scraped mid-run, must show populated '
                        'TTFT/TPOT percentiles + occupancy gauges, '
                        'zero post-warmup compiles, and a sync-free '
                        'trainer loop with the aggregator installed')
    p.add_argument('--obs-smoke-child', action='store_true',
                   help='(internal) run the obs-smoke measurement '
                        'and emit its JSON')
    p.add_argument('--cluster-obs-smoke', action='store_true',
                   help='preflight gate: live TRAINING-cluster '
                        'observability (telemetry.cluster) — a '
                        '2-proc ChaosCluster with a throttled rank '
                        'must be live-attributable mid-run '
                        '(/cluster/status.json names the straggler '
                        'with populated skew), a SIGKILLed rank must '
                        'degrade the view (stale-marked) not crash '
                        'it, and a publisher-enabled trainer loop '
                        'must stay sync-free and bit-exact')
    p.add_argument('--cluster-obs-smoke-child', action='store_true',
                   help='(internal) run the cluster-obs measurement '
                        'and emit its JSON')
    p.add_argument('--mem-smoke', action='store_true',
                   help='preflight gate: memory observatory '
                        '(telemetry.memory) — a dp=8 CPU mesh run '
                        'with PADDLE_TPU_MEMSTATS armed must produce '
                        'memory_compiled for every compiled module, '
                        'a populated three-way (predicted/compiled/'
                        'live) table in run_report --json, a seeded '
                        'near-budget injection firing exactly one '
                        'memory_pressure -> one supervisor re-plan '
                        'with a tightened hbm_budget_gb, and a '
                        'transfer-guard proof the armed sampler adds '
                        'zero syncs')
    p.add_argument('--mem-smoke-child', action='store_true',
                   help='(internal) run the mem-smoke measurement '
                        'and emit its JSON')
    p.add_argument('--fused-smoke', action='store_true',
                   help='steps/sec-vs-K sweep (K in {1,8,32}) of the '
                        'fused train loop on the lenet/widedeep '
                        'configs: K=32 must beat K=1 on lenet and '
                        'K=1 must stay bit-exact — gates whole-loop '
                        'compilation (core.scan_loop)')
    p.add_argument('--fused-smoke-child', action='store_true',
                   help='(internal) run the fused K-sweep and emit '
                        'its JSON')
    p.add_argument('--quant-smoke', action='store_true',
                   help='preflight gate: quantized collectives '
                        '(parallel.quant_collectives) — quantized-'
                        'wire lenet must converge within the loss-'
                        'delta gate of full width, the run_report '
                        'join must show s8-tagged predicted wire '
                        'bytes >=2x below the full-width baseline '
                        'with observed_us populated, zero post-'
                        'warmup compiles, and corrupt-after-crc '
                        'quantized payloads must be rejected')
    p.add_argument('--quant-smoke-child', action='store_true',
                   help='(internal) run the quant-smoke measurement '
                        'and emit its JSON')
    p.add_argument('--supervisor-smoke', action='store_true',
                   help='preflight gate: the self-healing plan '
                        'supervisor (resilience.supervisor) — '
                        'injected drift on a dp=8 CPU-mesh trainer '
                        'must produce exactly ONE safe plan '
                        'migration (mesh changes, steps/sec '
                        'recovers, cooldown suppresses re-fire) and '
                        'a clean armed run must actuate zero times')
    p.add_argument('--supervisor-smoke-child', action='store_true',
                   help='(internal) run the supervisor-smoke '
                        'measurement and emit its JSON')
    p.add_argument('--frontdoor-smoke', action='store_true',
                   help='preflight gate: the serving front door '
                        '(serving/frontend.py + router.py) — a real '
                        '2-replica fleet must shed a Poisson '
                        'overload TYPED (429/503/413, never OOM or '
                        'silent loss), a clean twin must shed '
                        'nothing and stream bit-exact vs '
                        'single-engine, a seeded replica_kill '
                        'mid-stream must leave every in-flight rid '
                        'terminal with >=1 bit-exact retry plus a '
                        'promoted warm spare, and a forced '
                        'slo_breach drain must drop zero in-flight '
                        'tokens')
    p.add_argument('--frontdoor-smoke-child', action='store_true',
                   help='(internal) run the frontdoor-smoke drill '
                        'and emit its JSON')
    p.add_argument('--threads-smoke', action='store_true',
                   help='preflight gate: the concurrency posture — '
                        'the static sweep (tpu_lint --threads) over '
                        'paddle_tpu/ must report zero HIGH findings, '
                        'and a dp=8 trainer + serving-engine smoke '
                        'with the runtime lock checker armed '
                        '(analysis.lockcheck) must finish with zero '
                        'lock-order cycles, zero unguarded accesses, '
                        'zero checker crashes, and bit-exact losses '
                        'vs the unarmed run')
    p.add_argument('--threads-smoke-child', action='store_true',
                   help='(internal) run the threads-smoke armed '
                        'measurement and emit its JSON')
    p.add_argument('--spmd-smoke', action='store_true',
                   help='preflight gate: the SPMD contract — the '
                        'static sweep (tpu_lint --spmd) over '
                        'paddle_tpu/ + tools/ must report zero HIGH '
                        'findings, and a 2-proc ChaosCluster with a '
                        'rank-gated skipped collective injected must '
                        'attribute collective_mismatch to the exact '
                        'seeded call site (no later than the generic '
                        'timeout) with I1-I7 intact, a clean twin '
                        'emitting zero mismatch events, and the '
                        'ledger-ON trainer loop sync-free + '
                        'bit-exact vs ledger-OFF')
    p.add_argument('--spmd-smoke-child', action='store_true',
                   help='(internal) run the spmd-smoke armed '
                        'measurement and emit its JSON')
    p.add_argument('--telemetry-dir', default=None,
                   help='(internal) telemetry JSONL dir for '
                        '--cache-smoke-child / --profile-smoke-child')
    args = p.parse_args()

    if args.cache_smoke_child:
        import tempfile
        _cache_smoke_child(args.telemetry_dir
                           or tempfile.mkdtemp(prefix='cache_tel_'),
                           args.smoke)
        return

    if args.profile_smoke_child:
        import tempfile
        _profile_smoke_child(args.telemetry_dir
                             or tempfile.mkdtemp(prefix='prof_tel_'))
        return

    if args.fused_smoke_child:
        _fused_smoke_child(args.smoke)
        return

    if args.quant_smoke_child:
        import tempfile
        _quant_smoke_child(args.telemetry_dir
                           or tempfile.mkdtemp(prefix='quant_tel_'),
                           args.smoke)
        return

    if args.supervisor_smoke_child:
        _supervisor_smoke_child()
        return

    if args.frontdoor_smoke_child:
        _frontdoor_smoke_child()
        return

    if args.threads_smoke_child:
        _threads_smoke_child()
        return

    if args.spmd_smoke_child:
        _spmd_smoke_child()
        return

    if args.serve_smoke_child:
        _serve_smoke_child(args.smoke)
        return

    if args.obs_smoke_child:
        _obs_smoke_child(args.smoke)
        return

    if args.cluster_obs_smoke_child:
        _cluster_obs_smoke_child(args.smoke)
        return

    if args.mem_smoke_child:
        _mem_smoke_child(args.smoke)
        return

    if args.single_json:
        if args.config == 'all':
            p.error('--single-json needs an explicit --config NAME')
        res = _run_one(args.config, args.smoke)
        print(json.dumps(res))
        return

    names = list(CONFIGS) if args.config == 'all' else [args.config]
    results = {}
    lint_summary = None
    chaos_summary = None
    plan_summary = None
    cache_summary = None
    profile_summary = None
    fused_summary = None
    serve_summary = None
    obs_summary = None
    cluster_obs_summary = None
    mem_summary = None
    quant_summary = None
    supervisor_summary = None
    frontdoor_summary = None
    threads_summary = None
    spmd_summary = None
    if args.threads_smoke:
        threads_ok, threads_summary = _threads_preflight()
        if not threads_ok:
            # a HIGH concurrency finding or an armed-run cycle/
            # violation means the host runtime can race or deadlock
            # mid-run on chip — and a loss divergence means the
            # checker itself perturbs training; fail before burning
            # chip time
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'threads preflight failed (HIGH concurrency '
                         'lint finding, lock-order cycle, unguarded '
                         'cross-thread access, checker crash, or '
                         'armed-vs-unarmed loss divergence); fix the '
                         'flagged runtime code or re-run without '
                         '--threads-smoke',
                'threads': threads_summary, 'extras': {}}))
            sys.exit(1)
    if args.spmd_smoke:
        spmd_ok, spmd_summary = _spmd_preflight()
        if not spmd_ok:
            # a HIGH SPMD finding means a rank-gated collective or
            # unbroadcast host entropy can deadlock or silently
            # diverge the fleet; a missed attribution means the
            # flight recorder can't name the first divergent
            # collective when it matters; a ghost mismatch or a
            # perturbed trainer means the ledger itself is unsafe to
            # leave on — fail before burning chip time
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'spmd preflight failed (HIGH SPMD lint '
                         'finding, missed or late collective_mismatch '
                         'attribution, ghost mismatch on a clean run, '
                         'broken chaos invariants, or ledger-on '
                         'trainer divergence); fix the flagged '
                         'collective code or re-run without '
                         '--spmd-smoke',
                'spmd': spmd_summary, 'extras': {}}))
            sys.exit(1)
    if args.supervisor_smoke:
        sup_ok, supervisor_summary = _supervisor_preflight()
        if not sup_ok:
            # a mis-actuating supervisor on chip is worse than none:
            # a missing swap means drift goes unremediated, a double
            # or clean-run swap means the actuator thrashes live
            # training — fail before burning chip time
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'supervisor preflight failed (missing/'
                         'double actuation, unchanged mesh, '
                         'unrecovered throughput, or a clean-run '
                         'swap); fix resilience.supervisor or re-run '
                         'without --supervisor-smoke',
                'supervisor': supervisor_summary, 'extras': {}}))
            sys.exit(1)
    if args.frontdoor_smoke:
        door_ok, frontdoor_summary = _frontdoor_preflight()
        if not door_ok:
            # a front door that sheds untyped, loses an in-flight rid
            # on replica death, or drops tokens across a drain will
            # do exactly that in production overload — fail before
            # burning chip time, with the drill as the artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'frontdoor preflight failed (untyped shed, '
                         'lost/diverged in-flight stream on '
                         'replica_kill, missing warm-spare '
                         'promotion, or a drain that dropped '
                         'tokens); fix serving/frontend.py|router.py '
                         'or re-run without --frontdoor-smoke',
                'frontdoor': frontdoor_summary, 'extras': {}}))
            sys.exit(1)
    if args.quant_smoke:
        quant_ok, quant_summary = _quant_preflight(args.smoke)
        if not quant_ok:
            # a failed quant gate means the quantized wire is either
            # wrong (loss drift, accepted corruption) or pointless
            # (no byte reduction) — fail before burning chip time,
            # with the measurement as the artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'quant preflight failed (quantized-wire '
                         'loss drift, <2x wire reduction, missing '
                         's8 evidence, post-warmup compiles, or '
                         'accepted corruption); fix '
                         'parallel.quant_collectives or re-run '
                         'without --quant-smoke',
                'quant': quant_summary, 'extras': {}}))
            sys.exit(1)
    if args.obs_smoke:
        obs_ok, obs_summary = _obs_preflight(args.smoke)
        if not obs_ok:
            # a dead live plane means a serving deploy flies blind
            # (no mid-run TTFT/occupancy) or — worse — observing the
            # engine perturbs it; fail before burning chip time
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'obs preflight failed (live metrics endpoint '
                         'unpopulated, post-warmup compiles with a '
                         'scraper attached, or a host sync from the '
                         'live aggregator); fix telemetry.live / '
                         'telemetry.httpd or re-run without '
                         '--obs-smoke',
                'obs': obs_summary, 'extras': {}}))
            sys.exit(1)
    if args.cluster_obs_smoke:
        cobs_ok, cluster_obs_summary = _cluster_obs_preflight(
            args.smoke)
        if not cobs_ok:
            # a blind or fragile cluster plane means multi-host chip
            # runs stay post-hoc-only (stragglers invisible until the
            # job dies) or — worse — observing the cluster kills it;
            # fail before burning chip time
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'cluster-obs preflight failed (straggler '
                         'not attributed, kill crashed the view, or '
                         'the publisher perturbed training); fix '
                         'telemetry.cluster or re-run without '
                         '--cluster-obs-smoke',
                'cluster_obs': cluster_obs_summary, 'extras': {}}))
            sys.exit(1)
    if args.mem_smoke:
        mem_ok, mem_summary = _mem_preflight(args.smoke)
        if not mem_ok:
            # a lying memory plane means the planner's HBM gate keeps
            # admitting plans that OOM live, and nothing re-plans
            # when they do — fail before burning chip time
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'mem preflight failed (memory_compiled '
                         'missing for a module, three-way table '
                         'unpopulated, pressure edge not exactly-'
                         'once, re-plan budget untightened, or the '
                         'armed sampler synced the host); fix '
                         'telemetry.memory / resilience.supervisor '
                         'or re-run without --mem-smoke',
                'mem': mem_summary, 'extras': {}}))
            sys.exit(1)
    if args.serve_smoke:
        serve_ok, serve_summary = _serve_preflight(args.smoke)
        if not serve_ok:
            # the serving runtime regressed below its acceptance bar
            # (slower than sequential decode, recompiles under load,
            # leaked blocks or numeric drift) — fail before burning
            # chip time, with the measurement as the artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'serve preflight failed (continuous batching '
                         'below the acceptance bar); fix '
                         'paddle_tpu/serving or re-run without '
                         '--serve-smoke',
                'serve': serve_summary, 'extras': {}}))
            sys.exit(1)
    if args.fused_smoke:
        fused_ok, fused_summary = _fused_preflight(args.smoke)
        if not fused_ok:
            # a K=1 drift or a missing uplift means the fused loop is
            # either wrong or pointless — fail before burning chip
            # time, with the sweep as the artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'fused preflight failed (K=1 numeric drift '
                         'or no steps/sec uplift at K=32); fix '
                         'core.scan_loop or re-run without '
                         '--fused-smoke',
                'fused': fused_summary, 'extras': {}}))
            sys.exit(1)
    if args.profile_smoke:
        profile_ok, profile_summary = _profile_preflight()
        if not profile_ok:
            # a dead capture path means chip sessions produce no
            # collective_observed evidence (the calibration loop
            # starves) or — worse — profiling costs per-step syncs;
            # fail before burning chip time
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'profile preflight failed (no capture '
                         'window / no collective_observed / host '
                         'sync outside windows); fix '
                         'telemetry.profile or re-run without '
                         '--profile-smoke',
                'profile': profile_summary, 'extras': {}}))
            sys.exit(1)
    if args.cache_smoke:
        cache_ok, cache_summary = _cache_preflight(args.smoke)
        if not cache_ok:
            # a cold warm-path means every elastic restart / serving
            # cold-start re-pays full compilation — fail before
            # burning chip time, with the per-target numbers as the
            # artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'cache preflight failed (no deserialize hit '
                         'or no warm-start speedup); fix the compile '
                         'cache or re-run without --cache-smoke',
                'compile_cache': cache_summary, 'extras': {}}))
            sys.exit(1)
    if args.plan_smoke:
        plan_ok, plan_summary = _plan_preflight()
        if not plan_ok:
            # a golden-plan mismatch means the cost model now ranks
            # shardings differently — fail before burning chip time,
            # with the diff as the artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'plan preflight failed (top-ranked plan '
                         'differs from tools/plan_goldens.json); '
                         'update the goldens deliberately or fix the '
                         'cost model, or re-run without --plan-smoke',
                'plan': plan_summary, 'extras': {}}))
            sys.exit(1)
    if args.chaos_smoke:
        chaos_ok, chaos_summary = _chaos_preflight()
        if not chaos_ok:
            # a resilience-invariant violation means checkpoints from
            # a chip run could be unrecoverable — fail before burning
            # chip time, with the violations as the artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'chaos preflight failed (resilience '
                         'invariant violations); fix or re-run '
                         'without --chaos-smoke',
                'chaos': chaos_summary, 'extras': {}}))
            sys.exit(1)
    if args.config == 'all' and not args.no_lint:
        lint_ok, lint_summary = _lint_preflight(smoke=args.smoke)
        if not lint_ok:
            # high-severity hazard: fail BEFORE burning chip time,
            # with the findings as the artifact
            print(json.dumps({
                'metric': METRIC_NAMES['resnet'], 'value': None,
                'unit': UNITS['resnet'], 'vs_baseline': None,
                'error': 'lint preflight failed (high-severity '
                         'findings); fix or re-run with --no-lint',
                'lint': lint_summary, 'extras': {}}))
            sys.exit(1)
    preflight_s = min(600, args.timeout * len(names))
    preflight_attempts = None
    if args.config == 'all':
        preflight_ok, preflight_attempts = \
            _device_preflight(preflight_s)
    else:
        preflight_ok = True
    if not preflight_ok:
        # dead accelerator tunnel: emit the artifact immediately with
        # errors instead of hanging 5 subprocesses to their timeouts —
        # but surface the most recent committed chip-verified number
        # per config (tagged stale_from) so a tunnel death at driver
        # time preserves real measurements with honest provenance;
        # top-level value stays null so staleness can never
        # masquerade as a fresh number.  The per-attempt diagnosis
        # (timeout vs crash, rc, stderr tail) rides along — rounds
        # r02-r05 failed here with no reason captured.
        why = (preflight_attempts or [{}])[-1].get('reason')
        stale = _load_chip_results()
        for n in names:
            r = {'value': None, 'unit': UNITS[n],
                 'error': 'device preflight failed (accelerator '
                          'runtime unreachable)'
                          + (f': {why}' if why else '')}
            s = stale.get(n) or {}
            if s.get('value') is not None:
                r['stale_value'] = s['value']
                r['stale_vs_baseline'] = s.get('vs_baseline')
                r['stale_from'] = s.get('measured_at')
                if s.get('note'):       # e.g. gptgen fallback shape —
                    r['stale_note'] = s['note']  # provenance must ride
            results[n] = r
        names = []
    for i, name in enumerate(names):
        if args.config == 'all':
            runner = _run_no_kill if name in NO_KILL else _run_isolated
            results[name] = runner(
                name, args.smoke,
                args.timeout * TIMEOUT_SCALE.get(name, 1))
            if not args.smoke:
                _record_chip_result(name, results[name])
            # partial artifact after EVERY config: a tunnel death (or
            # driver kill) mid-run keeps the finished configs' numbers
            _write_partial(results, smoke=args.smoke)
            err_s = str(results[name].get('error', ''))
            # 'exceeded' covers the no-kill orphan path — a compile
            # running past 2x budget is the strongest wedge signal
            if ('timeout' in err_s or 'exceeded' in err_s) and \
                    i + 1 < len(names):
                # a timed-out config usually means the tunnel wedged
                # mid-run: one quick probe decides between burning the
                # full timeout on every remaining config or failing
                # them fast with a diagnosable error
                probe_ok, probe_why = _device_preflight_once(90)
                if not probe_ok:
                    log('tunnel unresponsive after timeout; '
                        'fast-failing remaining configs')
                    for rest in names[i + 1:]:
                        results[rest] = {
                            'value': None, 'unit': UNITS[rest],
                            'error': 'accelerator runtime died '
                                     'mid-run (previous config '
                                     'timed out, preflight failed'
                                     + (f': {probe_why}' if probe_why
                                        else '') + ')'}
                    _write_partial(results, smoke=args.smoke)
                    break
        else:
            import jax
            log(f'device: {jax.devices()[0]}')
            results[name] = _run_one(name, args.smoke)
            if not args.smoke:
                _record_chip_result(name, results[name])

    # headline = resnet when it produced a number, else the first
    # config that did (a failed-resnet dict must not win selection)
    head_name = 'resnet' if (results.get('resnet') or {}).get('value') \
        else next((k for k, r in results.items() if r.get('value')),
                  'resnet')
    head = results.get(head_name, {})
    out = {
        'metric': METRIC_NAMES[head_name],
        'value': head.get('value'),
        'unit': head.get('unit', UNITS.get(head_name)),
        'vs_baseline': head.get('vs_baseline'),
        'extras': {k: v for k, v in results.items() if k != head_name},
    }
    if lint_summary is not None:
        out['lint'] = lint_summary
    if chaos_summary is not None:
        out['chaos'] = chaos_summary
    if plan_summary is not None:
        out['plan'] = plan_summary
    if cache_summary is not None:
        out['compile_cache'] = cache_summary
    if profile_summary is not None:
        out['profile'] = profile_summary
    if fused_summary is not None:
        out['fused'] = fused_summary
    if serve_summary is not None:
        out['serve'] = serve_summary
    if obs_summary is not None:
        out['obs'] = obs_summary
    if cluster_obs_summary is not None:
        out['cluster_obs'] = cluster_obs_summary
    if mem_summary is not None:
        out['mem'] = mem_summary
    if _preflight_memstats:
        # per-device HBM baseline captured by the passing preflight
        # probe (absent on CPU: no memory_stats there)
        out['device_mem'] = _preflight_memstats
    if quant_summary is not None:
        out['quant'] = quant_summary
    if supervisor_summary is not None:
        out['supervisor'] = supervisor_summary
    if frontdoor_summary is not None:
        out['frontdoor'] = frontdoor_summary
    if threads_summary is not None:
        out['threads'] = threads_summary
    if spmd_summary is not None:
        out['spmd'] = spmd_summary
    if preflight_attempts:
        # non-empty only when at least one preflight try failed: the
        # diagnosis (timeout vs crash, rc, stderr tail) per attempt
        out['device_preflight'] = {'attempts': preflight_attempts}
    # the headline config is excluded from extras, so its stale
    # provenance (if any) rides at the top level
    for k in ('stale_value', 'stale_vs_baseline', 'stale_from',
              'stale_note'):
        if k in head:
            out[k] = head[k]
    print(json.dumps(out))


if __name__ == '__main__':
    main()
