import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle


def mk(val, stop_gradient=False):
    t = paddle.to_tensor(val)
    t.stop_gradient = stop_gradient
    return t


class TestBackward:
    def test_simple_chain(self):
        x = mk([2.0, 3.0])
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])

    def test_matches_jax_grad(self):
        a = np.random.RandomState(0).randn(4, 3).astype('float32')
        w = np.random.RandomState(1).randn(3, 2).astype('float32')

        def f(aa, ww):
            return jnp.sum(jnp.tanh(aa @ ww))

        ga, gw = jax.grad(f, argnums=(0, 1))(a, w)

        ta, tw = mk(a), mk(w)
        loss = paddle.sum(paddle.tanh(paddle.matmul(ta, tw)))
        loss.backward()
        np.testing.assert_allclose(ta.grad.numpy(), np.asarray(ga), rtol=1e-5)
        np.testing.assert_allclose(tw.grad.numpy(), np.asarray(gw), rtol=1e-5)

    def test_grad_accumulation(self):
        x = mk([1.0, 2.0])
        y1 = (x * 2).sum()
        y1.backward()
        y2 = (x * 3).sum()
        y2.backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient(self):
        x = mk([1.0, 2.0])
        y = mk([3.0, 4.0], stop_gradient=True)
        loss = (x * y).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
        assert y.grad is None

    def test_detach(self):
        x = mk([2.0])
        d = x.detach()
        assert d.stop_gradient
        loss = (x * d).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_diamond_fanout(self):
        # x used twice: grads must accumulate through both paths
        x = mk([3.0])
        y = x * x + x * 2.0
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])  # 2x + 2

    def test_multi_output_op(self):
        x = mk([[3.0, 1.0, 2.0]])
        vals, idx = paddle.topk(x, 2)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])

    def test_no_grad_context(self):
        x = mk([1.0])
        with paddle.no_grad():
            y = x * 5
        assert y.grad_node is None and y.stop_gradient

    def test_deep_chain(self):
        x = mk(np.ones(4, np.float32))
        y = x
        for _ in range(60):
            y = y * 1.01
        loss = y.sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.full(4, 1.01 ** 60, np.float32),
                                   rtol=1e-4)

    def test_non_scalar_backward_with_grad(self):
        x = mk([1.0, 2.0])
        y = x * 3.0
        y.backward(paddle.to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])

    def test_getitem_grad(self):
        x = mk([[1.0, 2.0], [3.0, 4.0]])
        x[0].sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1, 1], [0, 0]])

    def test_broadcast_grad(self):
        x = mk(np.ones((3, 1), np.float32))
        y = mk(np.ones((1, 4), np.float32))
        (x + y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((3, 1), 4.0))
        np.testing.assert_allclose(y.grad.numpy(), np.full((1, 4), 3.0))

    def test_intermediate_grads_recorded(self):
        x = mk([2.0])
        h = x * 3.0
        loss = (h * h).sum()
        loss.backward()
        np.testing.assert_allclose(h.grad.numpy(), [12.0])
        np.testing.assert_allclose(x.grad.numpy(), [36.0])
