import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle


def mk(val, stop_gradient=False):
    t = paddle.to_tensor(val)
    t.stop_gradient = stop_gradient
    return t


class TestBackward:
    def test_simple_chain(self):
        x = mk([2.0, 3.0])
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])

    def test_matches_jax_grad(self):
        a = np.random.RandomState(0).randn(4, 3).astype('float32')
        w = np.random.RandomState(1).randn(3, 2).astype('float32')

        def f(aa, ww):
            return jnp.sum(jnp.tanh(aa @ ww))

        ga, gw = jax.grad(f, argnums=(0, 1))(a, w)

        ta, tw = mk(a), mk(w)
        loss = paddle.sum(paddle.tanh(paddle.matmul(ta, tw)))
        loss.backward()
        np.testing.assert_allclose(ta.grad.numpy(), np.asarray(ga), rtol=1e-5)
        np.testing.assert_allclose(tw.grad.numpy(), np.asarray(gw), rtol=1e-5)

    def test_grad_accumulation(self):
        x = mk([1.0, 2.0])
        y1 = (x * 2).sum()
        y1.backward()
        y2 = (x * 3).sum()
        y2.backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient(self):
        x = mk([1.0, 2.0])
        y = mk([3.0, 4.0], stop_gradient=True)
        loss = (x * y).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
        assert y.grad is None

    def test_detach(self):
        x = mk([2.0])
        d = x.detach()
        assert d.stop_gradient
        loss = (x * d).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_diamond_fanout(self):
        # x used twice: grads must accumulate through both paths
        x = mk([3.0])
        y = x * x + x * 2.0
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])  # 2x + 2

    def test_multi_output_op(self):
        x = mk([[3.0, 1.0, 2.0]])
        vals, idx = paddle.topk(x, 2)
        vals.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1.0, 0.0, 1.0]])

    def test_no_grad_context(self):
        x = mk([1.0])
        with paddle.no_grad():
            y = x * 5
        assert y.grad_node is None and y.stop_gradient

    def test_deep_chain(self):
        x = mk(np.ones(4, np.float32))
        y = x
        for _ in range(60):
            y = y * 1.01
        loss = y.sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   np.full(4, 1.01 ** 60, np.float32),
                                   rtol=1e-4)

    def test_non_scalar_backward_with_grad(self):
        x = mk([1.0, 2.0])
        y = x * 3.0
        y.backward(paddle.to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])

    def test_getitem_grad(self):
        x = mk([[1.0, 2.0], [3.0, 4.0]])
        x[0].sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [[1, 1], [0, 0]])

    def test_broadcast_grad(self):
        x = mk(np.ones((3, 1), np.float32))
        y = mk(np.ones((1, 4), np.float32))
        (x + y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((3, 1), 4.0))
        np.testing.assert_allclose(y.grad.numpy(), np.full((1, 4), 3.0))

    def test_intermediate_grads_recorded(self):
        x = mk([2.0])
        h = x * 3.0
        loss = (h * h).sum()
        loss.backward()
        np.testing.assert_allclose(h.grad.numpy(), [12.0])
        np.testing.assert_allclose(x.grad.numpy(), [36.0])


class TestGradAPI:
    """paddle.grad — partial derivatives without touching .grad
    (reference python/paddle/fluid/dygraph/base.py:407)."""

    def test_single_output(self):
        x = mk(2.0)
        y = x * x
        dx, = paddle.grad([y], [x])
        np.testing.assert_allclose(float(dx), 4.0)
        assert x.grad is None

    def test_multi_output_sum(self):
        x = mk(2.0)
        y1 = x * x
        y2 = x * 3.0
        dx, = paddle.grad([y1, y2], [x])
        np.testing.assert_allclose(float(dx), 7.0)

    def test_grad_outputs_seed(self):
        x = mk(2.0)
        y = x * x
        dx, = paddle.grad([y], [x], grad_outputs=[paddle.to_tensor(5.0)])
        np.testing.assert_allclose(float(dx), 20.0)

    def test_intermediate_input(self):
        x = mk(3.0)
        b = x * 2.0
        c = b * b
        db, = paddle.grad([c], [b], retain_graph=True)
        np.testing.assert_allclose(float(db), 12.0)  # 2b at b=6
        dx, = paddle.grad([c], [x])
        np.testing.assert_allclose(float(dx), 24.0)  # 8x at x=3

    def test_allow_unused(self):
        x = mk(2.0)
        z = mk(1.0)
        y = x * x
        with pytest.raises(RuntimeError):
            paddle.grad([y], [z], retain_graph=True)
        g = paddle.grad([y], [z], allow_unused=True)
        assert g[0] is None

    def test_no_grad_vars_cuts_flow(self):
        a = mk(3.0)
        b = a * 2.0
        c = b * a  # c = 2a^2; cutting b leaves only the direct edge: dc/da = b
        gc, = paddle.grad([c], [a], no_grad_vars=[b])
        np.testing.assert_allclose(float(gc), 6.0)

    def test_freed_graph_raises(self):
        x = mk(2.0)
        y = x * x
        paddle.grad([y], [x])
        with pytest.raises(RuntimeError, match='retain_graph'):
            paddle.grad([y], [x])

    def test_create_graph_unsupported(self):
        x = mk(2.0)
        y = x * x
        with pytest.raises(NotImplementedError):
            paddle.grad([y], [x], create_graph=True)

    def test_set_grad_enabled(self):
        x = mk(2.0)
        with paddle.set_grad_enabled(False):
            t = x * x
        assert t.grad_node is None
        with paddle.set_grad_enabled(True):
            t = x * x
        assert t.grad_node is not None


class TestRetainedGraphSeeds:
    """Seeds must be consumed per walk: a retained graph re-walked by
    backward() or grad() starts from fresh cotangents."""

    def test_grad_after_backward_no_double_count(self):
        x = mk(2.0)
        y = x * x
        y.backward(retain_graph=True)
        np.testing.assert_allclose(x.grad.numpy(), 4.0)
        dx, = paddle.grad([y], [x], retain_graph=True)
        np.testing.assert_allclose(float(dx), 4.0)  # not 8.0

    def test_repeated_backward_accumulates_linearly(self):
        x = mk(3.0)
        y = x * x
        y.backward(retain_graph=True)
        y.backward(retain_graph=True)
        np.testing.assert_allclose(x.grad.numpy(), 12.0)  # 6 + 6
